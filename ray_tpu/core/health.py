"""Health plane: the controller-side observe→act loop.

The five observability legs each end in a detector (PR 10's leak sweep
and store-pressure check, PR 11's error-spike check, the compile-storm
tracker riding device telemetry, PR 9's incident triggers). Before this
module they all terminated in an autopsy bundle; :class:`HealthEngine`
subscribes them to the actuator framework (util/actuators.py) so the
cluster can also CLOSE the loop — the Podracer-paper discipline of
feeding measurement back into control.

Detector → actuator wiring (each bounded + cooled + auditable, see the
README "Self-healing" table):

- ``memory_leak``     → :class:`LeakBackpressureActuator`: gc/ref-
  reclamation nudge to the worker processes holding the flagged
  call-site's objects (targeted owner backpressure).
- ``memory_pressure`` → :class:`PressureSpillActuator`: proactive spill
  of the pressured node's store down to ``health_spill_target_pct`` +
  a soft scheduler avoid (admission throttle) for ``health_throttle_s``.
- ``recompile_storm`` → :class:`StormPinActuator`: pin the storming
  function's shape buckets in the offending process's compile tracker
  (``compile_tracker.maybe_bucket`` then pads instead of re-lowering).
- ``error_spike``     → :class:`SpikeQuarantineActuator`: hard scheduler
  avoid (drain semantics: no new tasks/actors/PGs/leases) of the node
  the spiking signature attributes to, for ``health_quarantine_s``.

The engine runs entirely on the controller loop (observe() is called
from detector sites that already run there; tick() rides the telemetry
sweep), keeping the single-writer discipline. The fifth actuator —
podracer policy-lag → broadcast-cadence adaptation — is driver-local by
nature and lives in rllib/podracer/pipeline.py; its actions ship to this
controller's lifecycle ring over the ``task_events`` channel, so
``summarize_health()`` still shows one merged audit.
"""
from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, TYPE_CHECKING

from ray_tpu.util.actuators import (
    Actuator,
    ActuatorRegistry,
    HealthSignal,
    _get_metrics,
    parse_dry_run,
)
from ray_tpu.utils.ids import NodeID

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ray_tpu.core.controller import Controller

logger = logging.getLogger("ray_tpu.health")

# Bounded scan when attributing a leaked call-site to holder processes —
# the objects table can be envelope-sized and fire() runs on the loop.
_LEAK_SCAN_CAP = 200_000


class LeakBackpressureActuator(Actuator):
    """``memory_leak`` → gc/ref-reclamation nudge at the holders.

    The leak sweep flags a creation call-site whose open-ref count rises
    monotonically. The remediation is a targeted ``gc_nudge`` RPC to the
    (bounded set of) worker processes holding that site's objects: each
    runs ``gc.collect()`` + an immediate local-ref flush, which reclaims
    refs pinned only by reference cycles (the classic accidental-leak
    shape) and pushes the drop to the controller without waiting out the
    flush interval. Processes that don't shrink after the nudge are a
    REAL leak — the flag stays up and the incident autopsy has the
    call-site."""

    name = "leak_backpressure"
    triggers = ("memory_leak",)

    def __init__(self, ctrl: "Controller", **kw):
        super().__init__(**kw)
        self._ctrl = ctrl
        self.max_procs = int(
            getattr(ctrl.config, "health_nudge_max_procs", 8)
        )

    def fire(self, signal: HealthSignal):
        site = signal.key
        holders: set = set()
        for i, orec in enumerate(self._ctrl.objects.values()):
            if i >= _LEAK_SCAN_CAP:
                break
            if (orec.callsite or "(unknown)") != site:
                continue
            holders.update(orec.holders)
            if len(holders) >= self.max_procs * 4:
                break
        peers = []
        for w in self._ctrl.workers.values():
            if w.state == "DEAD" or w.peer.closed:
                continue
            if w.worker_id.hex() in holders:
                peers.append((w.worker_id.hex()[:12], w.peer))
            if len(peers) >= self.max_procs:
                break
        if not peers:
            return {"outcome": "skipped", "reason": "no_worker_holders",
                    "holders": len(holders)}

        async def nudge():
            import asyncio

            freed = {}
            for wid, peer in peers:
                try:
                    freed[wid] = await asyncio.wait_for(
                        peer.call("gc_nudge"), 5.0
                    )
                except Exception as e:  # noqa: BLE001 — a dead holder is fine
                    freed[wid] = {"error": str(e)}
            return {"outcome": "acted", "nudged": freed}

        return nudge()


class PressureSpillActuator(Actuator):
    """``memory_pressure`` → proactive spill + admission throttle.

    Instead of waiting for the allocation path to evict victim-by-victim
    under churn, spill the pressured node's store down to
    ``health_spill_target_pct`` in one pass, and soft-avoid the node in
    the scheduler for ``health_throttle_s`` so new placements prefer
    other nodes while the store drains."""

    name = "pressure_spill"
    triggers = ("memory_pressure",)

    def __init__(self, ctrl: "Controller", **kw):
        super().__init__(**kw)
        self._ctrl = ctrl

    def fire(self, signal: HealthSignal):
        cfg = self._ctrl.config
        frac = float(getattr(cfg, "health_spill_target_pct", 0.6))
        throttle_s = float(getattr(cfg, "health_throttle_s", 30.0))
        try:
            nid = NodeID.from_hex(signal.target or signal.key)
        except Exception:  # noqa: BLE001 — malformed target
            return {"outcome": "skipped", "reason": "bad_node"}
        node = self._ctrl.nodes.get(nid)
        if node is None:
            return {"outcome": "skipped", "reason": "node_gone"}
        throttled = False
        if throttle_s > 0 and len(self._ctrl.nodes) > 1:
            throttled = self._ctrl.cluster.set_avoid(
                nid, throttle_s, hard=False
            )
        if node.peer is None:  # the head's store is local
            res = self._ctrl.head_store.spill_to_fraction(frac)
            res.update(outcome="acted", throttled_s=throttle_s if throttled else 0)
            return res

        async def spill():
            import asyncio

            res = await asyncio.wait_for(
                node.peer.call("spill_store", frac), 10.0
            )
            out = dict(res or {})
            out.update(
                outcome="acted", throttled_s=throttle_s if throttled else 0
            )
            return out

        return spill()


class StormPinActuator(Actuator):
    """``recompile_storm`` → pin shape buckets in the offending process.

    The compile tracker in the storming worker knows the function and
    its churning shape strings; the remediation tells THAT process to
    pin the function (``pin_shapes`` RPC → ``compile_tracker.
    pin_functions``), after which workload code consulting
    ``compile_tracker.maybe_bucket(name, dim)`` gets power-of-two
    padded sizes — a bounded shape vocabulary instead of one compile per
    batch size."""

    name = "storm_pin"
    triggers = ("recompile_storm",)

    def __init__(self, ctrl: "Controller", **kw):
        super().__init__(**kw)
        self._ctrl = ctrl

    def fire(self, signal: HealthSignal):
        pid = signal.detail.get("pid")
        node_hex = signal.detail.get("node")
        fn = signal.detail.get("function") or signal.key
        target = None
        for w in self._ctrl.workers.values():
            if w.state == "DEAD" or w.peer.closed:
                continue
            if w.pid == pid and (
                not node_hex or w.node_id.hex() == node_hex
            ):
                target = w
                break
        if target is None:
            # Storms in drivers/controller processes have no worker peer
            # to reach — visible in compile_state(), not actuatable.
            return {"outcome": "skipped", "reason": "no_worker_peer",
                    "pid": pid}

        async def pin():
            import asyncio

            pinned = await asyncio.wait_for(
                target.peer.call("pin_shapes", [fn]), 5.0
            )
            return {"outcome": "acted", "pinned": pinned,
                    "worker": target.worker_id.hex()[:12]}

        return pin()


class SpikeQuarantineActuator(Actuator):
    """``error_spike`` → quarantine the node the spike attributes to.

    The error index links each signature to the lifecycle entity that
    first produced it; when one signature dominates a spike and resolves
    to a non-head node, hard-avoid that node for
    ``health_quarantine_s``: running work continues (and releases
    resources correctly), but no new tasks, actors, placement groups, or
    worker leases route there — the reference's drain semantics, applied
    automatically and bounded in time."""

    name = "spike_quarantine"
    triggers = ("error_spike",)

    def __init__(self, ctrl: "Controller", **kw):
        super().__init__(**kw)
        self._ctrl = ctrl

    def fire(self, signal: HealthSignal):
        cfg = self._ctrl.config
        quarantine_s = float(getattr(cfg, "health_quarantine_s", 60.0))
        node_hex = signal.target
        if not node_hex:
            return {"outcome": "skipped", "reason": "no_node_attribution"}
        nid = None
        for cand in self._ctrl.nodes:
            if cand.hex() == node_hex or cand.hex().startswith(node_hex):
                nid = cand
                break
        if nid is None:
            return {"outcome": "skipped", "reason": "node_gone"}
        node = self._ctrl.nodes.get(nid)
        if node is not None and node.peer is None:
            # Never quarantine the head: its "node" hosts the control
            # plane itself; losing placements there can deadlock small
            # clusters. The spike stays visible via incidents + index.
            return {"outcome": "skipped", "reason": "head_node"}
        if len(self._ctrl.nodes) < 2:
            return {"outcome": "skipped", "reason": "single_node"}
        ok = self._ctrl.cluster.set_avoid(nid, quarantine_s, hard=True)
        if not ok:
            return {"outcome": "skipped", "reason": "node_gone"}
        return {
            "outcome": "acted",
            "node": nid.hex()[:12],
            "quarantine_s": quarantine_s,
            "signature": signal.detail.get("signature", ""),
        }


class HealthEngine:
    """Controller-side health plane: registry + detector subscriptions.

    ``observe()`` is the single entry point detector sites call (always
    from the controller loop); ``tick()`` rides the telemetry sweep to
    scan telemetry-carried detectors (compile storms) and expire
    scheduler avoids."""

    def __init__(self, ctrl: "Controller"):
        self._ctrl = ctrl
        cfg = ctrl.config
        self.enabled = bool(getattr(cfg, "health_actuators", True))
        dry_spec = str(getattr(cfg, "health_dry_run", ""))
        cooldown = float(getattr(cfg, "health_action_cooldown_s", 30.0))
        self.registry = ActuatorRegistry(
            audit_ring=int(getattr(cfg, "health_audit_ring", 256)),
            max_actions_per_min=int(
                getattr(cfg, "health_max_actions_per_min", 6)
            ),
            recorder=ctrl.lifecycle.record,
        )
        for cls in (
            LeakBackpressureActuator,
            PressureSpillActuator,
            StormPinActuator,
            SpikeQuarantineActuator,
        ):
            self.registry.register(
                cls(
                    ctrl,
                    cooldown_s=cooldown,
                    dry_run=parse_dry_run(dry_spec, cls.name),
                )
            )
        # (proc_key, function) storms already acted on this activation —
        # a storm stays "active" for a whole window; without this the
        # tick would re-dispatch it every sweep just to hit cooldown.
        self._storms_seen: Dict[str, float] = {}

    # ------------------------------------------------------------------
    def observe(self, signal: HealthSignal) -> List[dict]:
        """Dispatch one detector signal. Cheap and exception-safe — the
        detector sites must never die because remediation did."""
        if not self.enabled:
            return []
        try:
            return self.registry.dispatch(signal)
        except Exception:  # noqa: BLE001 — must not break detectors
            logger.exception("health dispatch failed (%s)", signal.trigger)
            return []

    def tick(self):
        """Telemetry-sweep housekeeping: expire scheduler avoids, sync
        the avoid gauges, and scan device telemetry for compile storms
        (the one detector that lives in remote processes and arrives by
        snapshot rather than by callback)."""
        if not self.enabled:
            return
        cluster = self._ctrl.cluster
        cluster.prune_avoids()
        try:
            counts = {"hard": 0, "soft": 0}
            for _nid, (_deadline, hard) in cluster.avoids().items():
                counts["hard" if hard else "soft"] += 1
            g = _get_metrics()["avoids"]
            g.set(counts["hard"], {"mode": "hard"})
            g.set(counts["soft"], {"mode": "soft"})
        except Exception as e:  # noqa: BLE001 — metrics must not break tick
            logger.debug("avoid gauge failed: %s", e)
        now = time.time()
        window = float(getattr(self._ctrl.config, "compile_storm_window_s", 60.0))
        for k in [
            k for k, ts in self._storms_seen.items() if now - ts > 2 * window
        ]:
            self._storms_seen.pop(k, None)
        for proc_key, payload in self._ctrl._live_device_state().items():
            comp = payload.get("compile") or {}
            for fn in (comp.get("active_storms") or {}):
                skey = f"{proc_key}:{fn}"
                if skey in self._storms_seen:
                    continue
                self._storms_seen[skey] = now
                self.observe(
                    HealthSignal(
                        "recompile_storm",
                        key=skey,
                        target=proc_key,
                        detail={
                            "function": fn,
                            "pid": payload.get("pid"),
                            "node": payload.get("node_id"),
                        },
                    )
                )

    # ------------------------------------------------------------------
    def snapshot(self, limit: int = 50) -> dict:
        """The ``summarize_health()`` body: actuator configs + outcomes,
        the recent-action audit (controller actuators AND driver-side
        ones whose action events arrived over task_events), and the live
        scheduler avoid set."""
        out = {
            "enabled": self.enabled,
            **self.registry.snapshot(limit=limit),
        }
        now = time.monotonic()
        avoids = {}
        for nid, (deadline, hard) in self._ctrl.cluster.avoids().items():
            avoids[nid.hex()[:12]] = {
                "mode": "quarantine" if hard else "throttle",
                "remaining_s": round(max(0.0, deadline - now), 2),
            }
        out["avoids"] = avoids
        # Driver-side actuators (podracer cadence) audit through the
        # lifecycle ring only — merge their action events so the health
        # summary is the one place to read the whole self-healing story.
        remote = [
            ev
            for ev in self._ctrl.lifecycle.tail(2000)
            if ev.get("kind") == "action" and ev.get("remote")
        ]
        if remote:
            out["remote_actions"] = remote[-limit:]
        return out


def disabled_snapshot() -> dict:
    return {"enabled": False, "actuators": [], "actions_recent": [],
            "signals": {}, "outcomes": {}, "avoids": {}}
