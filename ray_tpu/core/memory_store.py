"""Owner-local in-process memory store for small task results.

Reference: src/ray/core_worker/store_provider/memory_store/memory_store.cc
(CoreWorkerMemoryStore) — small/inlined objects live in the OWNER process,
not the control plane, so a ``get`` of a direct-call result is a local
dictionary lookup with zero controller round-trips.

Entries hold either a ready value (serialized bytes + is_error) or are
pending until a direct call resolves them. Futures are created LAZILY —
only when a reader actually blocks — because a threading.Condition per
call is measurable on the hot path. Objects stay *local-only* until their
ref escapes the process (task arg, put, return value), at which point
CoreWorker promotes them to the controller's global directory —
the reference's equivalent is resolving the owner address from the ref;
promotion-on-escape keeps single-process hot paths entirely local.
"""
from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

_UNSET = object()


class Entry:
    __slots__ = (
        "_lock", "_value", "_future", "promoted", "doomed",
        "promote_on_resolve", "kind",
    )

    def __init__(self, lock):
        self._lock = lock  # the store's lock (shared)
        # (payload, is_error); ``payload`` is serialized bytes, or an
        # Exception instance for transport-level failures (ActorDiedError
        # etc.), or None when kind == "shm" (the value lives in the
        # global store; readers fall back to the controller).
        self._value = _UNSET
        self._future: Optional[Future] = None
        self.promoted = False  # registered with the controller directory
        self.doomed = False  # all local refs dropped while still pending
        # A ref escaped while the call was in flight: publish to the
        # controller as soon as the reply resolves the entry.
        self.promote_on_resolve = False
        self.kind = "inline"  # inline | shm

    @property
    def ready(self) -> bool:
        return self._value is not _UNSET

    def ensure_future(self) -> Future:
        """A Future resolving to (payload, is_error) — created on demand."""
        with self._lock:
            if self._future is None:
                self._future = Future()
                if self._value is not _UNSET:
                    self._future.set_result(self._value)
            return self._future

    def value(self, timeout: Optional[float] = None) -> Tuple[object, bool]:
        v = self._value
        if v is not _UNSET:
            return v
        return self.ensure_future().result(timeout)

    def _resolve(self, value):  # store lock held by caller
        if self._value is _UNSET:
            self._value = value
            if self._future is not None and not self._future.done():
                self._future.set_result(value)


class LocalMemoryStore:
    """Thread-safe oid→Entry table (gets come from arbitrary threads; the
    RPC loop resolves entries)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[bytes, Entry] = {}

    def register_pending(self, keys: List[bytes]):
        with self._lock:
            for k in keys:
                if k not in self._entries:
                    self._entries[k] = Entry(self._lock)

    def lookup(self, key: bytes) -> Optional[Entry]:
        return self._entries.get(key)

    def put(self, key: bytes, payload, is_error: bool, kind: str = "inline"):
        """Resolve (or create) an entry. Returns (doomed, want_promote):
        doomed = every local ref was dropped while pending (the entry is
        discarded; if the object got registered globally the caller must
        report the drop so the controller can GC it); want_promote = a
        ref escaped while pending (the caller must publish the value)."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = self._entries[key] = Entry(self._lock)
            doomed = e.doomed
            want_promote = e.promote_on_resolve and not e.promoted
            e.promote_on_resolve = False
            e.kind = kind
            e._resolve((payload, is_error))
            if doomed:
                del self._entries[key]
        return doomed, want_promote

    def request_promotion(self, key: bytes) -> str:
        """'done' (already global), 'ready' (caller promotes now),
        'deferred' (pending — promotion happens at resolve), 'gone'."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return "gone"
            if e.promoted or (e.ready and e.kind == "shm"):
                return "done"
            if e.ready:
                return "ready"
            e.promote_on_resolve = True
            return "deferred"

    def mark_promoted(self, key: bytes):
        e = self._entries.get(key)
        if e is not None:
            e.promoted = True

    def evict(self, key: bytes) -> bool:
        """Drop on last-local-ref release. A still-pending entry is only
        marked doomed — the in-flight reply resolves (then discards) it so
        a racing ``get`` never hangs on a deleted entry."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return False
            if not e.ready:
                e.doomed = True
                return False
            del self._entries[key]
            return True

    def stats(self) -> dict:
        """Census snapshot of the owner-local store: entry counts by
        state/kind and resident payload bytes (ready inline entries only
        — shm-kind entries hold no payload here, spilled/pending none).
        O(entries) under the store lock; entry counts are bounded by the
        process's live refs, so this stays cheap."""
        entries = ready_bytes = pending = shm = errors = 0
        with self._lock:
            for e in self._entries.values():
                entries += 1
                if not e.ready:
                    pending += 1
                    continue
                if e.kind == "shm":
                    shm += 1
                    continue
                payload, is_err = e._value
                if is_err:
                    errors += 1
                if isinstance(payload, (bytes, bytearray, memoryview)):
                    ready_bytes += len(payload)
        return {
            "entries": entries,
            "ready_bytes": ready_bytes,
            "pending": pending,
            "shm": shm,
            "errors": errors,
        }

    def is_local_only(self, key: bytes) -> bool:
        """True for entries that exist here and were never promoted to the
        controller (ref flushes for these stay local)."""
        e = self._entries.get(key)
        return e is not None and not e.promoted and e.kind == "inline"

    def __len__(self):
        return len(self._entries)
