"""Self-contained dashboard page (reference: python/ray/dashboard/ —
the reference ships a 29 kLoC React client; this rebuild serves ONE
dependency-free HTML page from the controller's HTTP gateway that polls
the same state API the React app would (/api/v0/*, /api/jobs) and
renders cluster resources, nodes, actors, tasks, placement groups, jobs
and the event tail with a 2 s refresh)."""

DASHBOARD_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  :root { color-scheme: light dark; }
  body { font: 13px/1.45 system-ui, sans-serif; margin: 0; padding: 1rem 1.4rem;
         max-width: 1200px; }
  h1 { font-size: 1.15rem; margin: 0 0 .2rem; }
  h2 { font-size: .95rem; margin: 1.2rem 0 .4rem; border-bottom: 1px solid
       color-mix(in srgb, currentColor 25%, transparent); padding-bottom: .2rem; }
  small { opacity: .65 }
  table { border-collapse: collapse; width: 100%; }
  th, td { text-align: left; padding: .18rem .6rem .18rem 0; vertical-align: top;
           border-bottom: 1px solid color-mix(in srgb, currentColor 12%, transparent); }
  th { font-weight: 600; opacity: .75 }
  .num { text-align: right; font-variant-numeric: tabular-nums; }
  .ok { color: #188038 } .bad { color: #c5221f } .warn { color: #b06000 }
  .bar { display: inline-block; height: .6rem; background: #1a73e8;
         border-radius: 2px; vertical-align: middle; }
  .pill { display: inline-block; padding: 0 .45rem; border-radius: 999px;
          background: color-mix(in srgb, currentColor 12%, transparent);
          font-size: .78rem; }
  #err { color: #c5221f; min-height: 1em; }
  code { font-size: .85em }
</style>
</head>
<body>
<h1>ray_tpu <small id="ts"></small></h1>
<div id="err"></div>
<h2>Resources</h2><div id="resources"></div>
<h2>Nodes</h2><div id="nodes"></div>
<h2>Telemetry <small>(host / HBM / compiles / collective skew)</small></h2>
<div id="telemetry"></div>
<h2>Actors</h2><div id="actors"></div>
<h2>Tasks <small>(most recent)</small></h2><div id="tasks"></div>
<h2>Placement groups</h2><div id="pgs"></div>
<h2>Jobs</h2><div id="jobs"></div>
<h2>Events <small>(tail)</small></h2><div id="events"></div>
<script>
const get = (p) => fetch(p).then(r => {
  if (!r.ok) throw new Error(p + " -> " + r.status);
  return r.json();
});
const esc = (s) => String(s ?? "").replace(/[&<>]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;"}[c]));
function table(rows, cols) {
  if (!rows || !rows.length) return "<small>none</small>";
  let h = "<table><tr>" + cols.map(c => `<th>${c[0]}</th>`).join("") + "</tr>";
  for (const r of rows)
    h += "<tr>" + cols.map(c => `<td class="${c[2]||""}">${c[1](r)}</td>`).join("") + "</tr>";
  return h + "</table>";
}
function stateCls(s) {
  if (["ALIVE","FINISHED","RUNNING","SUCCEEDED","CREATED"].includes(s)) return "ok";
  if (["DEAD","FAILED","CREATION_FAILED","STOPPED"].includes(s)) return "bad";
  return "warn";
}
const pill = (s) => `<span class="pill ${stateCls(s)}">${esc(s)}</span>`;
async function refresh() {
  try {
    const [total, avail, nodes, actors, tasks, pgs, events] = await Promise.all([
      get("/api/v0/cluster_resources"), get("/api/v0/available_resources"),
      get("/api/v0/nodes"), get("/api/v0/actors"), get("/api/v0/tasks?limit=60"),
      get("/api/v0/placement_groups"), get("/api/v0/events?limit=50"),
    ]);
    let jobs = [];
    try { jobs = await get("/api/jobs"); } catch (e) {}
    let summary = null;
    try { summary = await get("/api/v0/summarize_resources"); } catch (e) {}
    document.getElementById("ts").textContent = new Date().toLocaleTimeString();
    document.getElementById("err").textContent = "";
    let res = "<table>";
    for (const k of Object.keys(total).sort()) {
      const t = total[k], a = avail[k] ?? 0, used = t - a;
      const pct = t > 0 ? Math.round(100 * used / t) : 0;
      res += `<tr><td>${esc(k)}</td><td class="num">${used.toFixed(2)} / ${t}</td>
        <td style="width:40%"><span class="bar" style="width:${pct}%"></span>
        <small> ${pct}%</small></td></tr>`;
    }
    document.getElementById("resources").innerHTML = res + "</table>";
    document.getElementById("nodes").innerHTML = table(nodes, [
      ["node", r => `<code>${esc(r.node_id.slice(0,10))}</code>` +
                    (r.is_head ? ' <span class="pill">head</span>' : "")],
      ["state", r => pill(r.state)],
      ["host", r => esc(r.hostname)],
      ["workers", r => r.num_workers, "num"],
      ["cpu avail/total", r => {
        const res2 = r.resources || {};
        const t = (res2.total||{}).CPU ?? "-", a = (res2.available||{}).CPU ?? "-";
        return `${a} / ${t}`; }, "num"],
    ]);
    const gb = (n) => ((n || 0) / (1 << 30)).toFixed(1);
    if (summary && summary.nodes) {
      const rows = Object.entries(summary.nodes).map(([id, n]) => ({id, ...n}));
      let h = table(rows, [
        ["node", r => `<code>${esc(r.id.slice(0,10))}</code>` +
                      (r.is_head ? ' <span class="pill">head</span>' : "")],
        ["cpu%", r => ((r.host||{}).cpu_percent ?? 0).toFixed(1), "num"],
        ["mem GB", r => `${gb((r.host||{}).mem_used_bytes)} / ${gb((r.host||{}).mem_total_bytes)}`, "num"],
        ["store GB", r => `${gb((r.object_store||{}).used)} / ${gb((r.object_store||{}).capacity)}`, "num"],
        ["HBM used/limit GB", r => (r.devices||[]).map(d => {
            const pct = d.bytes_limit ? Math.round(100*d.bytes_in_use/d.bytes_limit) : 0;
            return `${d.id}: ${gb(d.bytes_in_use)}/${gb(d.bytes_limit)}` +
                   ` <span class="bar" style="width:${Math.min(pct,100)/3}px"></span>`;
          }).join("<br>") || "<small>no device reports</small>"],
        ["compiles/min", r => ((r.compile||{}).compiles_per_min ?? 0).toFixed(1), "num"],
        ["storms", r => ((r.compile||{}).active_storms||[]).map(s =>
            `<span class="pill bad">${esc(s)}</span>`).join(" ")],
      ]);
      const skew = (summary.totals||{}).collective_skew_ms || [];
      if (skew.length) {
        h += "<p><b>top-skew collectives</b></p>" + table(skew.slice(0,8), [
          ["group", r => esc(r.group)], ["op", r => esc(r.op)],
          ["skew ms", r => r.skew_ms, "num"], ["max ms", r => r.max_ms, "num"],
          ["min ms", r => r.min_ms, "num"],
          ["slowest rank", r => esc(r.slowest_rank), "num"],
        ]);
      }
      document.getElementById("telemetry").innerHTML = h;
    }
    document.getElementById("actors").innerHTML = table(actors, [
      ["actor", r => `<code>${esc(r.actor_id.slice(0,10))}</code>`],
      ["name", r => esc(r.name || "")],
      ["state", r => pill(r.state)],
      ["restarts", r => r.num_restarts, "num"],
      ["node", r => r.node_id ? `<code>${esc(r.node_id.slice(0,10))}</code>` : ""],
    ]);
    document.getElementById("tasks").innerHTML = table(tasks.slice(-40).reverse(), [
      ["task", r => `<code>${esc(r.task_id.slice(0,10))}</code>`],
      ["name", r => esc(r.name)],
      ["type", r => esc(r.type)],
      ["state", r => pill(r.state)],
    ]);
    const pgRows = Array.isArray(pgs) ? pgs : Object.values(pgs || {});
    document.getElementById("pgs").innerHTML = table(pgRows, [
      ["pg", r => `<code>${esc((r.placement_group_id || r.id || "").slice(0,10))}</code>`],
      ["name", r => esc(r.name || "")],
      ["state", r => pill(r.state || "")],
      ["bundles", r => esc(JSON.stringify(r.bundles || []))],
    ]);
    document.getElementById("jobs").innerHTML = table(
      Array.isArray(jobs) ? jobs : Object.values(jobs || {}), [
      ["job", r => `<code>${esc(r.submission_id || r.job_id || "")}</code>`],
      ["status", r => pill(r.status || "")],
      ["entrypoint", r => `<code>${esc((r.entrypoint || "").slice(0, 80))}</code>`],
    ]);
    document.getElementById("events").innerHTML = table(events.slice(-15).reverse(), [
      ["time", r => new Date(r.ts * 1000).toLocaleTimeString()],
      ["kind", r => esc(r.kind)],
      ["name", r => esc(r.name)],
      ["state", r => pill(r.state)],
    ]);
  } catch (e) {
    document.getElementById("err").textContent = "refresh failed: " + e;
  }
}
// re-arm only after each refresh completes: overlapping polls on a
// slow backend would interleave stale DOM writes
(async function loop() {
  await refresh();
  setTimeout(loop, 2000);
})();
</script>
</body>
</html>
"""


def render_profiles_page(rows) -> str:
    """The /profiles page: captured jax.profiler traces (reference: the
    dashboard's profiling surface — py-spy flamegraphs in the reporter
    module; here the TPU-native equivalent lists jax.profiler captures,
    openable with TensorBoard/XProf or `ray-tpu profile <id>`)."""
    import html as _html

    def td(v):
        return f"<td>{_html.escape(str(v))}</td>"

    body = "".join(
        "<tr>"
        + td(r.get("id", ""))
        + td(r.get("name", ""))
        + td(r.get("task_id", ""))
        + td(r.get("captured_at", ""))
        + td(r.get("duration_s", ""))
        + td(r.get("path", ""))
        + "</tr>"
        for r in rows
    )
    return f"""<!doctype html>
<html><head><title>ray_tpu profiles</title>
<style>
body {{ font-family: system-ui, sans-serif; margin: 2rem; }}
table {{ border-collapse: collapse; width: 100%; }}
td, th {{ border: 1px solid #ddd; padding: 6px 10px; font-size: 13px; }}
th {{ background: #f5f5f5; text-align: left; }}
</style></head>
<body>
<h2>jax.profiler captures ({len(rows)})</h2>
<p>Fetch with <code>ray-tpu profile &lt;id&gt;</code>; open trace dirs with
TensorBoard / XProf. JSON at <a href="/api/profiles">/api/profiles</a>;
Grafana dashboard JSON at
<a href="/api/grafana/dashboard">/api/grafana/dashboard</a>.</p>
<table><tr><th>id</th><th>name</th><th>task</th><th>captured</th>
<th>duration (s)</th><th>path</th></tr>{body}</table>
</body></html>"""
