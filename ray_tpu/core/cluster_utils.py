"""Test-cluster utilities: multi-node clusters on one host.

Reference: python/ray/cluster_utils.py:135 ``Cluster`` / ``add_node`` :201 /
``remove_node`` :279 — the reference's workhorse for multi-node tests spawns
extra raylets with fake resources on localhost; we spawn extra node agents.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ray_tpu.core import api
from ray_tpu.core.client import CoreWorker
from ray_tpu.utils import rpc


class NodeHandle:
    def __init__(self, proc: subprocess.Popen, node_id_hex: str):
        self.proc = proc
        self.node_id_hex = node_id_hex

    @property
    def node_id(self) -> str:
        return self.node_id_hex


class Cluster:
    def __init__(
        self,
        head_resources: Optional[Dict[str, float]] = None,
        system_config: Optional[Dict] = None,
    ):
        head_resources = dict(head_resources or {"CPU": 2})
        self.address, self._proc, self._session_dir = api._start_controller(
            head_resources, system_config or {}, owned=False
        )
        self._admin_runner = rpc.EventLoopThread("cluster-admin")
        self._admin = CoreWorker(self.address, mode="driver", loop_runner=self._admin_runner)
        self._nodes: List[NodeHandle] = []

    def _list_node_ids(self) -> set:
        return {n["node_id"] for n in self._admin.list_state("nodes") if n["state"] == "ALIVE"}

    def add_node(
        self,
        num_cpus: int = 1,
        resources: Optional[Dict[str, float]] = None,
        wait: bool = True,
        labels: Optional[Dict[str, str]] = None,
    ) -> NodeHandle:
        res = dict(resources or {})
        res.setdefault("CPU", num_cpus)
        from ray_tpu.core.node_agent import child_env

        before = self._list_node_ids()
        env = child_env(needs_tpu=False)
        if labels:
            env["RAY_TPU_NODE_LABELS"] = json.dumps(labels)
        log = open(os.path.join(self._session_dir, "logs", f"agent-{len(self._nodes)}.log"), "ab")
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu.core.node_agent",
                "--controller",
                self.address,
                "--session-dir",
                self._session_dir,
                "--resources",
                json.dumps(res),
            ],
            env=env,
            stdout=log,
            stderr=subprocess.STDOUT,
        )
        node_id_hex = ""
        if wait:
            deadline = time.time() + 30
            while time.time() < deadline:
                new = self._list_node_ids() - before
                if new:
                    node_id_hex = next(iter(new))
                    break
                time.sleep(0.02)
            else:
                raise TimeoutError("node agent did not register")
        handle = NodeHandle(proc, node_id_hex)
        self._nodes.append(handle)
        return handle

    def remove_node(self, handle: NodeHandle, graceful: bool = False):
        """Kill a node (SIGKILL by default — simulates node failure,
        reference: cluster_utils.py:279)."""
        handle.proc.send_signal(signal.SIGTERM if graceful else signal.SIGKILL)
        deadline = time.time() + 30
        while time.time() < deadline:
            if handle.node_id_hex not in self._list_node_ids():
                return
            time.sleep(0.02)
        raise TimeoutError("node did not deregister")

    def wait_for_nodes(self, count: int, timeout: float = 30):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self._list_node_ids()) >= count:
                return
            time.sleep(0.02)
        raise TimeoutError(f"cluster did not reach {count} nodes")

    def connect(self):
        return api.init(address=self.address)

    def shutdown(self):
        try:
            if api.is_initialized():
                api.shutdown()
        except Exception:
            pass
        try:
            # Deliberate teardown — don't ride the reconnect window.
            self._admin._reconnect_dead = True
            self._admin._call("shutdown_cluster", timeout=5)
        except Exception:
            pass
        self._admin.disconnect()
        self._admin_runner.stop()
        for h in self._nodes:
            try:
                h.proc.kill()
            except Exception:
                pass
        try:
            self._proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self._proc.kill()
