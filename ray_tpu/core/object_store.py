"""Object storage.

Two tiers, mirroring the reference's split (reference:
src/ray/core_worker/store_provider/memory_store/memory_store.cc for small
objects, src/ray/object_manager/plasma/store.cc for the shared-memory
store):

- *Inline tier*: objects at or below ``max_inline_object_size`` travel by
  value through the control plane and live in the controller's memory store.
- *Shared-memory tier* (``PlasmaStore``): large objects are written to
  mmap-able files under ``/dev/shm`` by the creating process and mapped
  read-only (zero-copy) by readers on the same host. Eviction spills sealed
  objects to a disk directory and restores them on access (reference:
  src/ray/raylet/local_object_manager.cc spilling + restore;
  python/ray/_private/external_storage.py).

The plasma arena itself is intentionally file-per-object on tmpfs rather
than a dlmalloc arena: on TPU hosts the kernel's tmpfs already provides the
shared mapping + lazy page allocation the reference built dlmalloc-over-mmap
for (reference: object_manager/plasma/dlmalloc.cc). A C++ slab allocator can
replace this behind the same interface if file-per-object overhead shows up.
"""
from __future__ import annotations

import mmap
import os
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from ray_tpu.utils.ids import ObjectID


@dataclass
class PlasmaEntry:
    size: int
    sealed: bool = False
    pinned: int = 0
    last_access: float = field(default_factory=time.monotonic)
    spilled: bool = False


class PlasmaBuffer:
    """A writable or readable mmap view of a stored object."""

    def __init__(self, path: str, size: int, writable: bool):
        flags = os.O_RDWR | (os.O_CREAT if writable else 0)
        self._fd = os.open(path, flags, 0o600)
        if writable:
            os.ftruncate(self._fd, size)
        self._mm = mmap.mmap(
            self._fd, size, access=mmap.ACCESS_WRITE if writable else mmap.ACCESS_READ
        )
        self.size = size

    def view(self) -> memoryview:
        return memoryview(self._mm)

    def close(self):
        try:
            self._mm.close()
        finally:
            os.close(self._fd)


class PlasmaStore:
    """Per-node shared-memory object store.

    Thread-safe; used directly by every process on the node (the creating
    process writes, readers map read-only). Capacity accounting and
    spill/evict decisions live here in the node agent's instance; worker
    processes use lightweight :class:`PlasmaClient` views.
    """

    def __init__(self, session_dir: str, capacity: int, spill_dir: Optional[str] = None, name: str = "head"):
        self.shm_dir = os.path.join(
            "/dev/shm", "ray_tpu", f"{os.path.basename(session_dir)}_{name}"
        )
        os.makedirs(self.shm_dir, exist_ok=True)
        self.spill_dir = spill_dir or os.path.join(session_dir, f"spilled_objects_{name}")
        os.makedirs(self.spill_dir, exist_ok=True)
        self.capacity = capacity
        self.used = 0
        self._entries: Dict[ObjectID, PlasmaEntry] = {}
        self._lock = threading.Lock()

    # -- paths -------------------------------------------------------------
    def _shm_path(self, oid: ObjectID) -> str:
        return os.path.join(self.shm_dir, oid.hex())

    def _spill_path(self, oid: ObjectID) -> str:
        return os.path.join(self.spill_dir, oid.hex())

    # -- write path --------------------------------------------------------
    def create(self, oid: ObjectID, size: int) -> PlasmaBuffer:
        with self._lock:
            if oid in self._entries:
                raise FileExistsError(f"object {oid.hex()} already exists")
            self._maybe_evict(size)
            self._entries[oid] = PlasmaEntry(size=size)
            self.used += size
        return PlasmaBuffer(self._shm_path(oid), size, writable=True)

    def seal(self, oid: ObjectID):
        with self._lock:
            e = self._entries.get(oid)
            if e is not None:
                e.sealed = True

    def put_bytes(self, oid: ObjectID, data: bytes | memoryview) -> int:
        buf = self.create(oid, len(data))
        buf.view()[:] = data
        buf.close()
        self.seal(oid)
        return len(data)

    def adopt(self, oid: ObjectID, size: int):
        """Account for an object another process wrote directly into the shm
        dir (workers write via PlasmaClient; the store owner is told after —
        the reference's seal notification, plasma/store.cc SealObjects)."""
        with self._lock:
            if oid in self._entries:
                return
            self._maybe_evict(size)
            self._entries[oid] = PlasmaEntry(size=size, sealed=True)
            self.used += size

    def ensure_local(self, oid: ObjectID) -> bool:
        """Restore a spilled object into shm; True if readable there."""
        with self._lock:
            e = self._entries.get(oid)
            if e is None or not e.sealed:
                return os.path.exists(self._shm_path(oid))
            if e.spilled:
                self._restore_locked(oid, e)
            e.last_access = time.monotonic()
            return True

    # -- read path ---------------------------------------------------------
    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._entries

    def get(self, oid: ObjectID) -> Optional[PlasmaBuffer]:
        with self._lock:
            e = self._entries.get(oid)
            if e is None or not e.sealed:
                return None
            e.last_access = time.monotonic()
            if e.spilled:
                self._restore_locked(oid, e)
        return PlasmaBuffer(self._shm_path(oid), e.size, writable=False)

    def size_of(self, oid: ObjectID) -> Optional[int]:
        with self._lock:
            e = self._entries.get(oid)
            return e.size if e else None

    # -- lifecycle ---------------------------------------------------------
    def pin(self, oid: ObjectID):
        with self._lock:
            e = self._entries.get(oid)
            if e:
                e.pinned += 1

    def unpin(self, oid: ObjectID):
        with self._lock:
            e = self._entries.get(oid)
            if e and e.pinned > 0:
                e.pinned -= 1

    def delete(self, oid: ObjectID):
        with self._lock:
            e = self._entries.pop(oid, None)
            if e is None:
                return
            if not e.spilled:
                self.used -= e.size
            for p in (self._shm_path(oid), self._spill_path(oid)):
                try:
                    os.unlink(p)
                except FileNotFoundError:
                    pass

    # -- eviction / spilling ----------------------------------------------
    def _maybe_evict(self, incoming: int):
        """Spill LRU sealed, unpinned objects until ``incoming`` fits."""
        if self.capacity <= 0 or self.used + incoming <= self.capacity:
            return
        victims = sorted(
            (
                (e.last_access, oid, e)
                for oid, e in self._entries.items()
                if e.sealed and e.pinned == 0 and not e.spilled
            ),
        )
        for _, oid, e in victims:
            if self.used + incoming <= self.capacity:
                break
            shutil.move(self._shm_path(oid), self._spill_path(oid))
            e.spilled = True
            self.used -= e.size

    def _restore_locked(self, oid: ObjectID, e: PlasmaEntry):
        self._maybe_evict(e.size)
        shutil.move(self._spill_path(oid), self._shm_path(oid))
        e.spilled = False
        self.used += e.size

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "used": self.used,
                "num_objects": len(self._entries),
                "num_spilled": sum(1 for e in self._entries.values() if e.spilled),
            }

    def destroy(self):
        shutil.rmtree(self.shm_dir, ignore_errors=True)
        shutil.rmtree(self.spill_dir, ignore_errors=True)


class PlasmaClient:
    """Worker-side view: maps objects created by any process on this node."""

    def __init__(self, shm_dir: str):
        self.shm_dir = shm_dir

    def _path(self, oid: ObjectID) -> str:
        return os.path.join(self.shm_dir, oid.hex())

    def put_bytes(self, oid: ObjectID, data: bytes | memoryview) -> int:
        # Writes directly into the node's shm dir; the node agent is told of
        # the new object afterwards (seal notification) and does accounting.
        path = self._path(oid)
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            os.ftruncate(fd, len(data))
            with mmap.mmap(fd, len(data), access=mmap.ACCESS_WRITE) as mm:
                mm[: len(data)] = data
        finally:
            os.close(fd)
        return len(data)

    def get_buffer(self, oid: ObjectID, size: int) -> PlasmaBuffer:
        return PlasmaBuffer(self._path(oid), size, writable=False)
