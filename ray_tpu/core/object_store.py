"""Object storage.

Two tiers, mirroring the reference's split (reference:
src/ray/core_worker/store_provider/memory_store/memory_store.cc for small
objects, src/ray/object_manager/plasma/store.cc for the shared-memory
store):

- *Inline tier*: objects at or below ``max_inline_object_size`` travel by
  value through the control plane and live in the controller's memory store.
- *Shared-memory tier* (``PlasmaStore``): large objects land in the node's
  native C++ **arena** — one mmap'd file on /dev/shm with a boundary-tag
  allocator and process-shared object table (ray_tpu/native/src/arena.cc;
  reference: object_manager/plasma/store.cc + plasma_allocator.cc +
  dlmalloc.cc). Every process on the node maps the same arena, so reads
  are zero-copy with no per-object file opens. Objects that don't fit the
  arena (or when the native toolchain is unavailable) fall back to
  file-per-object on tmpfs behind the same interface.

Eviction spills sealed objects to a disk directory and restores them on
access (reference: src/ray/raylet/local_object_manager.cc spilling;
python/ray/_private/external_storage.py).
"""
from __future__ import annotations

import logging
import mmap
import os
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from ray_tpu.util.guards import GuardedDict, GuardedSet, guarded_by
from ray_tpu.utils.ids import ObjectID

logger = logging.getLogger("ray_tpu.object_store")

_ARENA_DISABLED = os.environ.get("RAY_TPU_DISABLE_NATIVE_ARENA") == "1"

# Live zero-copy pin registry for THIS process (reference: the plasma
# client tracks its own in-use buffers — client.cc objects_in_use_).
# view_pinned registers arena pins here so the memory census
# (core/memory_census.py rpc_dump_memory) can attribute "who pins the
# store" per process; release() unregisters. Keyed oid bytes -> [refs,
# bytes] (one object may be pinned by several concurrent readers).
_pins_lock = threading.Lock()
_live_pins: Dict[bytes, list] = {}


def _pin_register(key: bytes, size: int):
    with _pins_lock:
        row = _live_pins.get(key)
        if row is None:
            _live_pins[key] = [1, size]
        else:
            row[0] += 1


def _pin_unregister(key: bytes):
    with _pins_lock:
        row = _live_pins.get(key)
        if row is not None:
            row[0] -= 1
            if row[0] <= 0:
                del _live_pins[key]


def live_pin_stats() -> dict:
    """This process's live pinned arena views: {count, bytes, objects}.
    The display list caps at 256 ids (``objects_truncated`` set when it
    did); per-object membership checks must use :func:`live_pin_keys`."""
    with _pins_lock:
        return {
            "count": sum(r[0] for r in _live_pins.values()),
            "bytes": sum(r[1] for r in _live_pins.values()),
            "objects": [k.hex() for k in list(_live_pins)[:256]],
            "objects_truncated": len(_live_pins) > 256,
        }


def live_pin_keys() -> set:
    """Full hex-id set of this process's live pins (uncapped — the
    census's per-object attribution source)."""
    with _pins_lock:
        return {k.hex() for k in _live_pins}


def _try_arena():
    if _ARENA_DISABLED:
        return None
    try:
        from ray_tpu.native import arena as arena_mod

        return arena_mod if arena_mod.available() else None
    except Exception as e:  # pragma: no cover - toolchain missing
        logger.warning("native arena unavailable, using file-per-object: %s", e)
        return None


@dataclass
class PlasmaEntry:
    size: int
    sealed: bool = False
    pinned: int = 0
    last_access: float = field(default_factory=time.monotonic)
    spilled: bool = False
    in_arena: bool = False


class PlasmaBuffer:
    """A writable or readable mmap view of a file-tier object."""

    def __init__(self, path: str, size: int, writable: bool):
        flags = os.O_RDWR | (os.O_CREAT if writable else 0)
        self._fd = os.open(path, flags, 0o600)
        if writable:
            os.ftruncate(self._fd, size)
        self._mm = mmap.mmap(
            self._fd, size, access=mmap.ACCESS_WRITE if writable else mmap.ACCESS_READ
        )
        self.size = size

    def view(self) -> memoryview:
        return memoryview(self._mm)

    def close(self):
        try:
            self._mm.close()
        finally:
            os.close(self._fd)


class PlasmaStore:
    """Per-node shared-memory object store (the arena's owner).

    Thread-safe. Worker processes use :class:`PlasmaClient` views over the
    same arena file; this instance (in the node agent) owns capacity
    accounting and spill/evict decisions.
    """

    def __init__(self, session_dir: str, capacity: int, spill_dir: Optional[str] = None, name: str = "head"):
        self.shm_dir = os.path.join(
            "/dev/shm", "ray_tpu", f"{os.path.basename(session_dir)}_{name}"
        )
        os.makedirs(self.shm_dir, exist_ok=True)
        from ray_tpu.utils import cloudfs

        self.spill_dir = spill_dir or os.path.join(session_dir, f"spilled_objects_{name}")
        # Cloud spill targets (reference: external_storage.py:452 spills
        # to S3 via smart_open) — `gs://bucket/spill` just works; local
        # paths stay on the plain-os fast path.
        self._spill_uri = cloudfs.is_uri(self.spill_dir)
        cloudfs.makedirs(self.spill_dir)
        self.capacity = capacity
        self.used = 0  # file-tier bytes only; the arena self-accounts
        self._entries: Dict[ObjectID, PlasmaEntry] = GuardedDict(
            "_lock", owner=self, name="entries"
        )
        # Arena slots whose refcount-driven delete was refused because a
        # reader held a pinned view at the time; retried (and freed) on
        # later eviction passes once the pins drop.
        self._deferred_deletes: set = GuardedSet(
            "_lock", owner=self, name="deferred_deletes"
        )
        # Spill-loop churn counter (monotonic): one tick per object
        # spilled to disk. The controller's store-pressure detector
        # watches the DELTA per telemetry sweep — a store thrashing the
        # eviction loop spills continuously even when occupancy hovers
        # below the incident threshold.
        self.spill_ops = 0
        self._lock = threading.Lock()
        self._arena = None
        arena_mod = _try_arena()
        if arena_mod is not None:
            try:
                self._arena = arena_mod.Arena.create(
                    self.arena_path, max(capacity, 16 * 1024 * 1024)
                )
            except Exception as e:
                logger.warning("arena create failed (%s); file-per-object mode", e)

    @property
    def arena_path(self) -> str:
        return os.path.join(self.shm_dir, "arena")

    # -- paths -------------------------------------------------------------
    def _shm_path(self, oid: ObjectID) -> str:
        return os.path.join(self.shm_dir, oid.hex())

    def _part_path(self, oid: ObjectID) -> str:
        # File-tier objects are written under a .part name and renamed on
        # seal, so readers (PlasmaClient.try_view has no entry table) can
        # NEVER map an in-progress object — torn reads during network
        # pulls were possible otherwise (the arena tier's lookup already
        # refuses unsealed slots).
        return os.path.join(self.shm_dir, oid.hex() + ".part")

    def _spill_path(self, oid: ObjectID) -> str:
        if self._spill_uri:
            from ray_tpu.utils import cloudfs

            return cloudfs.join(self.spill_dir, oid.hex())
        return os.path.join(self.spill_dir, oid.hex())

    # -- write path --------------------------------------------------------
    def create(self, oid: ObjectID, size: int):
        with self._lock:
            if oid in self._entries:
                raise FileExistsError(f"object {oid.hex()} already exists")
            if self._arena is not None:
                buf = self._arena_alloc_evicting(oid.binary(), size)
                if buf is not None:
                    self._entries[oid] = PlasmaEntry(size=size, in_arena=True)
                    return buf
            self._maybe_evict(size)
            self._entries[oid] = PlasmaEntry(size=size)
            self.used += size
        return PlasmaBuffer(self._part_path(oid), size, writable=True)

    @guarded_by("_lock")
    def _drain_deferred_deletes(self):
        """Free arena slots whose delete was refused while pinned (the
        pins have since dropped for any that succeed here)."""
        for vid in list(self._deferred_deletes):
            if self._arena.delete(vid.binary()):
                self._deferred_deletes.discard(vid)

    @guarded_by("_lock")
    def _arena_alloc_evicting(self, oid_bytes: bytes, size: int):
        """Arena alloc, spilling LRU victims to disk until it fits (the
        reference's eviction-on-create, plasma/eviction_policy.cc)."""
        self._drain_deferred_deletes()
        swept = False
        while True:
            buf = self._arena.create_object(oid_bytes, size)
            if buf is not None:
                return buf
            victim = self._arena.lru_victim()
            if victim is None:
                # Everything evictable may be pinned by crashed readers —
                # reclaim dead-process pins once, then retry.
                if not swept:
                    swept = True
                    if self._arena.sweep_pins() > 0:
                        self._drain_deferred_deletes()
                        continue
                return None  # nothing evictable; caller falls back
            vid_bytes, vsize = victim
            vid = ObjectID(vid_bytes)
            if vid in self._deferred_deletes:
                # Refcount-dead, delete deferred while a reader was
                # pinned; it is unpinned now (lru_victim skips pins) —
                # free it without spilling (nothing will ever fetch it).
                if self._arena.delete(vid_bytes):
                    self._deferred_deletes.discard(vid)
                continue
            ve = self._entries.get(vid)
            vbuf = self._arena.get(vid_bytes)
            if vbuf is not None:
                if self._spill_uri:
                    from ray_tpu.utils import cloudfs

                    cloudfs.write_bytes(self._spill_path(vid), bytes(vbuf.view()))
                else:
                    with open(self._spill_path(vid), "wb") as f:
                        f.write(vbuf.view())
                vbuf.close()
            if not self._arena.delete(vid_bytes):
                # A reader pinned the victim (view_pinned) after the LRU
                # scan — the slot must stay resident while mapped. Leave
                # the entry arena-backed (spilled stays False) and drop
                # the copy written above: delete() only cleans the cloud
                # spill path for entries marked spilled, so keeping it
                # would leak the blob.
                if vbuf is not None:
                    self._delete_spilled(vid)
                continue
            if ve is not None:
                ve.spilled = True
                ve.in_arena = False
            self.spill_ops += 1

    def seal(self, oid: ObjectID):
        with self._lock:
            e = self._entries.get(oid)
            if e is not None:
                e.sealed = True
                if e.in_arena and self._arena is not None:
                    self._arena.seal(oid.binary())
                elif os.path.exists(self._part_path(oid)):
                    os.rename(self._part_path(oid), self._shm_path(oid))

    def put_bytes(self, oid: ObjectID, data: bytes | memoryview) -> int:
        buf = self.create(oid, len(data))
        buf.view()[:] = data
        buf.close()
        self.seal(oid)
        return len(data)

    def adopt(self, oid: ObjectID, size: int):
        """Account for an object another process wrote directly (workers
        write via PlasmaClient; the store owner is told after — the
        reference's seal notification, plasma/store.cc SealObjects)."""
        with self._lock:
            if oid in self._entries:
                return
            in_arena = (
                self._arena is not None and self._arena.contains(oid.binary())
            )
            if not in_arena:
                self._maybe_evict(size)
                self.used += size
            self._entries[oid] = PlasmaEntry(size=size, sealed=True, in_arena=in_arena)

    def ensure_local(self, oid: ObjectID) -> bool:
        """Restore a spilled object; True if readable on this node."""
        with self._lock:
            e = self._entries.get(oid)
            if e is None or not e.sealed:
                if self._arena is not None and self._arena.contains(oid.binary()):
                    return True
                return os.path.exists(self._shm_path(oid))
            if e.spilled:
                self._restore_locked(oid, e)
            e.last_access = time.monotonic()
            return True

    # -- read path ---------------------------------------------------------
    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._entries

    def get(self, oid: ObjectID):
        with self._lock:
            e = self._entries.get(oid)
            if e is None or not e.sealed:
                return None
            e.last_access = time.monotonic()
            if e.spilled:
                self._restore_locked(oid, e)
            if e.in_arena and self._arena is not None:
                return self._arena.get(oid.binary())
        return PlasmaBuffer(self._shm_path(oid), e.size, writable=False)

    def size_of(self, oid: ObjectID) -> Optional[int]:
        with self._lock:
            e = self._entries.get(oid)
            return e.size if e else None

    # -- lifecycle ---------------------------------------------------------
    def pin(self, oid: ObjectID):
        with self._lock:
            e = self._entries.get(oid)
            if e:
                e.pinned += 1
                if e.in_arena and self._arena is not None:
                    self._arena.pin(oid.binary(), 1)

    def unpin(self, oid: ObjectID):
        with self._lock:
            e = self._entries.get(oid)
            if e and e.pinned > 0:
                e.pinned -= 1
                if e.in_arena and self._arena is not None:
                    self._arena.pin(oid.binary(), -1)

    def delete(self, oid: ObjectID):
        with self._lock:
            e = self._entries.pop(oid, None)
            if e is None:
                return
            if e.in_arena and self._arena is not None:
                if not self._arena.delete(oid.binary()):
                    # A live reader holds a pinned view — the slot stays
                    # resident until the pin drops; eviction passes retry
                    # the delete (and skip spilling these).
                    self._deferred_deletes.add(oid)
            elif not e.spilled:
                self.used -= e.size
            for p in (self._shm_path(oid), self._part_path(oid)):
                try:
                    os.unlink(p)
                except FileNotFoundError:
                    pass
            if self._spill_uri:
                if e.spilled:
                    from ray_tpu.utils import cloudfs

                    cloudfs.delete(self._spill_path(oid), recursive=False)
            else:
                try:
                    os.unlink(self._spill_path(oid))
                except FileNotFoundError:
                    pass

    # -- eviction / spilling (file tier) -----------------------------------
    @guarded_by("_lock")
    def _maybe_evict(self, incoming: int):
        """Spill LRU sealed, unpinned file-tier objects until ``incoming``
        fits."""
        if self.capacity <= 0 or self.used + incoming <= self.capacity:
            return
        victims = sorted(
            (
                (e.last_access, oid, e)
                for oid, e in self._entries.items()
                if e.sealed and e.pinned == 0 and not e.spilled and not e.in_arena
            ),
        )
        for _, oid, e in victims:
            if self.used + incoming <= self.capacity:
                break
            if self._spill_uri:
                from ray_tpu.utils import cloudfs

                with open(self._shm_path(oid), "rb") as f:
                    cloudfs.write_bytes(self._spill_path(oid), f.read())
                os.unlink(self._shm_path(oid))
            else:
                shutil.move(self._shm_path(oid), self._spill_path(oid))
            e.spilled = True
            self.spill_ops += 1
            self.used -= e.size

    def spill_to_fraction(self, fraction: float) -> dict:
        """Proactively spill LRU sealed, unpinned entries until store
        occupancy (file tier + arena) is at or below ``fraction`` of
        capacity — the health plane's pressure actuator. The allocation
        path's eviction (:meth:`_maybe_evict` / arena victims) frees just
        enough for ONE incoming object, so a store under sustained
        pressure churns the eviction loop; one proactive pass drains it
        below the incident threshold instead."""
        fraction = min(max(float(fraction), 0.0), 1.0)
        spilled = 0
        freed = 0
        with self._lock:
            if self.capacity <= 0:
                return {"spilled": 0, "freed_bytes": 0, "occupancy": None}
            target = self.capacity * fraction
            arena_used = (
                self._arena.stats()["used"] if self._arena is not None else 0
            )
            occupancy = self.used + arena_used
            # File tier first (a rename per object, no copy)…
            victims = sorted(
                (e.last_access, oid, e)
                for oid, e in self._entries.items()
                if e.sealed and e.pinned == 0 and not e.spilled and not e.in_arena
            )
            for _, oid, e in victims:
                if occupancy <= target:
                    break
                if self._spill_uri:
                    from ray_tpu.utils import cloudfs

                    with open(self._shm_path(oid), "rb") as f:
                        cloudfs.write_bytes(self._spill_path(oid), f.read())
                    os.unlink(self._shm_path(oid))
                else:
                    shutil.move(self._shm_path(oid), self._spill_path(oid))
                e.spilled = True
                self.spill_ops += 1
                self.used -= e.size
                occupancy -= e.size
                freed += e.size
                spilled += 1
            # …then arena victims (copy-out + slot delete), bounded by
            # the entry count so a pinned-up arena can't loop forever.
            if self._arena is not None:
                for _ in range(len(self._entries) + 1):
                    if occupancy <= target:
                        break
                    n = self._spill_one_arena_victim()
                    if n is None:
                        break
                    occupancy -= n
                    freed += n
                    spilled += 1
            return {
                "spilled": spilled,
                "freed_bytes": freed,
                "occupancy": (
                    round(occupancy / self.capacity, 4) if self.capacity else None
                ),
            }

    @guarded_by("_lock")
    def _spill_one_arena_victim(self):
        """Spill the arena's LRU victim to the spill tier; returns the
        bytes freed, or None when nothing is evictable. Caller holds the
        lock. Mirrors the victim half of :meth:`_arena_alloc_evicting`
        (including deferred-delete and late-pin handling) without the
        allocation retry loop."""
        self._drain_deferred_deletes()
        victim = self._arena.lru_victim()
        if victim is None:
            return None
        vid_bytes, vsize = victim
        vid = ObjectID(vid_bytes)
        if vid in self._deferred_deletes:
            # Refcount-dead with a delete deferred behind a reader pin
            # that has since dropped — free it, nothing to spill.
            if self._arena.delete(vid_bytes):
                self._deferred_deletes.discard(vid)
                return vsize
            return None
        ve = self._entries.get(vid)
        vbuf = self._arena.get(vid_bytes)
        if vbuf is not None:
            if self._spill_uri:
                from ray_tpu.utils import cloudfs

                cloudfs.write_bytes(self._spill_path(vid), bytes(vbuf.view()))
            else:
                with open(self._spill_path(vid), "wb") as f:
                    f.write(vbuf.view())
            vbuf.close()
        if not self._arena.delete(vid_bytes):
            # A reader pinned the victim after the LRU scan — keep it
            # resident and drop the spilled copy (same rule as the
            # allocation path's eviction).
            if vbuf is not None:
                self._delete_spilled(vid)
            return None
        if ve is not None:
            ve.spilled = True
            ve.in_arena = False
        self.spill_ops += 1
        return vsize

    @guarded_by("_lock")
    def _restore_locked(self, oid: ObjectID, e: PlasmaEntry):
        if self._arena is not None:
            buf = self._arena_alloc_evicting(oid.binary(), e.size)
            if buf is not None:
                buf.view()[:] = self._read_spilled(oid)
                buf.close()
                self._arena.seal(oid.binary())
                self._delete_spilled(oid)
                e.spilled = False
                e.in_arena = True
                return
        self._maybe_evict(e.size)
        if self._spill_uri:
            with open(self._shm_path(oid), "wb") as f:
                f.write(self._read_spilled(oid))
            self._delete_spilled(oid)
        else:
            shutil.move(self._spill_path(oid), self._shm_path(oid))
        e.spilled = False
        self.used += e.size

    def _read_spilled(self, oid: ObjectID) -> bytes:
        if self._spill_uri:
            from ray_tpu.utils import cloudfs

            return cloudfs.read_bytes(self._spill_path(oid))
        with open(self._spill_path(oid), "rb") as f:
            return f.read()

    def _delete_spilled(self, oid: ObjectID):
        if self._spill_uri:
            from ray_tpu.utils import cloudfs

            cloudfs.delete(self._spill_path(oid), recursive=False)
        else:
            try:
                os.unlink(self._spill_path(oid))
            except FileNotFoundError:
                pass

    def stats(self) -> dict:
        with self._lock:
            spilled_bytes = pinned_slots = pinned_bytes = 0
            num_spilled = 0
            for e in self._entries.values():
                if e.spilled:
                    num_spilled += 1
                    spilled_bytes += e.size
                if e.pinned > 0:
                    pinned_slots += 1
                    pinned_bytes += e.size
            out = {
                "capacity": self.capacity,
                "used": self.used,
                "num_objects": len(self._entries),
                "num_spilled": num_spilled,
                # Spill-dir disk usage, accounted from entry sizes (covers
                # cloud spill URIs, where statvfs can't see the bytes).
                "spilled_bytes": spilled_bytes,
                # Store-side pins only (task-arg/broadcast pins taken via
                # PlasmaStore.pin); reader zero-copy pins live in each
                # reading process's census (live_pin_stats).
                "pinned_slots": pinned_slots,
                "pinned_bytes": pinned_bytes,
                # Refcount-dead arena slots whose delete is deferred
                # behind a live reader pin — the spill queue depth of the
                # delete path.
                "deferred_deletes": len(self._deferred_deletes),
                "spill_ops": self.spill_ops,
                "native_arena": self._arena is not None,
            }
            if self._arena is not None:
                a = self._arena.stats()
                out["used"] += a["used"]
                out["arena"] = a
            return out

    def spilled_ids(self) -> set:
        """Hex ids of currently-spilled entries — the cheap per-object
        spill lookup for summaries (no row materialization)."""
        with self._lock:
            return {
                oid.hex() for oid, e in self._entries.items() if e.spilled
            }

    def object_rows(self, limit: int = 1000) -> list:
        """Per-object store rows for the memory census fan-out (newest-
        insertion tail, O(limit) like the controller's list RPCs):
        {object_id, size, sealed, pinned, spilled, in_arena}."""
        import collections as _c

        with self._lock:
            return [
                {
                    "object_id": oid.hex(),
                    "size": e.size,
                    "sealed": e.sealed,
                    "pinned": e.pinned,
                    "spilled": e.spilled,
                    "in_arena": e.in_arena,
                }
                for oid, e in _c.deque(self._entries.items(), maxlen=limit)
            ]

    def destroy(self):
        if self._arena is not None:
            self._arena.close()
            self._arena = None
        shutil.rmtree(self.shm_dir, ignore_errors=True)
        if self._spill_uri:
            from ray_tpu.utils import cloudfs

            cloudfs.delete(self.spill_dir)
        else:
            shutil.rmtree(self.spill_dir, ignore_errors=True)


def _noop_release():
    pass


class PlasmaClient:
    """Worker-side view: maps objects created by any process on this node."""

    def __init__(self, shm_dir: str):
        self.shm_dir = shm_dir
        self._arena = None
        self._arena_tried = False
        self._arena_lock = threading.Lock()

    def _get_arena(self):
        # Locked lazy init: concurrent first readers (the data iterator's
        # prefetch pool) must not observe _arena_tried=True while _arena
        # is still being opened — that sent them to the file tier for
        # arena-resident objects ("object missing from store").
        if not self._arena_tried:
            with self._arena_lock:
                if not self._arena_tried:
                    arena_mod = _try_arena()
                    path = os.path.join(self.shm_dir, "arena")
                    if arena_mod is not None and os.path.exists(path):
                        try:
                            self._arena = arena_mod.Arena.open(path)
                        except Exception as e:
                            logger.warning("arena open failed (%s); file mode", e)
                    self._arena_tried = True
        return self._arena

    def _path(self, oid: ObjectID) -> str:
        return os.path.join(self.shm_dir, oid.hex())

    def put_parts(self, oid: ObjectID, meta: bytes, raws: list, total: int) -> int:
        """Single-copy put: serialize-parts are written straight into the
        object's mapping (no intermediate contiguous blob)."""
        from ray_tpu.utils.serialization import write_parts

        arena = self._get_arena()
        if arena is not None:
            try:
                buf = arena.create_object(oid.binary(), total)
            except FileExistsError:
                return total
            if buf is not None:
                write_parts(buf.view(), meta, raws)
                buf.close()
                arena.seal(oid.binary())
                return total
        path = self._path(oid)
        # write under .part, rename on completion: readers never see a
        # torn object (see PlasmaStore._part_path)
        part = path + ".part"
        fd = os.open(part, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            os.ftruncate(fd, total)
            with mmap.mmap(fd, total, access=mmap.ACCESS_WRITE) as mm:
                write_parts(memoryview(mm), meta, raws)
        finally:
            os.close(fd)
        os.rename(part, path)
        return total

    def put_bytes(self, oid: ObjectID, data: bytes | memoryview) -> int:
        # Writes directly into the node's arena; the node agent is told of
        # the new object afterwards (seal notification) and does accounting.
        arena = self._get_arena()
        if arena is not None:
            try:
                buf = arena.create_object(oid.binary(), len(data))
            except FileExistsError:
                return len(data)  # another writer beat us; content identical
            if buf is not None:
                buf.view()[:] = data
                buf.close()
                arena.seal(oid.binary())
                return len(data)
        path = self._path(oid)
        part = path + ".part"
        fd = os.open(part, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            os.ftruncate(fd, len(data))
            with mmap.mmap(fd, len(data), access=mmap.ACCESS_WRITE) as mm:
                mm[: len(data)] = data
        finally:
            os.close(fd)
        os.rename(part, path)
        return len(data)

    def get_buffer(self, oid: ObjectID, size: int):
        arena = self._get_arena()
        if arena is not None:
            buf = arena.get(oid.binary())
            if buf is not None:
                return buf
        return PlasmaBuffer(self._path(oid), size, writable=False)

    def try_view(self, oid: ObjectID, size: int) -> Optional[memoryview]:
        """Zero-copy read view of a sealed object, or None if it is neither
        in the arena nor on the file tier (e.g. spilled to disk)."""
        arena = self._get_arena()
        if arena is not None:
            buf = arena.get(oid.binary())
            if buf is not None:
                return buf.view()
        return self._file_view(oid, size)

    def _file_view(self, oid: ObjectID, size: int) -> Optional[memoryview]:
        path = self._path(oid)
        try:
            fd = os.open(path, os.O_RDONLY)
        except FileNotFoundError:
            return None
        try:
            mm = mmap.mmap(fd, size, access=mmap.ACCESS_READ)
        finally:
            os.close(fd)
        return memoryview(mm)

    def view_pinned(self, oid: ObjectID, size: int):
        """Zero-copy ``(view, release)`` of a sealed object, protected from
        arena eviction until ``release()`` runs (idempotent). None when the
        object is not mappable here (spilled / never local). The pin count
        lives in the shared arena table (any process may pin objects any
        other process wrote) and is taken BEFORE the lookup so eviction
        cannot recycle the slot between map and use; file-tier views need
        no pin — the mapping keeps the inode alive across spills and
        unlinks."""
        arena = self._get_arena()
        if arena is not None and arena.pin(oid.binary(), 1) >= 1:
            buf = arena.get(oid.binary())
            if buf is not None:
                lock = threading.Lock()
                released = [False]
                key = oid.binary()
                _pin_register(key, size)

                def release():
                    with lock:
                        if released[0]:
                            return
                        released[0] = True
                    _pin_unregister(key)
                    arena.pin(key, -1)

                return buf.view(), release
            arena.pin(oid.binary(), -1)  # unsealed or raced away
        view = self._file_view(oid, size)
        if view is None:
            return None
        return view, _noop_release
