"""Control-plane flight recorder: lifecycle events for tasks, actors,
placement groups, worker leases, and worker processes.

Reference: src/ray/gcs/gcs_server/gcs_task_manager.{h,cc} — the GCS task
manager ingests batched ``TaskEvents`` from every worker into a bounded
store and serves the state API / ``ray timeline`` from it. Same shape
here, generalized past tasks: every control-plane entity records
state-TRANSITION events (``submitted → queued → lease_granted →
worker_assigned → running → finished/failed``, actor restarts, PG
reserve/commit) into a bounded ring, and each transition's **dwell time**
(how long the entity sat in the previous state) feeds per-(kind, state)
sample rings and cluster metrics.

Writers:
  controller   — authoritative for controller-dispatched tasks, actors,
                 PGs, leases, and worker registration (records in-process)
  workers      — direct-push task RUNNING/FINISHED events ride the
                 existing ``task_events`` batch channel (worker_main)
  drivers      — direct-path SUBMITTED/WORKER_ASSIGNED events ship over
                 the same channel (normal_direct)
  node agents  — worker SPAWNED events ship with their telemetry loop

The controller's recorder is the single aggregation point: cross-process
events are folded in by :meth:`LifecycleRecorder.ingest`, which tolerates
out-of-order arrival across flush channels (a late-arriving older event
is ring-recorded but never corrupts dwell accounting).

Everything is bounded: the event ring (``lifecycle_ring_size``), the
per-state dwell sample rings (``lifecycle_dwell_samples``), the open-
entity map (LRU), and the metric tag space (kind/state/reason only —
never task ids).
"""
from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional, Tuple

# Pubsub channel carrying worker/actor/node DEATH (and node DRAIN)
# events as they are recorded — subscribers (e.g. the train
# BackendExecutor's gang watcher) learn about a failure push-style in
# ~the connection-loss latency instead of waiting out an RPC timeout.
DEATH_CHANNEL = "lifecycle:deaths"

# Terminal states pop the entity's open entry: the transition chain is
# complete and the entity must not pin LRU space.
TERMINAL_STATES = frozenset(
    {
        "FINISHED",
        "FAILED",
        "DEAD",
        "REMOVED",
        "REGISTERED",  # worker spawn chain: SPAWNED -> REGISTERED
        "GRANTED",  # lease chain: REQUESTED -> GRANTED
        "ABANDONED",  # lease requester died/timed out while parked
    }
)

# "Why pending" attribution vocabulary (bounded — these are metric tags).
PENDING_REASONS = (
    "insufficient_resources",  # feasible nodes exist, none has capacity now
    "no_idle_worker",  # resources free but the node's worker pool is busy
    "pg_unready",  # task targets a placement group not yet CREATED
    "spillback",  # every candidate node's pool rejected the task
    "infeasible",  # no node could EVER satisfy the demand
    "waiting_deps",  # parked on an unresolved object dependency
    "waiting_actor",  # actor task queued while the actor is not ALIVE
)

# Controller-internal state names -> the canonical lifecycle vocabulary
# (the legacy ``self.events`` ring keeps the old names for back-compat).
_CANONICAL = {
    "PENDING_SCHEDULING": "SUBMITTED",
    "PENDING_CREATION": "SUBMITTED",
    "CREATING": "WORKER_ASSIGNED",
    "CREATION_FAILED": "FAILED",
    "RECONSTRUCTING": "RETRYING",
}

_INGEST_KINDS = frozenset(
    {"task", "actor", "pg", "lease", "worker", "node", "action"}
)

# Extra attrs forwarded from shipped events into the ring (never metric
# tags): the self-healing "action" events carry their audit fields here.
_INGEST_ATTRS = ("name", "node", "worker", "actuator", "trigger", "target",
                 "outcome", "dry_run", "remote")

_DWELL_BOUNDARIES_MS = (
    1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 15000, 60000,
)

_metrics: Optional[Dict[str, Any]] = None


def _get_metrics() -> Dict[str, Any]:
    """Process-wide metric singletons (Metric registers globally; a
    recorder re-created in tests must not duplicate series)."""
    global _metrics
    if _metrics is None:
        from ray_tpu.util.metrics import Counter, Histogram

        _metrics = {
            "dwell": Histogram(
                "task_state_dwell_ms",
                "Time spent in each lifecycle state before transitioning out",
                boundaries=_DWELL_BOUNDARIES_MS,
                tag_keys=("kind", "state"),
            ),
            "transitions": Counter(
                "task_state_transitions_total",
                "Lifecycle state transitions by entity kind and new state",
                ("kind", "state"),
            ),
            "reasons": Counter(
                "task_pending_reason_total",
                "Why-pending attribution: why a task/lease could not be placed",
                ("reason",),
            ),
            "lease": Histogram(
                "lease_latency_ms",
                "Worker-lease scheduling latency (lease request to grant)",
                boundaries=_DWELL_BOUNDARIES_MS,
            ),
        }
    return _metrics


class LifecycleRecorder:
    """Bounded flight recorder for control-plane state transitions.

    Single-writer by design: the controller mutates it only from its
    asyncio loop (the same discipline as every other controller
    structure), so no lock is needed.
    """

    def __init__(self, ring_size: int = 20000, dwell_samples: int = 4096,
                 enabled: bool = True):
        self.enabled = enabled
        self.events: "collections.deque[dict]" = collections.deque(maxlen=ring_size)
        # (kind, id) -> [state, ts, pending_reason] for entities mid-chain.
        self._open: "collections.OrderedDict[Tuple[str, str], list]" = (
            collections.OrderedDict()
        )
        self._max_open = max(4 * ring_size, 50000)
        # (kind, id) -> terminal ts for recently-closed chains (LRU): a
        # late-arriving non-terminal half (cross-channel flush race, e.g.
        # a fast task's driver SUBMITTED after the worker's FINISHED)
        # must not re-open a finished entity — but a GENUINE re-open with
        # a newer ts (lineage reconstruction) still may.
        self._closed: "collections.OrderedDict[Tuple[str, str], float]" = (
            collections.OrderedDict()
        )
        self._dwell: Dict[Tuple[str, str], collections.deque] = {}
        self._dwell_samples = dwell_samples
        self._counts: Dict[Tuple[str, str], int] = {}
        self._reasons: Dict[str, int] = {}
        self._recorded = 0
        # Cluster-metric sync is THROTTLED: per-event Counter/Histogram
        # calls cost ~10us each (tags-key + cap resolution + lock) which
        # measurably taxes the controller loop at envelope depths, so
        # record() only accumulates locally and a bulk flush
        # (Histogram.observe_many / Counter.inc(n)) runs at most every
        # _METRIC_FLUSH_S — and on snapshot(), so readers never see a
        # stale rollup.
        self._pending_dwell: Dict[Tuple[str, str], list] = {}
        self._pending_lease: list = []
        self._pending_transitions: Dict[Tuple[str, str], int] = {}
        self._last_metric_flush = time.monotonic()

    _METRIC_FLUSH_S = 0.5

    # ------------------------------------------------------------------
    def record(self, kind: str, eid: str, state: str,
               ts: Optional[float] = None, **attrs) -> Optional[dict]:
        """Record one transition. ``attrs`` go into the ring event only
        (free-form context: name/node/reason) — never into metric tags."""
        if not self.enabled:
            return None
        state = _CANONICAL.get(state, state)
        if ts is None:
            ts = time.time()
        key = (kind, eid)
        entry = self._open.get(key)
        prev = None
        dwell_ms = None
        stale = False
        if entry is not None:
            if ts >= entry[1]:
                prev = entry[0]
                dwell_ms = (ts - entry[1]) * 1000.0
            else:
                # Out-of-order cross-channel arrival (e.g. a driver's
                # SUBMITTED flushing after the worker's RUNNING): keep the
                # newer open state, record the event without dwell.
                stale = True
        terminal = state in TERMINAL_STATES
        if terminal:
            # Close the chain even when the terminal event arrived
            # out-of-order (cross-host clock skew can stamp a worker's
            # FINISHED behind the driver's WORKER_ASSIGNED): leaving the
            # entry open would leak a ghost into `open`/pending counts.
            self._open.pop(key, None)
            while len(self._closed) >= self._max_open:
                self._closed.popitem(last=False)
            self._closed[key] = ts if entry is None else max(ts, entry[1])
        elif not stale:
            if entry is None:
                closed_ts = self._closed.get(key)
                if closed_ts is not None:
                    if ts <= closed_ts:
                        # late half of an already-finished chain: record
                        # the event, never re-open (a ghost open entry
                        # would inflate `open`/pending counts forever)
                        stale = True
                    else:
                        self._closed.pop(key, None)  # genuine re-open
            if not stale:
                if entry is None:
                    if len(self._open) >= self._max_open:
                        self._open.popitem(last=False)
                    self._open[key] = [state, ts, None]
                else:
                    entry[0], entry[1], entry[2] = state, ts, None
                    self._open.move_to_end(key)
        if dwell_ms is not None and prev is not None:
            pkey = (kind, prev)
            dq = self._dwell.get(pkey)
            if dq is None:
                dq = self._dwell[pkey] = collections.deque(
                    maxlen=self._dwell_samples
                )
            dq.append(dwell_ms)
            pend = self._pending_dwell.get(pkey)
            if pend is None:
                pend = self._pending_dwell[pkey] = []
            pend.append(dwell_ms)
            if kind == "lease" and state == "GRANTED":
                self._pending_lease.append(dwell_ms)
        skey = (kind, state)
        self._counts[skey] = self._counts.get(skey, 0) + 1
        self._pending_transitions[skey] = self._pending_transitions.get(skey, 0) + 1
        now_m = time.monotonic()
        if now_m - self._last_metric_flush >= self._METRIC_FLUSH_S:
            self.flush_metrics(now_m)
        ev = {"ts": ts, "kind": kind, "id": eid, "state": state}
        if prev is not None:
            ev["prev"] = prev
        if dwell_ms is not None:
            ev["dwell_ms"] = round(dwell_ms, 3)
        for k, v in attrs.items():
            if v is not None and v != "":
                ev[k] = v
        self.events.append(ev)
        self._recorded += 1
        return ev

    def record_batch(self, kind: str, state: str, n: int,
                     ts: Optional[float] = None, prev: Optional[str] = None,
                     dwell_ms: Optional[float] = None, **attrs) -> Optional[dict]:
        """Record ``n`` identical transitions as ONE ring event.

        The batched lease path grants N leases in one controller
        round-trip; recording them one-by-one would re-serialize exactly
        what the batching won (N record() calls, N ring appends, N
        entries churning the _open LRU). This folds the whole grant
        batch into one ring event carrying ``count``, one count bump of
        n, and one bulk dwell extension.

        Only for chains that OPEN AND CLOSE within the same call site
        (e.g. lease REQUESTED→GRANTED inside rpc_lease_batch): it never
        touches the ``_open``/``_closed`` maps, so out-of-order merging
        against per-event record() calls for the same entities is the
        caller's responsibility.
        """
        if not self.enabled or n <= 0:
            return None
        state = _CANONICAL.get(state, state)
        if ts is None:
            ts = time.time()
        if dwell_ms is not None and prev is not None:
            pkey = (kind, prev)
            dq = self._dwell.get(pkey)
            if dq is None:
                dq = self._dwell[pkey] = collections.deque(
                    maxlen=self._dwell_samples
                )
            dq.extend([dwell_ms] * n)
            pend = self._pending_dwell.get(pkey)
            if pend is None:
                pend = self._pending_dwell[pkey] = []
            pend.extend([dwell_ms] * n)
            if kind == "lease" and state == "GRANTED":
                self._pending_lease.extend([dwell_ms] * n)
        skey = (kind, state)
        self._counts[skey] = self._counts.get(skey, 0) + n
        self._pending_transitions[skey] = (
            self._pending_transitions.get(skey, 0) + n
        )
        now_m = time.monotonic()
        if now_m - self._last_metric_flush >= self._METRIC_FLUSH_S:
            self.flush_metrics(now_m)
        ev = {"ts": ts, "kind": kind, "id": "(batch)", "state": state,
              "count": n}
        if prev is not None:
            ev["prev"] = prev
        if dwell_ms is not None:
            ev["dwell_ms"] = round(dwell_ms, 3)
        for k, v in attrs.items():
            if v is not None and v != "":
                ev[k] = v
        self.events.append(ev)
        self._recorded += n
        return ev

    def pending_reason(self, kind: str, eid: str, reason: Optional[str]):
        """Attribute WHY an entity is stuck pending. Counted once per
        reason CHANGE (a blocked class re-visited every pump must not
        inflate the counter); the current reason is kept on the open
        entry so summaries can show live pending attribution."""
        if not self.enabled or not reason:
            return
        entry = self._open.get((kind, eid))
        if entry is None:
            # Unknown/LRU-evicted entity: without the entry there is no
            # dedup state, and counting every pump re-visit would inflate
            # the counter with pump frequency — skip instead (every call
            # site records a transition before attributing).
            return
        if entry[2] == reason:
            return
        entry[2] = reason
        self._reasons[reason] = self._reasons.get(reason, 0) + 1
        _get_metrics()["reasons"].inc(1, {"reason": reason})

    def ingest(self, ev: dict):
        """Fold one cross-process event (worker/driver/agent batches)."""
        if not self.enabled or not isinstance(ev, dict):
            return
        kind = ev.get("kind")
        if kind not in _INGEST_KINDS:
            return
        eid = ev.get("task_id") or ev.get("id")
        state = ev.get("state")
        if not eid or not state:
            return
        attrs = {k: ev.get(k) for k in _INGEST_ATTRS if ev.get(k) is not None}
        self.record(kind, eid, state, ts=ev.get("ts"), **attrs)

    def flush_metrics(self, now_m: Optional[float] = None):
        """Sync accumulated transitions/dwell into the cluster metrics
        (bulk: one tags-key + lock per (kind, state), not per event)."""
        self._last_metric_flush = now_m if now_m is not None else time.monotonic()
        if not (
            self._pending_transitions or self._pending_dwell or self._pending_lease
        ):
            return
        m = _get_metrics()
        trans, self._pending_transitions = self._pending_transitions, {}
        for (kind, state), n in trans.items():
            # bounded vocabulary: kinds are the 5 _INGEST_KINDS and states
            # the canonical lifecycle set — never entity ids
            m["transitions"].inc(n, {"kind": kind, "state": state})  # ray-tpu: lint-ignore[RTL004]
        dwell, self._pending_dwell = self._pending_dwell, {}
        for (kind, state), vals in dwell.items():
            m["dwell"].observe_many(vals, {"kind": kind, "state": state})
        lease, self._pending_lease = self._pending_lease, []
        if lease:
            m["lease"].observe_many(lease)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        """Aggregate view: per-(kind, state) transition counts and dwell
        percentiles, why-pending counters, currently-open entities by
        state, and ring accounting."""
        from ray_tpu.util.metrics import summarize_samples

        self.flush_metrics()

        states: Dict[str, Dict[str, dict]] = {}
        for (kind, state), n in sorted(self._counts.items()):
            states.setdefault(kind, {})[state] = {"count": n}
        for (kind, state), dq in sorted(self._dwell.items()):
            row = states.setdefault(kind, {}).setdefault(state, {"count": 0})
            row["dwell_ms"] = summarize_samples(dq)
        open_by: Dict[str, Dict[str, int]] = {}
        pending_now: Dict[str, int] = {}
        for (kind, _eid), entry in self._open.items():
            by = open_by.setdefault(kind, {})
            by[entry[0]] = by.get(entry[0], 0) + 1
            if entry[2]:
                pending_now[entry[2]] = pending_now.get(entry[2], 0) + 1
        return {
            "enabled": self.enabled,
            "states": states,
            "pending_reasons": dict(self._reasons),
            "pending_now": pending_now,
            "open": open_by,
            "events": {
                "recorded": self._recorded,
                "in_ring": len(self.events),
                "ring_size": self.events.maxlen,
            },
        }

    def tail(self, limit: int = 10000) -> List[dict]:
        n = len(self.events)
        if limit <= 0 or n == 0:
            return []
        if limit >= n:
            return list(self.events)
        import itertools

        # islice instead of list(...)[-limit:]: no full-ring copy on the
        # controller loop for a partial read.
        return list(itertools.islice(self.events, n - limit, n))


# ---------------------------------------------------------------------------
def to_chrome(events: List[dict]) -> List[dict]:
    """Lifecycle events -> Chrome-trace slices: per entity, consecutive
    transitions become complete ("X") events named by the state dwelled
    in, plus an instant for the final state. Loadable alongside span
    JSONL files in one chrome://tracing view (``ray-tpu timeline``)."""
    by_entity: Dict[Tuple[str, str], List[dict]] = {}
    for ev in events:
        if "kind" in ev and "id" in ev and "ts" in ev:
            by_entity.setdefault((ev["kind"], ev["id"]), []).append(ev)
    trace: List[dict] = []
    for (kind, eid), evs in by_entity.items():
        evs.sort(key=lambda e: e["ts"])
        pid = f"lifecycle:{kind}"
        tid = eid[:12]
        for a, b in zip(evs, evs[1:]):
            args = {"kind": kind, "id": eid, "next": b["state"]}
            if a.get("name"):
                args["name"] = a["name"]
            trace.append(
                {
                    "name": a["state"],
                    "cat": "lifecycle",
                    "ph": "X",
                    "ts": a["ts"] * 1e6,
                    "dur": max(0.0, (b["ts"] - a["ts"])) * 1e6,
                    "pid": pid,
                    "tid": tid,
                    "args": args,
                }
            )
        last = evs[-1]
        args = {"kind": kind, "id": eid}
        if last.get("name"):
            args["name"] = last["name"]
        if last.get("reason"):
            args["reason"] = last["reason"]
        trace.append(
            {
                "name": last["state"],
                "cat": "lifecycle",
                "ph": "i",
                "s": "t",
                "ts": last["ts"] * 1e6,
                "pid": pid,
                "tid": tid,
                "args": args,
            }
        )
    return trace
