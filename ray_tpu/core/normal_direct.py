"""Lease-based direct submission for NORMAL tasks.

Reference: src/ray/core_worker/transport/normal_task_submitter.cc:24
(SubmitTask queues by SchedulingKey, RequestNewWorkerIfNeeded :299 leases
workers, PushNormalTask pushes to the leased worker) +
src/ray/core_worker/lease_policy.cc (locality-aware raylet choice) +
src/ray/raylet/local_task_manager.cc:122 (the node-local dispatch half).

Shape here, mapped onto the controller/agent split (round 17 — every
hop is BATCHED; one arrow carries a window, not one task):

  caller ──lease_batch─────────▶ controller  (PLACEMENT ONLY: grants up
                                              to lease_batch_max leases
                                              for the key in ONE
                                              round-trip — locality-
                                              aware, resources reserved
                                              per lease)
  caller ──lease_worker_batch──▶ node agent  (the agent owns the node's
                                              free-worker view; binds a
                                              worker per grant non-
                                              blocking, None = miss →
                                              parked single claim; the
                                              controller plays this role
                                              for head-node leases)
  caller ──push_task_batch─────▶ worker      (a WINDOW of tasks per
                                              frame, ONE gathered reply,
                                              two frames double-buffered
                                              per lease; results land in
                                              the caller's owner-local
                                              memory store)

Both windows are dynamic (TCP-style slow start): the per-key lease
window doubles while full batch requests come back fully granted and
halves on partial grants / pool misses (spillback); the per-lease push
window doubles on clean full-window completions and halves on a lost
worker. ``lease_batching=False`` restores the per-lease/per-task
round-13 path (the bench A/B knob).

The controller is consulted once per LEASE BATCH, not once per task — a
queue of 10k same-shaped tasks costs a handful of batched round-trips,
and every push and reply travels caller↔worker. Dependencies are
resolved caller-side before a task becomes leaseable (reference:
LocalDependencyResolver), so a leased worker never blocks on a dep
fetch while holding its slot.

All submitter state is mutated ONLY on the CoreWorker's asyncio loop
thread (same single-writer discipline as direct.py).
"""
from __future__ import annotations

import asyncio
import logging
import os
import time
from collections import deque
from typing import Dict, Optional, Tuple

from ray_tpu.core.direct import _copy_future, complete_results, fail_returns
from ray_tpu.core.task_spec import TaskSpec, pack_normal_task
from ray_tpu.exceptions import TaskCancelledError, WorkerCrashedError
from ray_tpu.utils import rpc

logger = logging.getLogger("ray_tpu.normal_direct")

_push_m = None


def _push_batch_hist():
    """Lazy caller-side batch-size histogram (one observe per FRAME, not
    per task; ships to the controller over the ordinary metric channel —
    the controller-side twin lease_batch_size lives in controller.py)."""
    global _push_m
    if _push_m is None:
        from ray_tpu.util.metrics import Histogram

        _push_m = Histogram(
            "task_push_batch_size",
            "Tasks per push_task_batch frame",
            boundaries=(1, 2, 4, 8, 16, 32, 64, 128),
        )
    return _push_m


class _NCall:
    __slots__ = ("spec", "pins", "attempts_left", "cancelled", "global_deps")

    def __init__(self, spec: TaskSpec, pins, attempts_left: int):
        self.spec = spec
        self.pins = pins
        self.attempts_left = attempts_left
        self.cancelled = False
        self.global_deps = None  # filled during resolve (locality hint)


class _Lease:
    __slots__ = ("lease_id", "worker_peer", "worker_id_hex", "agent_addr",
                 "inflight", "window", "batches_inflight")

    def __init__(self, lease_id: bytes, worker_peer: rpc.Peer, worker_id_hex: str, agent_addr: str):
        self.lease_id = lease_id
        self.worker_peer = worker_peer
        self.worker_id_hex = worker_id_hex
        self.agent_addr = agent_addr  # "controller" for head-node leases
        self.inflight: set = set()
        # Dynamic per-lease push window (batched path): tasks per
        # push_task_batch frame. Doubles on a clean full-window batch
        # completion (capped at task_push_batch_max), halves on failure.
        self.window = 2
        # Batches on the wire to this worker (double buffering: one
        # executing, one in flight keeps the serial executor fed).
        self.batches_inflight = 0


class _KeyState:
    """Per-SchedulingKey queue + leases (reference: SchedulingKey entries
    in normal_task_submitter.h:40-54)."""

    __slots__ = ("key", "demand_items", "strategy", "ehash", "queue", "leases",
                 "pending_requests", "resolving", "lease_window")

    def __init__(self, key, spec: TaskSpec, ehash: str):
        self.key = key
        self.demand_items = tuple(spec.resources.items_fp())
        self.strategy = spec.scheduling_strategy
        self.ehash = ehash
        self.queue: deque = deque()
        self.leases: list = []
        self.pending_requests = 0
        self.resolving = 0  # calls still waiting on dependencies
        # Dynamic lease window (batched path): leases to ask for in the
        # next lease_batch round-trip. Slow-start: doubles while full
        # requests come back fully granted (capped at lease_batch_max),
        # halves on a partial grant or a worker-pool miss (spillback).
        self.lease_window = 1


class _PeerHandler:
    def on_disconnect(self, peer):
        pass


class NormalSubmitter:
    """One per CoreWorker process; owns every scheduling key's state."""

    def __init__(self, core):
        self.core = core
        cfg = core.config
        self.pipeline = int(cfg.get("max_tasks_in_flight_per_lease", 2))
        self.max_leases = int(cfg.get("max_leases_per_scheduling_key", 10))
        self.lease_timeout = float(cfg.get("worker_lease_timeout_s", 30.0))
        # Batched control plane (round 17): one lease_batch round-trip
        # grants a window of leases, pushes coalesce into
        # push_task_batch frames with one gathered reply. Off = the
        # legacy per-lease/per-task path above (the bench A/B knob).
        self.batching = bool(cfg.get("lease_batching", True))
        self.lease_batch_max = int(cfg.get("lease_batch_max", 16))
        self.push_batch_max = int(cfg.get("task_push_batch_max", 64))
        # Fresh leases start at this push window (slow-start floor).
        self.push_init = max(2, self.pipeline)
        self.keys: Dict[Tuple, _KeyState] = {}
        self.tasks: Dict = {}  # TaskID -> (_KeyState, _NCall) for cancel
        self.returns: Dict = {}  # return ObjectID -> TaskID
        self._worker_peers: Dict[str, rpc.Peer] = {}
        self._agent_peers: Dict[str, rpc.Peer] = {}
        self._handoff = rpc.BatchedHandoff(
            core.loop_runner.loop, lambda item: self._enqueue(*item)
        )
        # Flight-recorder feed: direct-push tasks bypass the controller,
        # so the CALLER emits the SUBMITTED/QUEUED/WORKER_ASSIGNED half
        # of each task's lifecycle chain (the executing worker emits
        # RUNNING/FINISHED), batched over the same task_events channel
        # (reference: TaskEventBuffer → gcs_task_manager). SUBMITTED
        # dwell = handling + dep resolution; QUEUED dwell = capacity
        # wait; WORKER_ASSIGNED dwell = push → worker pickup.
        self._lc_enabled = bool(cfg.get("lifecycle_events", True))
        # Bounded: a wedged flush must degrade to dropping the OLDEST
        # events, never grow the driver's memory.
        self._lc_events: deque = deque(maxlen=20000)
        if self._lc_enabled:
            core.loop_runner.submit(self._lc_flush_loop())

    def _lc_record(self, spec: TaskSpec, state: str, **attrs):
        if not self._lc_enabled:
            return
        ev = {
            "ts": time.time(),
            "kind": "task",
            "task_id": spec.task_id.hex(),
            "name": spec.name,
            "state": state,
        }
        for k, v in attrs.items():
            if v:
                ev[k] = v
        self._lc_events.append(ev)

    async def _lc_flush_loop(self):
        interval = float(self.core.config.get("event_flush_period_s", 0.25))
        while True:
            await asyncio.sleep(interval)
            if self.core.peer.closed:
                return  # driver shutting down
            if not self._lc_events:
                continue
            batch = []
            while self._lc_events and len(batch) < 20000:
                batch.append(self._lc_events.popleft())
            try:
                await self.core.peer.notify("task_events", batch)
            except Exception as e:  # noqa: BLE001 — transient controller hiccup
                if self.core.peer.closed:
                    return
                # Survive the hiccup: re-queue if there's room (the deque
                # is bounded; when full, the failed batch is dropped
                # rather than displacing newer events) and keep flushing.
                if (self._lc_events.maxlen or 0) - len(self._lc_events) >= len(batch):
                    self._lc_events.extendleft(reversed(batch))
                logger.debug("lifecycle event flush failed: %s", e)

    # -- caller thread ---------------------------------------------------
    def submit(self, spec: TaskSpec, pins) -> None:
        call = _NCall(spec, pins, spec.max_retries)
        self._handoff.push((spec, call))

    def cancel_threadsafe(self, task_id) -> None:
        self.core.loop_runner.loop.call_soon_threadsafe(self._cancel, task_id)

    def owns_task(self, task_id) -> bool:
        return task_id in self.tasks

    def task_for_return(self, oid):
        return self.returns.get(oid)

    # -- loop thread -----------------------------------------------------
    def _key_state(self, spec: TaskSpec) -> _KeyState:
        from ray_tpu.runtime_env import env_hash

        ehash = env_hash(spec.runtime_env)
        key = (spec.scheduling_class(), ehash)
        ks = self.keys.get(key)
        if ks is None:
            ks = self.keys[key] = _KeyState(key, spec, ehash)
        return ks

    def _enqueue(self, spec: TaskSpec, call: _NCall) -> None:
        ks = self._key_state(spec)
        self._lc_record(spec, "SUBMITTED")
        self.tasks[spec.task_id] = (ks, call)
        for oid in spec.return_ids():
            self.returns[oid] = spec.task_id
        ks.resolving += 1
        asyncio.get_running_loop().create_task(self._resolve_then_queue(ks, call))

    async def _resolve_then_queue(self, ks: _KeyState, call: _NCall) -> None:
        """Wait until every dependency is READY — owner-local entries via
        their local futures, global objects via one controller wait
        (reference: LocalDependencyResolver resolves deps BEFORE the lease
        request; pushing earlier could deadlock a full cluster on a task
        blocked fetching a dep that needs the held slot to be produced)."""
        try:
            ms = self.core.memory_store
            global_deps = []
            for dep in call.spec.dependencies:
                key = dep.binary()
                e = ms.lookup(key)
                if e is None:
                    global_deps.append(dep)
                    continue
                if not e.ready:
                    await asyncio.wrap_future(_copy_future(e.ensure_future()))
                if e.kind != "inline":
                    global_deps.append(dep)
            if global_deps:
                await self.core.peer.call(
                    "object_wait", global_deps, len(global_deps), None
                )
            call.global_deps = global_deps
        except Exception as e:  # noqa: BLE001 — controller gone / dep wait failed
            ks.resolving -= 1
            self._fail(call, e)
            self._pump(ks)  # may be the last pending work → release leases
            return
        ks.resolving -= 1
        if call.cancelled:
            self._pump(ks)
            return
        ks.queue.append(call)
        # The task is now LEASEABLE: SUBMITTED dwell = submission
        # handling + dep resolution (the control plane's share), QUEUED
        # dwell = waiting for lease/worker capacity (the cluster's
        # share) — same vocabulary as the controller pump's intake.
        self._lc_record(call.spec, "QUEUED")
        self._pump(ks)

    # -- lease + dispatch pump -------------------------------------------
    def _pump(self, ks: _KeyState) -> None:
        if self.core.peer.closed:
            return  # shutting down: no new lease requests, no retries
        if self.batching:
            self._pump_batched(ks)
            return
        for lease in list(ks.leases):
            while ks.queue and len(lease.inflight) < self.pipeline:
                self._send(ks, lease, ks.queue.popleft())
        if ks.queue:
            # Rate-limit lease REQUESTS in flight (reference:
            # max_pending_lease_requests per scheduling category); held
            # leases are unbounded — they scale with queue depth so a
            # storm can fan out across the whole cluster.
            want = min(len(ks.queue), self.max_leases) - ks.pending_requests
            for _ in range(max(0, want)):
                ks.pending_requests += 1
                asyncio.get_running_loop().create_task(self._lease_task(ks))
            return
        if ks.resolving:
            return  # tasks still resolving deps will want these leases
        # Queue drained: release leases with nothing in flight (reference:
        # the submitter returns the leased worker when its scheduling
        # key's queue empties).
        for lease in [l for l in ks.leases if not l.inflight]:
            self._release_lease(ks, lease)

    def _pump_batched(self, ks: _KeyState) -> None:
        """Batched pump (round 17): feed each lease whole WINDOWS of
        tasks (one framed RPC per window, double-buffered), then keep at
        most ONE lease_batch request in flight for the backlog."""
        for lease in list(ks.leases):
            while ks.queue and lease.batches_inflight < 2:
                n = min(len(ks.queue), lease.window)
                self._send_batch(
                    ks, lease, [ks.queue.popleft() for _ in range(n)]
                )
        if ks.queue:
            if not ks.pending_requests:
                ks.pending_requests = 1
                asyncio.get_running_loop().create_task(
                    self._lease_batch_task(ks)
                )
            return
        if ks.resolving:
            return  # tasks still resolving deps will want these leases
        for lease in [l for l in ks.leases if not l.inflight]:
            self._release_lease(ks, lease)

    async def _lease_batch_task(self, ks: _KeyState) -> None:
        """One batched lease round-trip: ask the controller for a WINDOW
        of leases, then claim workers for every grant per agent in one
        lease_worker_batch RPC. Pool misses fall back to the parking
        single-worker path and shrink the window (spillback)."""
        try:
            dep_hint = []
            if ks.queue:
                head = ks.queue[0]
                if head.global_deps:
                    dep_hint = [d.binary() for d in head.global_deps]
            # Enough leases to cover the backlog at the slow-start push
            # window, capped by the dynamic lease window.
            need = -(-len(ks.queue) // self.push_init)
            count = max(1, min(ks.lease_window, need))
            resp = await self.core.peer.call(
                "lease_batch", list(ks.demand_items), ks.strategy, ks.ehash,
                dep_hint, len(ks.queue), count,
            )
            if resp is None:
                return  # shutting down
            grants = resp["grants"]
            if len(grants) == count and count == ks.lease_window:
                ks.lease_window = min(self.lease_batch_max, ks.lease_window * 2)
            elif len(grants) < count:
                ks.lease_window = max(1, ks.lease_window // 2)
            by_agent: Dict[str, list] = {}
            for g in grants:
                by_agent.setdefault(g["agent_addr"], []).append(g)
            await asyncio.gather(
                *(self._claim_workers(ks, addr, gs)
                  for addr, gs in by_agent.items())
            )
        except Exception as e:  # noqa: BLE001 — controller unreachable
            if ks.queue and not self.core.peer.closed:
                logger.warning("lease batch failed (%s); retrying", e)
                await asyncio.sleep(0.05)
        finally:
            ks.pending_requests = 0
            self._pump(ks)

    async def _claim_workers(self, ks: _KeyState, agent_addr: str,
                             grants: list) -> None:
        """Claim workers for a batch of grants on ONE agent (or the
        controller for head-node leases) in one round-trip."""
        lease_ids = [g["lease_id"] for g in grants]
        try:
            if agent_addr == "controller":
                peer = self.core.peer
            else:
                peer = await self._agent_peer(agent_addr)
            outs = await asyncio.wait_for(
                peer.call("lease_worker_batch", lease_ids, ks.ehash),
                self.lease_timeout,
            )
        except Exception as e:  # noqa: BLE001 — agent unreachable, timeout
            for g in grants:
                self._notify_release(g["lease_id"], None, None)
            if ks.queue and not self.core.peer.closed:
                logger.warning("batch worker handout failed (%s); retrying", e)
            return
        misses = []
        for g, out in zip(grants, outs):
            if out is None:
                misses.append(g)
                continue
            try:
                wpeer = await self._worker_peer(out["worker_addr"])
            except Exception:  # noqa: BLE001 — worker died before connect
                self._notify_release(g["lease_id"], agent_addr, out["worker_id"])
                continue
            self._adopt_lease(
                ks, _Lease(g["lease_id"], wpeer, out["worker_id"], agent_addr)
            )
        if misses:
            # Worker-pool spillback: shrink the lease window and park the
            # missed leases on the blocking single-worker path (spawns
            # are already in flight agent-side).
            ks.lease_window = max(1, ks.lease_window // 2)
            for g in misses:
                asyncio.get_running_loop().create_task(
                    self._claim_one(ks, agent_addr, g)
                )

    async def _claim_one(self, ks: _KeyState, agent_addr: str, grant: dict) -> None:
        """Parked single-worker claim for a batch grant whose agent pool
        had no free worker (same contract as the legacy _lease_task
        inner half: waits for a spawn, bounded by the lease timeout)."""
        lease_id = grant["lease_id"]
        try:
            if agent_addr == "controller":
                peer = self.core.peer
            else:
                peer = await self._agent_peer(agent_addr)
            out = await asyncio.wait_for(
                peer.call("lease_worker", lease_id, ks.ehash),
                self.lease_timeout,
            )
            wpeer = await self._worker_peer(out["worker_addr"])
        except Exception as e:  # noqa: BLE001 — timeout / worker gone
            self._notify_release(lease_id, None, None)
            if ks.queue and not self.core.peer.closed:
                logger.warning("parked worker claim failed (%s)", e)
            return
        self._adopt_lease(
            ks, _Lease(lease_id, wpeer, out["worker_id"], agent_addr)
        )

    def _adopt_lease(self, ks: _KeyState, lease: _Lease) -> None:
        lease.window = self.push_init
        if ks.queue:
            ks.leases.append(lease)
            self._pump(ks)
        else:
            # burst already drained by other leases
            self._notify_release(
                lease.lease_id, lease.agent_addr, lease.worker_id_hex
            )

    async def _lease_task(self, ks: _KeyState) -> None:
        lease = None
        lease_id = None
        try:
            # Locality hint: global deps of the head-of-queue task — the
            # controller weighs their stored bytes per node (reference:
            # lease_policy.cc best_node_by_arg_bytes).
            dep_hint = []
            if ks.queue:
                head = ks.queue[0]
                if head.global_deps:
                    dep_hint = [d.binary() for d in head.global_deps]
            resp = await self.core.peer.call(
                "lease_request", list(ks.demand_items), ks.strategy, ks.ehash,
                dep_hint, len(ks.queue),
            )
            if resp is None:
                return  # shutting down
            lease_id = resp["lease_id"]
            agent_addr = resp["agent_addr"]
            if agent_addr == "controller":
                grant = await asyncio.wait_for(
                    self.core.peer.call("lease_worker", lease_id, ks.ehash),
                    self.lease_timeout,
                )
            else:
                agent = await self._agent_peer(agent_addr)
                grant = await asyncio.wait_for(
                    agent.call("lease_worker", lease_id, ks.ehash),
                    self.lease_timeout,
                )
            peer = await self._worker_peer(grant["worker_addr"])
            lease = _Lease(lease_id, peer, grant["worker_id"], agent_addr)
        except Exception as e:  # noqa: BLE001 — agent/worker unreachable, timeout
            if lease_id is not None:
                self._notify_release(lease_id, None, None)
            if ks.queue and not self.core.peer.closed:
                logger.warning("lease acquisition failed (%s); retrying", e)
                await asyncio.sleep(0.05)
            return
        finally:
            ks.pending_requests -= 1
            if lease is not None:
                if ks.queue:
                    ks.leases.append(lease)
                else:
                    # burst already drained by other leases
                    self._notify_release(lease.lease_id, lease.agent_addr, lease.worker_id_hex)
            self._pump(ks)

    async def _agent_peer(self, addr: str) -> rpc.Peer:
        p = self._agent_peers.get(addr)
        if p is None or p.closed:
            host, port = addr.rsplit(":", 1)
            p = await rpc.connect(host, int(port), _PeerHandler(), retries=3, delay=0.05)
            self._agent_peers[addr] = p
        return p

    async def _worker_peer(self, addr: str) -> rpc.Peer:
        p = self._worker_peers.get(addr)
        if p is None or p.closed:
            host, port = addr.rsplit(":", 1)
            p = await rpc.connect(host, int(port), _PeerHandler(), retries=3, delay=0.05)
            self._worker_peers[addr] = p
        return p

    # -- push / reply -----------------------------------------------------
    def _send(self, ks: _KeyState, lease: _Lease, call: _NCall) -> None:
        if call.cancelled:
            # e.g. cancelled while in flight, then requeued by a worker
            # connection loss — must resolve the returns, not vanish
            self._fail(call, TaskCancelledError(call.spec.task_id.hex()))
            return
        inline = None
        ms = self.core.memory_store
        for dep in call.spec.dependencies:
            key = dep.binary()
            e = ms.lookup(key)
            if e is None or e.kind != "inline" or not e.ready:
                continue
            payload, is_err = e.value()
            if isinstance(payload, Exception) or is_err:
                # dep resolved to an error — fail without occupying the lease
                from ray_tpu.utils.serialization import serialize

                blob = bytes(payload) if not isinstance(payload, Exception) else serialize(payload)
                self._fail(call, None, serialized=blob)
                return
            if inline is None:
                inline = {}
            inline[key] = bytes(payload)
        lease.inflight.add(call)
        self._lc_record(
            call.spec, "WORKER_ASSIGNED", worker=lease.worker_id_hex[:12]
        )
        fut = lease.worker_peer.call_nowait(
            "push_task", pack_normal_task(call.spec), inline
        )
        fut.add_done_callback(lambda f: self._on_reply(ks, lease, call, f))

    def _on_reply(self, ks: _KeyState, lease: _Lease, call: _NCall, fut: asyncio.Future) -> None:
        lease.inflight.discard(call)
        if fut.cancelled() or fut.exception() is not None:
            self._lease_lost(ks, lease)
            if call.attempts_left > 0:
                call.attempts_left -= 1
                ks.queue.appendleft(call)
            else:
                asyncio.get_running_loop().create_task(
                    self._fail_worker_death(call, lease.worker_id_hex)
                )
            self._pump(ks)
            return
        # already-done future (done-callback): no wait  # ray-tpu: lint-ignore[RTL008]
        results, error = fut.result()
        if error is not None and call.spec.retry_exceptions and call.attempts_left > 0:
            call.attempts_left -= 1
            ks.queue.appendleft(call)
            self._pump(ks)
            return
        complete_results(self.core, call.spec, results, error)
        self._done(call)
        self._pump(ks)

    def _send_batch(self, ks: _KeyState, lease: _Lease, calls: list) -> None:
        """Push a window of tasks in ONE framed RPC with one gathered
        reply (round 17) — the per-task push + reply frames were half
        the measured per-task control cost. Inline deps are merged
        across the batch (dedup: same dep bytes travel once)."""
        inline = None
        good = []
        ms = self.core.memory_store
        for call in calls:
            if call.cancelled:
                self._fail(call, TaskCancelledError(call.spec.task_id.hex()))
                continue
            bad_dep = False
            for dep in call.spec.dependencies:
                key = dep.binary()
                e = ms.lookup(key)
                if e is None or e.kind != "inline" or not e.ready:
                    continue
                payload, is_err = e.value()
                if isinstance(payload, Exception) or is_err:
                    from ray_tpu.utils.serialization import serialize

                    blob = (
                        bytes(payload) if not isinstance(payload, Exception)
                        else serialize(payload)
                    )
                    self._fail(call, None, serialized=blob)
                    bad_dep = True
                    break
                if inline is None:
                    inline = {}
                inline[key] = bytes(payload)
            if bad_dep:
                continue
            good.append(call)
        if not good:
            return
        for call in good:
            lease.inflight.add(call)
            self._lc_record(
                call.spec, "WORKER_ASSIGNED", worker=lease.worker_id_hex[:12]
            )
        _push_batch_hist().observe(len(good))
        fut = lease.worker_peer.call_nowait(
            "push_task_batch", [pack_normal_task(c.spec) for c in good], inline
        )
        lease.batches_inflight += 1
        sent_full = len(calls) >= lease.window
        fut.add_done_callback(
            lambda f: self._on_batch_reply(ks, lease, good, sent_full, f)
        )

    def _on_batch_reply(self, ks: _KeyState, lease: _Lease, calls: list,
                        sent_full: bool, fut: asyncio.Future) -> None:
        lease.batches_inflight -= 1
        for call in calls:
            lease.inflight.discard(call)
        if fut.cancelled() or fut.exception() is not None:
            # Whole-batch connection loss: retry semantics are PER TASK,
            # unchanged from the single-push path — each call burns one
            # attempt and requeues (order preserved), or fails terminally.
            self._lease_lost(ks, lease)
            lease.window = max(1, lease.window // 2)
            for call in reversed(calls):
                if call.attempts_left > 0:
                    call.attempts_left -= 1
                    ks.queue.appendleft(call)
                else:
                    asyncio.get_running_loop().create_task(
                        self._fail_worker_death(call, lease.worker_id_hex)
                    )
            self._pump(ks)
            return
        # already-done future (done-callback): no wait  # ray-tpu: lint-ignore[RTL008]
        replies = fut.result()
        for call, (results, error) in zip(calls, replies):
            if (
                error is not None
                and call.spec.retry_exceptions
                and call.attempts_left > 0
            ):
                call.attempts_left -= 1
                ks.queue.appendleft(call)
                continue
            complete_results(self.core, call.spec, results, error)
            self._done(call)
        if sent_full:
            # Clean completion of a full window: grow toward the cap.
            lease.window = min(self.push_batch_max, lease.window * 2)
        self._pump(ks)

    # -- lease lifecycle ---------------------------------------------------
    def _lease_lost(self, ks: _KeyState, lease: _Lease) -> None:
        if lease in ks.leases:
            ks.leases.remove(lease)
            # resources must be freed even though the worker is gone; the
            # agent's pool entry cleans itself up on the worker's death
            self._notify_release(lease.lease_id, None, None)
        addr_peer = self._worker_peers
        for addr, p in list(addr_peer.items()):
            if p is lease.worker_peer:
                addr_peer.pop(addr, None)

    def _release_lease(self, ks: _KeyState, lease: _Lease) -> None:
        ks.leases.remove(lease)
        self._notify_release(lease.lease_id, lease.agent_addr, lease.worker_id_hex)

    def _notify_release(self, lease_id: bytes, agent_addr: Optional[str], worker_id_hex: Optional[str]) -> None:
        asyncio.ensure_future(self.core.peer.notify("lease_release", lease_id))
        if agent_addr and agent_addr != "controller" and worker_id_hex:
            async def _ret():
                try:
                    agent = await self._agent_peer(agent_addr)
                    await agent.notify("lease_return", worker_id_hex, lease_id)
                except Exception as e:  # noqa: BLE001 — agent gone with its node
                    logger.debug("lease_return to %s failed: %s", agent_addr, e)

            asyncio.ensure_future(_ret())

    async def _fail_worker_death(self, call: _NCall, worker_id_hex: str) -> None:
        """Terminal worker death: ask the controller WHY the worker died
        so an OOM kill surfaces as OutOfMemoryError, matching the legacy
        path's taxonomy (reference: worker exit detail in GCS)."""
        from ray_tpu.exceptions import OutOfMemoryError

        reason = None
        for _ in range(5):  # death processing may lag the conn loss
            try:
                reason = await self.core.peer.call("worker_death_info", worker_id_hex)
            except Exception:  # noqa: BLE001 — controller gone too
                break
            if reason is not None:
                break
            await asyncio.sleep(0.1)
        if reason == "oom":
            exc: Exception = OutOfMemoryError(
                f"task {call.spec.name} killed by the memory monitor"
            )
        else:
            exc = WorkerCrashedError(
                f"worker executing {call.spec.name} died (connection lost)"
            )
        self._fail(call, exc)

    # -- completion --------------------------------------------------------
    def _fail(self, call: _NCall, exc: Optional[Exception], serialized: Optional[bytes] = None) -> None:
        fail_returns(self.core, call.spec, exc, serialized)
        self._done(call)

    def _done(self, call: _NCall) -> None:
        call.pins = None
        self.tasks.pop(call.spec.task_id, None)
        for oid in call.spec.return_ids():
            self.returns.pop(oid, None)

    def _cancel(self, task_id) -> None:
        entry = self.tasks.get(task_id)
        if entry is None:
            return
        ks, call = entry
        call.cancelled = True
        try:
            ks.queue.remove(call)
        except ValueError:
            pass
        else:
            self._fail(call, TaskCancelledError(task_id.hex()))
            return
        for lease in ks.leases:
            if call in lease.inflight:
                asyncio.ensure_future(lease.worker_peer.notify("cancel", task_id))
                return
        # still resolving deps — _resolve_then_queue observes the flag
        self._fail(call, TaskCancelledError(task_id.hex()))
