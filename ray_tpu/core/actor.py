"""Actor API: ActorClass / ActorHandle / ActorMethod.

Reference: python/ray/actor.py (``ActorClass._remote`` :869, method
wrappers, ``max_restarts``/``max_task_retries`` semantics :75-171). Handles
pickle down to the actor id and rebind on deserialization, so they can be
passed between tasks/actors freely.
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

from ray_tpu.core.remote_function import (
    _DEFAULT_TASK_OPTIONS,
    build_resource_set,
    normalize_strategy,
)
from ray_tpu.core.resources import ResourceSet
from ray_tpu.core.task_spec import TaskSpec, TaskType
from ray_tpu.utils.ids import ActorID, TaskID
from ray_tpu.utils.serialization import serialize_function

_DEFAULT_ACTOR_OPTIONS = dict(
    num_cpus=None,  # None → 1 CPU for placement only (reference default)
    num_tpus=0,
    memory=0,
    resources=None,
    max_restarts=0,
    max_task_retries=0,
    max_concurrency=1,
    concurrency_groups=None,  # {name: max_concurrency} per-group executors
    name=None,
    lifetime=None,
    scheduling_strategy=None,
    runtime_env=None,
)


def method(concurrency_group: Optional[str] = None, **unsupported):
    """Method-level actor options (reference: python/ray/actor.py
    ``@ray.method``): declare the concurrency group a method routes to.
    Per-call ``.options(concurrency_group=...)`` overrides this.

    ``num_returns`` is per-CALL here (handles don't carry class metadata
    across pickling) — use ``.options(num_returns=...)``; passing it at
    declaration raises rather than being silently ignored."""
    if unsupported:
        raise ValueError(
            f"unsupported @ray_tpu.method option(s) {sorted(unsupported)}; "
            "declare num_returns per call via .options(num_returns=...)"
        )

    def deco(f):
        f.__ray_tpu_method_options__ = {"concurrency_group": concurrency_group}
        return f

    return deco


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(_DEFAULT_ACTOR_OPTIONS)
        self._options.update(options or {})
        self._blob: Optional[bytes] = None
        self._digest: Optional[bytes] = None

    def options(self, **opts) -> "ActorClass":
        new = ActorClass(self._cls, {**self._options, **opts})
        new._blob, new._digest = self._blob, self._digest
        return new

    def remote(self, *args, **kwargs) -> "ActorHandle":
        from ray_tpu.core.api import _require_worker

        if self._options.get("lifetime") not in (None, "detached"):
            raise ValueError(
                f"lifetime must be None or 'detached', got {self._options['lifetime']!r}"
            )
        core = _require_worker()
        if self._blob is None:
            self._blob = serialize_function(self._cls)
            self._digest = hashlib.blake2b(self._blob, digest_size=16).digest()
        opts = self._options
        actor_id = ActorID.from_random()
        args_blob, deps, captures = core.build_args(args, kwargs)
        res_opts = dict(opts)
        # Explicit resource requests are held while the actor lives; the
        # default 1 CPU is for scheduling only (reference: actor.py).
        hold = (
            res_opts["num_cpus"] is not None
            or bool(res_opts["num_tpus"])
            or bool(res_opts["memory"])
            or bool(res_opts["resources"])
        )
        if res_opts["num_cpus"] is None:
            res_opts["num_cpus"] = 1
        from ray_tpu.util import tracing as _tracing

        runtime_env = dict(opts.get("runtime_env") or {})
        if opts.get("name"):
            runtime_env["__actor_name__"] = opts["name"]
        runtime_env = _tracing.inject_runtime_env(runtime_env) or runtime_env
        spec = TaskSpec(
            task_id=TaskID.for_actor_creation(actor_id),
            task_type=TaskType.ACTOR_CREATION_TASK,
            name=f"{self._cls.__name__}.__init__",
            func_digest=self._digest,
            func_blob=self._blob,
            args_blob=args_blob,
            dependencies=deps,
            num_returns=1,
            resources=build_resource_set(res_opts),
            owner_id=core.worker_id,
            scheduling_strategy=normalize_strategy(opts.get("scheduling_strategy")),
            max_retries=0,
            actor_id=actor_id,
            max_restarts=opts["max_restarts"],
            max_task_retries=opts["max_task_retries"],
            max_concurrency=opts["max_concurrency"],
            concurrency_groups=dict(opts["concurrency_groups"])
            if opts.get("concurrency_groups")
            else None,
            runtime_env=runtime_env,
            hold_resources_while_alive=hold,
            lifetime=opts.get("lifetime"),
        )
        core.create_actor(spec, captures)
        return ActorHandle(actor_id, max_task_retries=opts["max_task_retries"])

    def bind(self, *args, **kwargs):
        """Lazy DAG class node (reference: actor.py bind → dag ClassNode)."""
        from ray_tpu.dag.node import ClassNode

        return ClassNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actors cannot be instantiated directly. Use {self._cls.__name__}.remote() instead."
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, max_task_retries: int = 0):
        self._actor_id = actor_id
        self._max_task_retries = max_task_retries

    def __getattr__(self, item: str) -> "ActorMethod":
        if item.startswith("_"):
            raise AttributeError(item)
        return ActorMethod(self, item)

    def _call_fn(self, fn, *args, _name: Optional[str] = None, **kwargs):
        """Run ``fn(actor_instance, *args, **kwargs)`` on the actor — the
        reference's ``__ray_call__`` escape hatch (actor.py), used by
        compiled DAGs and worker groups."""
        from ray_tpu.core.api import _require_worker

        core = _require_worker()
        blob = serialize_function(fn)
        digest = hashlib.blake2b(blob, digest_size=16).digest()
        args_blob, deps, captures = core.build_args(args, kwargs)
        spec = TaskSpec(
            task_id=core.next_task_id(),
            task_type=TaskType.ACTOR_TASK,
            name=_name or f"actor.{getattr(fn, '__name__', 'fn')}",
            func_digest=digest,
            func_blob=blob,
            args_blob=args_blob,
            dependencies=deps,
            num_returns=1,
            resources=ResourceSet.from_dict({}),
            owner_id=core.worker_id,
            max_retries=0,  # __ray_call__ has actor-task semantics: no
            # implicit retry (the TaskSpec default of 3 is for normal tasks)
            actor_id=self._actor_id,
            actor_method_name=None,
        )
        return core.submit_actor_task(spec, captures)[0]

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._max_task_retries))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


class ActorMethod:
    def __init__(self, handle: ActorHandle, name: str, num_returns: int = 1,
                 concurrency_group: Optional[str] = None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def options(self, num_returns: int = 1, concurrency_group: Optional[str] = None,
                **_ignored) -> "ActorMethod":
        return ActorMethod(self._handle, self._name, num_returns, concurrency_group)

    def bind(self, *args, **kwargs):
        """Lazy DAG node on a live actor (reference: actor method bind —
        required form for compiled DAGs)."""
        from ray_tpu.dag.node import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)

    def remote(self, *args, **kwargs):
        from ray_tpu.core.api import _require_worker

        core = _require_worker()
        streaming = self._num_returns == "streaming"
        args_blob, deps, captures = core.build_args(args, kwargs)
        from ray_tpu.util import tracing as _tracing

        spec = TaskSpec(
            task_id=core.next_task_id(),
            task_type=TaskType.ACTOR_TASK,
            name=f"actor.{self._name}",
            func_digest=b"\x00" * 16,
            func_blob=None,
            args_blob=args_blob,
            dependencies=deps,
            num_returns=TaskSpec.STREAMING if streaming else self._num_returns,
            resources=build_resource_set({}),
            owner_id=core.worker_id,
            max_retries=self._handle._max_task_retries,
            actor_id=self._handle._actor_id,
            actor_method_name=self._name,
            concurrency_group=self._concurrency_group,
            runtime_env=_tracing.inject_runtime_env(None),
        )
        refs = core.submit_actor_task(spec, captures)
        if streaming:
            from ray_tpu.core.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id)
        return refs[0] if self._num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(f"Actor methods cannot be called directly. Use .{self._name}.remote().")
