"""Worker process: executes tasks and hosts actors.

Reference: python/ray/_private/workers/default_worker.py (entrypoint) +
the Cython execution path python/ray/_raylet.pyx:2222
``task_execution_handler`` and the receiver-side scheduling queues
(src/ray/core_worker/transport/task_receiver.cc, concurrency groups in
transport/concurrency_group_manager.h).

Structure: the asyncio loop (in a background thread via EventLoopThread)
handles RPC; execution happens on a ThreadPoolExecutor so blocking user code
never stalls the control plane. Actor tasks run on a per-actor pool of
``max_concurrency`` threads — FIFO when 1 (ordered actors), concurrent
otherwise. ``async def`` methods are driven to completion on the executing
thread (the reference uses boost fibers — transport/fiber.h).
"""
from __future__ import annotations

import asyncio
import logging
import os
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from ray_tpu.core.client import CoreWorker
from ray_tpu.core.object_ref import _RefMarker
from ray_tpu.core.task_spec import TaskSpec, TaskType
from ray_tpu.exceptions import TaskError
from ray_tpu.utils import rpc
from ray_tpu.utils.ids import NodeID, TaskID, WorkerID
from ray_tpu.utils.serialization import (
    deserialize,
    deserialize_function,
    serialize,
)

logger = logging.getLogger("ray_tpu.worker")


class WorkerHandler:
    """RPC handler for controller→worker messages AND the worker's direct
    listener (caller→actor pushes arrive on separate connections —
    reference: the worker's CoreWorkerService gRPC server).

    Dispatches may arrive between worker registration and executor attach
    (registration happens inside CoreWorker.__init__) — buffer until ready.
    """

    def __init__(self):
        self.executor: Optional[TaskExecutor] = None
        self._buffer: list = []
        self._controller_peer = None
        self._agent_peer = None

    def attach_executor(self, executor: "TaskExecutor"):
        self.executor = executor
        buffered, self._buffer = self._buffer, []
        for spec, kind in buffered:
            executor.submit(spec, kind)

    def _dispatch(self, spec: TaskSpec, kind: str):
        if self.executor is None:
            self._buffer.append((spec, kind))
        else:
            self.executor.submit(spec, kind)

    def rpc_execute_task(self, peer, spec: TaskSpec):
        self._dispatch(spec, "task")

    def rpc_create_actor(self, peer, spec: TaskSpec):
        self._dispatch(spec, "actor_create")

    def rpc_execute_actor_task(self, peer, spec: TaskSpec):
        self._dispatch(spec, "actor_task")

    def rpc_push_actor_task(self, peer, packed: tuple, inline_deps=None):
        """Direct caller→actor push; the returned Future resolves to the
        reply carrying the results (reference:
        CoreWorkerService::PushTask). Returning a Future (not awaiting)
        keeps the hot path free of per-request task creation."""
        from ray_tpu.core.task_spec import unpack_actor_task

        spec = unpack_actor_task(packed)
        if self.executor is None:
            return self._push_when_ready(spec, "actor_task", inline_deps)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self.executor.submit(spec, "actor_task", reply=(loop, fut), inline_deps=inline_deps)
        return fut

    def rpc_push_task(self, peer, packed: tuple, inline_deps=None):
        """Direct lease-holder→worker push of a NORMAL task (reference:
        NormalTaskSubmitter PushNormalTask → CoreWorkerService::PushTask);
        results travel back in the reply to the caller's memory store."""
        from ray_tpu.core.task_spec import unpack_normal_task

        spec = unpack_normal_task(packed)
        if self.executor is None:
            return self._push_when_ready(spec, "task", inline_deps)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self.executor.submit(spec, "task", reply=(loop, fut), inline_deps=inline_deps)
        return fut

    def rpc_push_task_batch(self, peer, packed_list: list, inline_deps=None):
        """Push a BATCH of normal tasks in one frame with ONE gathered
        reply (round 17): the reply frame is half the per-task RPC cost,
        and the execution pool is serial anyway, so per-task replies buy
        nothing. ``inline_deps`` is the merged dep dict for the whole
        batch. Resolves to a list of per-task (results, error) tuples in
        submission order."""
        from ray_tpu.core.task_spec import unpack_normal_task

        specs = [unpack_normal_task(p) for p in packed_list]
        if self.executor is None:
            return self._push_batch_when_ready(specs, inline_deps)
        loop = asyncio.get_running_loop()
        futs = []
        for spec in specs:
            fut = loop.create_future()
            self.executor.submit(spec, "task", reply=(loop, fut),
                                 inline_deps=inline_deps)
            futs.append(fut)
        return asyncio.gather(*futs)

    async def _push_when_ready(self, spec: TaskSpec, kind: str, inline_deps):
        while self.executor is None:  # registration race (first push only)
            await asyncio.sleep(0.002)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self.executor.submit(spec, kind, reply=(loop, fut), inline_deps=inline_deps)
        return fut

    async def _push_batch_when_ready(self, specs: list, inline_deps):
        while self.executor is None:  # registration race (first push only)
            await asyncio.sleep(0.002)
        loop = asyncio.get_running_loop()
        futs = []
        for spec in specs:
            fut = loop.create_future()
            self.executor.submit(spec, "task", reply=(loop, fut),
                                 inline_deps=inline_deps)
            futs.append(fut)
        return await asyncio.gather(*futs)

    def rpc_cancel(self, peer, task_id: TaskID):
        if self.executor is not None:
            self.executor.cancelled.add(task_id)

    def rpc_current_task(self, peer):
        """What this worker is executing right now — queried by the
        controller's OOM victim policies for direct-push tasks it never
        dispatched (reference: the raylet knows its leased workers'
        tasks; here the worker itself is the source of truth)."""
        if self.executor is None:
            return None
        return self.executor.current_task_info

    def rpc_exit(self, peer):
        ex = self.executor
        events = ex._events if ex is not None else None
        if not events or self._controller_peer is None or self._controller_peer.closed:
            os._exit(0)
        # Best-effort final event flush: an exiting worker (actor kill,
        # pool retire, teardown) must not eat the tail of its tasks'
        # lifecycle chains — up to one flush period of RUNNING/FINISHED
        # events can still be buffered. A timer hard-exits if the
        # controller connection is wedged.
        threading.Timer(1.0, lambda: os._exit(0)).start()
        batch = []
        while events and len(batch) < 10000:
            batch.append(events.popleft())

        async def _flush_then_exit():
            try:
                await self._controller_peer.notify("task_events", batch)
            except Exception as e:  # noqa: BLE001 — exiting regardless
                logger.debug("final event flush failed: %s", e)
            os._exit(0)

        asyncio.ensure_future(_flush_then_exit())

    def rpc_ping(self, peer):
        return "pong"

    def rpc_stack_dump(self, peer):
        """Live stacks of every thread (reference: py-spy dump via the
        dashboard reporter / `ray stack`)."""
        from ray_tpu.utils.stack_dump import dump_all_threads

        return dump_all_threads()

    def rpc_dump_stacks(self, peer):
        """Structured stack dump: thread names + frames + current-task
        attribution + lockwatch held-lock annotations (the `ray-tpu
        profile stacks` fan-out leg)."""
        from ray_tpu.util import profiling

        return profiling.dump_stacks()

    def rpc_profile_cpu(self, peer, duration_s: float = 10.0, hz: float = 100.0):
        """Sampling CPU profile of this worker for ``duration_s``. The
        sampler runs on its own thread; the returned coroutine just
        sleeps, so the worker's control plane stays live."""
        from ray_tpu.util import profiling

        return profiling.sample_async(duration_s, hz)

    def rpc_profile_device(self, peer, action: str, capture: str = "",
                           base_dir=None):
        """Attach/detach a jax.profiler trace on this live worker (no
        restart). Returns {ok, dir?, error?}; gracefully degrades when
        jax or the backend profiler is unavailable."""
        from ray_tpu.util import profiling

        return profiling.device_trace_control(action, capture, base_dir)

    def rpc_dump_memory(self, peer, limit: int = 1000):
        """This worker's object/memory census (`ray-tpu memory` fan-out
        leg): open local refs by creation call-site, owner-local memory
        store occupancy, and live zero-copy arena pins."""
        from ray_tpu.core import memory_census

        return memory_census.dump(limit)

    def rpc_pubsub_msg(self, peer, channel: str, message):
        from ray_tpu.experimental.pubsub import _deliver

        _deliver(channel, message)

    def rpc_gc_nudge(self, peer):
        """Health-plane leak actuator: force a collection in this worker
        so unreachable reference cycles holding ObjectRefs break NOW
        (the refs' __del__ marks them dropped; the ref-flush loop ships
        the drops within one flush period). Returns collection stats."""
        import gc

        unreachable = gc.collect()
        pending = 0
        core = self.executor.core if self.executor is not None else None
        if core is not None:
            pending = core.refs.pending_drops()
        return {"unreachable": unreachable, "pending_drops": pending}

    def rpc_pin_shapes(self, peer, functions):
        """Health-plane storm actuator: pin shape-bucketing for the named
        functions in this worker's compile tracker (util/compile_tracker)
        so recompile-storm workloads round dynamic dims up to power-of-2
        buckets instead of recompiling per shape."""
        from ray_tpu.util import compile_tracker

        return compile_tracker.pin_functions(functions)

    def on_disconnect(self, peer):
        if peer is self._agent_peer:
            # The spawning agent died (host death, SIGKILL): this worker
            # is an orphan — nothing will ever retire it, and a rejoined
            # agent spawns a fresh pool. Self-reap immediately instead of
            # lingering as a stray process (the PR 13 orphan fix).
            logger.warning("node agent connection lost; exiting")
            os._exit(1)
        # Direct-caller connections come and go; only the controller
        # connection is load-bearing.
        if peer is not self._controller_peer:
            return
        core = self.executor.core if self.executor is not None else None
        window = 0.0
        if core is not None and isinstance(getattr(core, "config", None), dict):
            window = float(core.config.get("controller_reconnect_window_s", 0.0))
        # Only a BUSY worker (hosting an actor / running a task) has
        # state worth riding a controller restart for. An idle pool
        # worker that reconnects just re-idles — exiting now instead of
        # lingering a full window loses nothing (the agent respawns on
        # demand) and keeps teardown/chaos tests free of straggler
        # processes.
        busy = self.executor is not None and (
            self.executor.actor_instance is not None
            or self.executor.current_task_info is not None
        )
        if window <= 0 or core is None or not busy:
            os._exit(1)

        # Bounded reconnect (jittered backoff inside try_reconnect):
        # rides through a controller restart on the same address; a
        # controller that is truly gone still ends with exit(1), just
        # one window later. Runs on its own thread — this callback is
        # on the IO loop the reconnect itself needs.
        def _rejoin():
            if core.try_reconnect():
                self._controller_peer = core.peer
            else:
                os._exit(1)

        threading.Thread(target=_rejoin, daemon=True,
                         name="controller-rejoin").start()


class TaskExecutor:
    def __init__(self, core: CoreWorker):
        import collections

        self.core = core
        self.pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="task-exec")
        self.actor_pool: Optional[ThreadPoolExecutor] = None
        # Named concurrency groups (reference: concurrency_group_manager.h
        # :34 — per-group executors so a slow group can't starve another;
        # ordering is preserved within each group's queue). Actor tasks
        # that arrive before __init__ completes park in _pending_actor so
        # group routing (which needs the constructed class) happens after
        # creation, in submission order.
        self.actor_groups: Dict[str, ThreadPoolExecutor] = {}
        self._actor_ready = False
        self._pending_actor: list = []
        self._actor_gate = threading.Lock()
        self.actor_instance: Any = None
        self.cancelled: set = set()
        self.current_task_info: Optional[dict] = None  # read by rpc_current_task
        self._func_cache: Dict[bytes, Any] = {}
        self._reply_handoff = None  # created lazily (needs the loop)
        # Direct-push tasks bypass the controller, so their observability
        # events flush in periodic batches (reference: TaskEventBuffer →
        # GCS task manager, task_event_buffer.cc).
        self._events = collections.deque()
        self.core.loop_runner.submit(self._event_flush_loop())

    async def _event_flush_loop(self):
        interval = self.core.config.get("event_flush_period_s", 0.25)
        while True:
            await asyncio.sleep(interval)
            if not self._events:
                continue
            batch = []
            while self._events and len(batch) < 10000:
                batch.append(self._events.popleft())
            try:
                await self.core.peer.notify("task_events", batch)
            except Exception:  # noqa: BLE001 — controller gone
                return

    def _group_for(self, spec: TaskSpec) -> Optional[str]:
        """Resolve an actor task's concurrency group: per-call override
        (.options(concurrency_group=...)) wins over the method's declared
        group (@ray_tpu.method(concurrency_group=...))."""
        if spec.concurrency_group:
            return spec.concurrency_group
        if self.actor_instance is not None and spec.actor_method_name:
            m = getattr(type(self.actor_instance), spec.actor_method_name, None)
            if m is not None:
                return getattr(m, "__ray_tpu_method_options__", {}).get(
                    "concurrency_group"
                )
        return None

    def submit(self, spec: TaskSpec, kind: str, reply=None, inline_deps=None):
        if kind == "actor_task":
            with self._actor_gate:
                if not self._actor_ready:
                    # __init__ still running (or queued): park; flushed in
                    # order by _flush_pending_actor_tasks after creation.
                    self._pending_actor.append((spec, reply, inline_deps))
                    return
            # Unknown group names fall through to the default pool; _run
            # rejects them with a clean TaskError before executing.
            pool = (
                self.actor_groups.get(self._group_for(spec))
                or self.actor_pool
                or self.pool
            )
        else:
            pool = self.pool
        pool.submit(self._guarded_run, spec, kind, reply, inline_deps)

    def _flush_pending_actor_tasks(self):
        """Called once creation finished (or failed): open the gate and
        route everything parked behind it, preserving submission order."""
        with self._actor_gate:
            self._actor_ready = True
            pending, self._pending_actor = self._pending_actor, []
            for spec, reply, inline_deps in pending:
                pool = (
                    self.actor_groups.get(self._group_for(spec))
                    or self.actor_pool
                    or self.pool
                )
                pool.submit(self._guarded_run, spec, "actor_task", reply, inline_deps)

    def _guarded_run(self, spec: TaskSpec, kind: str, reply=None, inline_deps=None):
        try:
            self._run(spec, kind, reply, inline_deps)
        except Exception:
            logger.exception("internal error running task %s", spec.name)
            if reply is not None:
                self._reply(reply, ([], TaskError(spec.name, traceback.format_exc(), None)))
        finally:
            # Creation done (success OR failure): release parked actor
            # tasks — on failure they run against actor_instance=None and
            # report clean TaskErrors, same as before the gate existed.
            if kind == "actor_create" and not self._actor_ready:
                self._flush_pending_actor_tasks()
            from ray_tpu import runtime_context
            from ray_tpu.util import profiling

            runtime_context._set_task(None, None)
            profiling.set_thread_task(None)

    def _reply(self, reply, payload):
        """Batched exec-thread → loop handoff for completed replies."""
        loop, fut = reply
        if self._reply_handoff is None:
            self._reply_handoff = rpc.BatchedHandoff(loop, _resolve_reply)
        self._reply_handoff.push((fut, payload))

    # ------------------------------------------------------------------
    def _load_func(self, spec: TaskSpec):
        fn = self._func_cache.get(spec.func_digest)
        if fn is None:
            fn = deserialize_function(spec.func_blob)
            self._func_cache[spec.func_digest] = fn
        return fn

    def _resolve_args(self, spec: TaskSpec, inline_deps=None):
        args, kwargs = deserialize(spec.args_blob)

        def res(v):
            if isinstance(v, _RefMarker):
                if inline_deps is not None:
                    data = inline_deps.get(v.oid.binary())
                    if data is not None:
                        # caller-owned value shipped with the push
                        # (reference: LocalDependencyResolver inlining)
                        return deserialize(data)
                value, is_error = self.core.get_raw(v.oid)
                if is_error:
                    # dependency failures propagate AS the original error
                    # (ObjectLostError, the producer's exception, …) — not
                    # wrapped in this task's TaskError (reference: dep
                    # errors pass through ray.get unchanged)
                    raise _DepError(value)
                return value
            return v

        return tuple(res(a) for a in args), {k: res(v) for k, v in kwargs.items()}

    def _run(self, spec: TaskSpec, kind: str, reply=None, inline_deps=None):
        if spec.task_id in self.cancelled:
            from ray_tpu.exceptions import TaskCancelledError

            err = TaskCancelledError(spec.task_id.hex())
            if reply is not None:
                self._reply(reply, ([], err))
            else:
                self._report(spec, None, err)
            return
        from ray_tpu import runtime_context
        from ray_tpu.util import profiling

        runtime_context._set_task(
            spec.task_id.hex(), spec.actor_id.hex() if spec.actor_id else None
        )
        # CPU-sample attribution: the profiler tags this thread's samples
        # with the executing task/actor-method name (cleared in finally;
        # spec.name already carries "actor.<method>" for actor tasks).
        profiling.set_thread_task(spec.name)
        if reply is not None:
            # Direct pushes bypass the controller, so the worker emits the
            # RUNNING half of the task's timeline span itself (FINISHED
            # comes from _report_direct); the event flush batches both.
            self._events.append(
                {
                    "ts": time.time(),
                    "kind": "task",
                    "type": spec.task_type.name,
                    "task_id": spec.task_id.hex(),
                    "name": spec.name,
                    "state": "RUNNING",
                }
            )
        if kind == "task" and reply is not None:
            # direct-push normal task: controller doesn't track it, so the
            # worker itself answers OOM-victim queries (rpc_current_task)
            self.current_task_info = {
                "task_id": spec.task_id.hex(),
                "name": spec.name,
                "owner": spec.owner_id.hex() if spec.owner_id else "",
                "retriable": spec.max_retries > 0,
                "start": time.time(),
            }
        trace_span_cm = None
        profiler_cm = None
        try:
            if spec.runtime_env:
                from ray_tpu import runtime_env as _renv

                _renv.ensure_applied(spec.runtime_env)
                ctx = spec.runtime_env.get("__trace_ctx__")
                if ctx:
                    # Caller traced this call: record the execution span
                    # under its context (reference: tracing_helper's
                    # _inject_tracing_into_function execution wrapper).
                    from ray_tpu.util import tracing as _tracing

                    if not _tracing.tracing_enabled():
                        _tracing.enable_tracing(
                            os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu")
                        )
                    _tracing.attach_context(ctx)
                    trace_span_cm = _tracing.start_span(
                        f"execute:{spec.name}", {"task_id": spec.task_id.hex()}
                    )
                    trace_span_cm.__enter__()
            if spec.runtime_env and spec.runtime_env.get("jax_profiler"):
                # per-task jax.profiler capture (reference: the nsight
                # runtime-env plugin wraps the worker with the profiler)
                from ray_tpu.runtime_env.jax_profiler import task_trace

                profiler_cm = task_trace(spec, spec.runtime_env["jax_profiler"])
                profiler_cm.__enter__()
            args, kwargs = self._resolve_args(spec, inline_deps)
            if kind == "task":
                fn = self._load_func(spec)
                result = _maybe_async(fn(*args, **kwargs))
            elif kind == "actor_create":
                cls = self._load_func(spec)
                self.actor_instance = cls(*args, **kwargs)
                n = max(1, spec.max_concurrency)
                self.actor_pool = ThreadPoolExecutor(n, thread_name_prefix="actor-exec")
                for gname, gsize in (spec.concurrency_groups or {}).items():
                    self.actor_groups[gname] = ThreadPoolExecutor(
                        max(1, int(gsize)), thread_name_prefix=f"actor-cg-{gname}"
                    )
                result = None
            elif spec.func_blob is not None:
                # Function-on-actor (reference: __ray_call__): compiled-DAG
                # loops and worker-group utilities execute arbitrary fns
                # against the actor instance.
                fn = self._load_func(spec)
                result = _maybe_async(fn(self.actor_instance, *args, **kwargs))
            else:  # actor_task
                group = self._group_for(spec)  # per-call override OR declared
                if group and group not in self.actor_groups:
                    raise ValueError(
                        f"unknown concurrency group {group!r}; "
                        f"declared groups: {sorted(self.actor_groups)}"
                    )
                method = getattr(self.actor_instance, spec.actor_method_name)
                result = _maybe_async(method(*args, **kwargs))
            # Close the profiler capture BEFORE reporting: the caller's
            # ray.get returns at report time and must be able to list
            # the finished capture (streaming generator bodies run during
            # _report and are not captured — a documented edge).
            if profiler_cm is not None:
                cmx, profiler_cm = profiler_cm, None
                try:
                    cmx.__exit__(None, None, None)
                except Exception as e:  # noqa: BLE001 — capture teardown only
                    logger.debug("profiler capture teardown failed: %s", e)
            # Report inside the span: for streaming tasks the generator
            # body runs during _report, which must be attributed.
            if reply is not None:
                self._report_direct(spec, result, None, reply)
            else:
                self._report(spec, result, None)
        except _DepError as e:
            if reply is not None:
                self._report_direct(spec, None, e.inner, reply)
            else:
                self._report(spec, None, e.inner)
        except Exception as e:  # noqa: BLE001 — user errors cross the wire
            tb = traceback.format_exc()
            err = e if isinstance(e, TaskError) else TaskError(spec.name, tb, None)
            # Structured log plane: the failure traceback is recorded —
            # attributed to this task — BEFORE the error crosses the
            # wire, so `state.summarize_errors()` sees every failure even
            # when the caller never gets the ref (core/log_plane.py).
            from ray_tpu.core import log_plane

            log_plane.record_task_error(spec.name, spec.task_id.hex(), e, tb)
            if reply is not None:
                self._report_direct(spec, None, err, reply)
            else:
                self._report(spec, None, err)
        finally:
            self.current_task_info = None
            if profiler_cm is not None:
                try:
                    profiler_cm.__exit__(None, None, None)
                except Exception as e:  # noqa: BLE001 — capture teardown only
                    logger.debug("profiler capture teardown failed: %s", e)
            if trace_span_cm is not None:
                from ray_tpu.util import tracing as _tracing

                trace_span_cm.__exit__(None, None, None)
                _tracing.detach_context()

    def _report_direct(self, spec: TaskSpec, result, error, reply):
        """Direct-push completion: results travel back IN the push reply
        to the caller's memory store (reference: PushTask reply carries
        return objects). Large results go to the local shm store and are
        registered with the controller directory; inline results with
        nested refs are also registered so containment pins exist."""
        results = []
        if error is None:
            try:
                if spec.num_returns == 1:
                    values = [result]
                else:
                    values = list(result)
                    if len(values) != spec.num_returns:
                        raise ValueError(
                            f"task {spec.name} returned {len(values)} values, "
                            f"expected num_returns={spec.num_returns}"
                        )
                from ray_tpu.core.client import _serialize_parts_capturing
                from ray_tpu.core.memory_census import task_site
                from ray_tpu.utils.serialization import assemble_parts

                # census attribution label — "" (no-op) when the census
                # is disabled; interned, so unique task names stay bounded
                site = task_site(spec.name)
                for oid, value in zip(spec.return_ids(), values):
                    meta, raws, total, contained = _serialize_parts_capturing(value)
                    if contained:
                        # nested refs escape to the caller → must be
                        # globally resolvable + containment-pinned
                        self.core.promote_refs(contained)
                    if total <= self.core.inline_limit:
                        data = assemble_parts(meta, raws)
                        if contained:
                            self.core._call(
                                "object_put_inline", oid, data, False, contained,
                                callsite=site,
                            )
                        # 5th element: globally registered — the caller
                        # must mark its entry promoted so ref flushes
                        # reach the controller (else the record + its
                        # containment pins leak forever)
                        results.append((oid, "inline", data, False, bool(contained)))
                    else:
                        self.core.plasma.put_parts(oid, meta, raws, total)
                        self.core._call(
                            "object_put_shm", oid, total, self.core.node_id,
                            False, contained or [],
                            callsite=site,
                        )
                        results.append((oid, "shm"))
            except Exception:  # noqa: BLE001 — unpicklable results
                results = []
                error = TaskError(spec.name, traceback.format_exc(), None)
        self._events.append(
            {
                "ts": time.time(),
                "kind": "task",
                "type": spec.task_type.name,
                "task_id": spec.task_id.hex(),
                "name": spec.name,
                "state": "FINISHED" if error is None else "FAILED",
            }
        )
        if (
            spec.task_type == TaskType.NORMAL_TASK
            and any(r[1] == "shm" for r in results)
        ):
            # shm results are reconstructible — give the controller the
            # lineage the legacy path would have recorded (reference:
            # owner-side TaskManager lineage feeding ObjectRecoveryManager)
            self.core._submit("task_lineage", spec)
        self._reply(reply, (results, error))

    def _report(self, spec: TaskSpec, result, error):
        if spec.is_streaming and error is None:
            self._report_stream(spec, result)
            return
        results = []
        if error is None:
            try:
                if spec.num_returns == 1:
                    values = [result]
                else:
                    values = list(result)
                    if len(values) != spec.num_returns:
                        raise ValueError(
                            f"task {spec.name} returned {len(values)} values, "
                            f"expected num_returns={spec.num_returns}"
                        )
                from ray_tpu.core.client import _serialize_parts_capturing
                from ray_tpu.utils.serialization import assemble_parts

                for oid, value in zip(spec.return_ids(), values):
                    # Refs nested in a return value are pinned by the
                    # return object (containment) until it is freed —
                    # otherwise the worker's own ref drop could GC a
                    # ray_tpu.put() object before the caller ever sees it.
                    meta, raws, total, contained = _serialize_parts_capturing(value)
                    if contained:
                        self.core.promote_refs(contained)
                    if total <= self.core.inline_limit:
                        results.append(
                            (oid, "inline", assemble_parts(meta, raws), False, contained)
                        )
                    else:
                        self.core.plasma.put_parts(oid, meta, raws, total)
                        results.append((oid, "shm", total, contained))
            except Exception:  # noqa: BLE001 — unpicklable results must not hang the caller
                results = []
                error = TaskError(spec.name, traceback.format_exc(), None)
        try:
            self.core._call("task_done", spec.task_id, results, error)
        except rpc.ConnectionLost:
            os._exit(1)

    def _report_stream(self, spec: TaskSpec, result):
        """Stream generator items as they are produced: each yield becomes
        its own object, published immediately (reference: streaming
        generator execution, _raylet.pyx:1077)."""
        from ray_tpu.utils.ids import ObjectID

        from ray_tpu.core.client import _serialize_capturing
        from ray_tpu.core.memory_census import task_site as _task_site

        index = 0
        error = None
        try:
            for item in result:
                if spec.task_id in self.cancelled:
                    # Consumer cancelled mid-stream (abandoned LLM stream):
                    # stop producing; close() runs the generator's finally
                    # blocks so replica-side resources are released.
                    try:
                        result.close()
                    except Exception:  # noqa: BLE001 — user close errors
                        logger.exception("stream close failed for %s", spec.name)
                    break
                oid = ObjectID.for_task_return(spec.task_id, index)
                data, contained = _serialize_capturing(item)
                self.core.put_serialized(
                    oid, data, contained=contained,
                    callsite=_task_site(spec.name),
                )
                self.core._call("stream_item", spec.task_id, index)
                index += 1
        except Exception as e:  # noqa: BLE001 — mid-stream error → final item
            tb = traceback.format_exc()
            err_item = e if isinstance(e, TaskError) else TaskError(spec.name, tb, None)
            from ray_tpu.core import log_plane

            log_plane.record_task_error(spec.name, spec.task_id.hex(), e, tb)
            oid = ObjectID.for_task_return(spec.task_id, index)
            self.core.put_serialized(oid, serialize(err_item), is_error=True)
            self.core._call("stream_item", spec.task_id, index)
        try:
            self.core._call("task_done", spec.task_id, [], error)
        except rpc.ConnectionLost:
            os._exit(1)


class _DepError(Exception):
    """Carrier for a failed dependency's ORIGINAL error."""

    def __init__(self, inner):
        self.inner = inner


def _resolve_reply(item):
    fut, payload = item
    if not fut.done():
        fut.set_result(payload)


def _maybe_async(result):
    # inspect.iscoroutine, NOT asyncio.iscoroutine: the latter also
    # matches plain generator objects (legacy generator-based coroutine
    # support, Python ≤3.10), which would asyncio.run() streaming task
    # generators instead of handing them to _report_stream.
    import inspect

    if inspect.iscoroutine(result):
        return asyncio.run(result)
    return result


def main():
    logging.basicConfig(level=logging.INFO, format="[worker] %(levelname)s %(message)s")
    from ray_tpu.util import lockwatch

    lockwatch.maybe_install()  # RAY_TPU_LOCKWATCH=1: watch locks created from here on
    from ray_tpu.util import chaos

    chaos.install_fault_plan_from_env()  # RAY_TPU_FAULT_PLAN: deterministic chaos
    addr = os.environ["RAY_TPU_CONTROLLER"]
    node_id = NodeID.from_hex(os.environ["RAY_TPU_NODE_ID"])
    worker_id = WorkerID.from_hex(os.environ["RAY_TPU_WORKER_ID"])
    shm_dir = os.environ["RAY_TPU_SHM_DIR"]

    from ray_tpu.utils.net import bind_host, host_ip

    handler = WorkerHandler()
    loop_runner = rpc.EventLoopThread("worker-io")
    # Direct-transport listener: callers push actor tasks straight here
    # (reference: each worker hosts a CoreWorkerService gRPC server).
    # Loopback unless RAY_TPU_NODE_IP opts this host into multi-host.
    _server, listen_port = loop_runner.run(rpc.serve(handler, bind_host(), 0))
    core = CoreWorker(
        addr,
        mode="worker",
        loop_runner=loop_runner,
        handler=handler,
        worker_id=worker_id,
        node_id=node_id,
        local_shm_dir=shm_dir,
        listen_addr=f"{host_ip()}:{listen_port}",
    )
    handler._controller_peer = core.peer
    # Structured log plane (core/log_plane.py): stamp every logging
    # record / print() line / task traceback with {node, worker, task,
    # severity, ts} into the JSONL sidecar next to this worker's raw log,
    # rotate both at log_rotate_bytes, and ship ERROR records to the
    # controller's error index. Installed BEFORE the executor attaches so
    # buffered tasks' output is captured too.
    if core.config.get("log_structured", True):
        from ray_tpu.core import log_plane

        log_plane.install(
            core.session_dir,
            node_id=node_id.hex(),
            worker_id=worker_id.hex(),
            capture_streams=True,
            rotate_bytes=int(core.config.get("log_rotate_bytes", 64 << 20)),
        )
        log_plane.start_ship_loop(core)
    # Make the full public API usable from inside tasks (nested tasks,
    # ray_tpu.get/put in user code) BEFORE any buffered task can run.
    from ray_tpu.core import api
    from ray_tpu import runtime_context

    runtime_context._set_process(node_id.hex(), worker_id.hex())
    api._attach_worker(core)
    handler.attach_executor(TaskExecutor(core))
    # Device telemetry (per-device HBM + compile tracking): no-ops until
    # user code imports jax in this worker, then reports ~every poll.
    from ray_tpu.core.node_telemetry import start_process_telemetry

    start_process_telemetry(core)
    # Continuous low-rate CPU sampling for incident auto-capture (off
    # unless profiling_continuous_hz is configured).
    from ray_tpu.util import profiling

    profiling.ensure_continuous()
    agent_addr = os.environ.get("RAY_TPU_AGENT_ADDR", "")
    if agent_addr:
        # Direct-pool worker spawned by a node agent: announce to the
        # agent's free-worker view (reference: worker registration with
        # its raylet). The connection stays open; the agent uses it to
        # retire the worker and to observe its death.
        async def _attach():
            host, port = agent_addr.rsplit(":", 1)
            peer = await rpc.connect(host, int(port), handler)
            await peer.notify(
                "worker_attach", worker_id.hex(), f"{host_ip()}:{listen_port}"
            )
            handler._agent_peer = peer  # keep alive

        loop_runner.run(_attach())

    # serve-forever park by design; exit via rpc_exit / os._exit  # ray-tpu: lint-ignore[RTL008]
    threading.Event().wait()


if __name__ == "__main__":
    main()
