"""Node agent: per-node daemon for non-head nodes.

Reference: the raylet (src/ray/raylet/main.cc, node_manager.cc) minus
scheduling (which is GCS-direct in this design — see controller.py): it
registers the node's resources, hosts the node's shared-memory store, and
spawns/kills worker processes on request (reference: worker_pool.cc:438
``StartWorkerProcess``).
"""
from __future__ import annotations

import argparse
import asyncio
import collections
import json
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Dict

from ray_tpu.core.object_store import PlasmaStore
from ray_tpu.util.guards import OWNER_THREAD, GuardedDict, GuardedSet
from ray_tpu.utils import rpc
from ray_tpu.utils.ids import NodeID, ObjectID, WorkerID

logger = logging.getLogger("ray_tpu.node_agent")

_children: Dict[int, subprocess.Popen] = {}

# Worker-lifecycle events recorded at spawn time (flight recorder,
# core/lifecycle.py): SPAWNED here pairs with REGISTERED at the
# controller, making the dwell the worker-startup latency. Agents ship
# the deque over their telemetry channel; the controller (spawning head
# workers through this same function) drains it in-process. Bounded —
# an undrained deque (telemetry disabled) must not grow forever.
_lifecycle_events: "collections.deque" = collections.deque(maxlen=10000)


def child_env(needs_tpu: bool) -> dict:
    """Environment for spawned processes.

    The host image hooks TPU runtime registration into every interpreter via
    sitecustomize (costing ~2s of jax import per process). Control-plane
    processes never touch jax, and CPU-mode workers don't need the TPU hook,
    so strip the trigger var for them — worker spawn drops from ~2.3s to
    ~0.4s.
    """
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    # The trigger var must survive a CPU-mode hop in the spawn chain
    # (driver → controller [no TPU] → worker [TPU]): stash it instead of
    # dropping it, and restore for TPU-mode children.
    saved = env.pop("RAY_TPU_SAVED_AXON_POOL_IPS", None)
    if not needs_tpu:
        cur = env.pop("PALLAS_AXON_POOL_IPS", None) or saved
        if cur:
            env["RAY_TPU_SAVED_AXON_POOL_IPS"] = cur
    elif "PALLAS_AXON_POOL_IPS" not in env and saved:
        env["PALLAS_AXON_POOL_IPS"] = saved
    return env


def spawn_worker(session_dir: str, controller_addr: str, node_id: NodeID, shm_dir: str,
                 extra_env: Dict[str, str] = None,
                 container_image: str = None) -> subprocess.Popen:
    """Start a worker process (reference: python/ray/_private/workers/
    default_worker.py is the reference's equivalent entrypoint).

    ``container_image``: launch the worker INSIDE this OCI image via the
    node's container runtime (reference: runtime_env/image_uri.py; here
    ray_tpu/runtime_env/container.py builds the podman/docker argv)."""
    worker_id = WorkerID.from_random()
    _lifecycle_events.append(
        {
            "ts": time.time(),
            "kind": "worker",
            "id": worker_id.hex(),
            "state": "SPAWNED",
            "node": node_id.hex()[:12],
        }
    )
    # Workers may run TPU compute tasks — keep the TPU hook unless the
    # session is pinned to CPU (tests).
    env = child_env(needs_tpu=os.environ.get("JAX_PLATFORMS", "") != "cpu")
    env.update(
        RAY_TPU_CONTROLLER=controller_addr,
        RAY_TPU_NODE_ID=node_id.hex(),
        RAY_TPU_WORKER_ID=worker_id.hex(),
        RAY_TPU_SHM_DIR=shm_dir,
        RAY_TPU_SESSION_DIR=session_dir,
        # Log-to-driver streaming tails the redirected stdout file; block
        # buffering would hold prints back until process exit.
        PYTHONUNBUFFERED="1",
    )
    if extra_env:
        env.update(extra_env)
    cmd = [sys.executable, "-m", "ray_tpu.core.worker_main"]
    if container_image:
        # wrap_command embeds the (cached) image pull in the spawned
        # shell — spawn_worker itself never blocks on a registry (it is
        # called from the controller/agent event loop).
        from ray_tpu.runtime_env import container as _container

        cmd = _container.wrap_command(container_image, cmd, env, session_dir, shm_dir)
    log_dir = os.path.join(session_dir, "logs")
    os.makedirs(log_dir, exist_ok=True)
    # O_APPEND ("ab") is load-bearing: the worker size-caps this file
    # in-process by copy-truncate rotation (core/log_plane.py — rename
    # would chase this inherited fd), and append-mode writes land at the
    # new EOF after a truncate instead of leaving a sparse hole.
    out = open(os.path.join(log_dir, f"worker-{worker_id.hex()[:8]}.log"), "ab")
    proc = subprocess.Popen(
        cmd,
        env=env,
        stdout=out,
        stderr=subprocess.STDOUT,
        start_new_session=False,
    )
    _children[proc.pid] = proc
    return proc


def reap_children():
    for pid, proc in list(_children.items()):
        if proc.poll() is not None:
            _children.pop(pid, None)


def kill_children():
    for proc in _children.values():
        try:
            proc.terminate()
        except Exception:
            pass


class _DirectWorker:
    """One spawned direct-pool worker in the agent's free-worker view."""

    __slots__ = ("wid", "addr", "env_hash", "busy", "peer")

    def __init__(self, wid: str, addr: str, peer=None):
        self.wid = wid
        self.addr = addr
        self.env_hash = ""
        self.busy = False
        self.peer = peer  # the worker's attach connection (exit channel)


class NodeAgent:
    def __init__(self, controller_addr: str, session_dir: str, resources: Dict[str, float], capacity: int):
        self.controller_addr = controller_addr
        self.session_dir = session_dir
        self.resources = resources
        self.node_id = NodeID.from_random()
        self.store = PlasmaStore(session_dir, capacity, name=self.node_id.hex()[:8])
        self._exit = asyncio.Event()
        self._controller_peer = None
        from ray_tpu.core.object_transfer import ChunkReader, FetchPeerCache

        self._fetch_peers = FetchPeerCache()
        self._chunk_reader = ChunkReader(self.store)
        self._chunk_bytes = 8 * 1024 * 1024
        # Single-writer agent state (asyncio-loop discipline, same as the
        # controller's maps): OWNER_THREAD guards make it ConcSan-checked.
        self._inflight_pulls: Dict = GuardedDict(
            OWNER_THREAD, owner=self, name="inflight_pulls"
        )  # oid -> InflightPull (broadcast hops)
        # Direct-lease worker pool: THE AGENT owns this node's free-worker
        # view (reference: the raylet's WorkerPool, worker_pool.h:174); the
        # controller only places leases onto the node.
        import collections

        self._direct: Dict[str, _DirectWorker] = GuardedDict(
            OWNER_THREAD, owner=self, name="direct"
        )
        self._direct_waiters: "collections.deque" = collections.deque()
        self._direct_starting = 0
        self._direct_spawns: list = []  # Popen handles not yet attached
        self._lease_workers: Dict[bytes, str] = GuardedDict(
            OWNER_THREAD, owner=self, name="lease_workers"
        )  # lease_id -> worker id
        # rpc_lease_worker grants in flight, and leases released while
        # their grant was still in flight (bounded: only grants currently
        # executing can enter _released_leases; the grant's finally
        # clears both).
        self._granting: set = GuardedSet(
            OWNER_THREAD, owner=self, name="granting"
        )
        self._released_leases: set = GuardedSet(
            OWNER_THREAD, owner=self, name="released_leases"
        )
        ncpu = int(resources.get("CPU", 1))
        self._max_direct = max(4 * max(ncpu, 1), 16)
        self._listen_addr = ""  # set in run()
        # Push-fed local cluster view (round 17, core/pubsub.py): the
        # controller streams per-node availability deltas and avoid/
        # drain state instead of the agent polling per decision. Mirror
        # stats ride the telemetry heartbeat; self-avoid transitions are
        # logged for operators.
        from ray_tpu.core.pubsub import ResourceViewMirror

        self.resource_mirror = ResourceViewMirror()
        self._avoid_view: Dict = {"avoid": {}, "draining": []}
        self._self_avoided = False

    # -- notifications from the controller ------------------------------
    def rpc_pubsub_msg(self, peer, channel: str, message):
        """Topic-bus push (round 17): resource deltas/snapshots feed the
        local mirror; avoid/drain snapshots update the avoid view. Both
        are at-most-once pushes — the periodic reconcile snapshot is
        what guarantees convergence (see core/pubsub.py)."""
        from ray_tpu.core import pubsub as _ps

        if channel == _ps.RESOURCES_CHANNEL:
            self.resource_mirror.ingest(message)
            self._note_self_avoid()
        elif channel == _ps.AVOID_CHANNEL:
            if isinstance(message, dict) and message.get("snapshot"):
                self._avoid_view = {
                    "avoid": message.get("avoid", {}),
                    "draining": message.get("draining", []),
                }
                self._note_self_avoid()

    def _note_self_avoid(self):
        """Log transitions of THIS node's avoid/drain standing (pushed,
        not polled — the operator sees quarantine land in the agent log
        within one broadcast interval)."""
        me = self.node_id.hex()
        view = self.resource_mirror.nodes.get(me) or {}
        avoided = bool(
            view.get("avoid")
            or view.get("draining")
            or me in self._avoid_view.get("avoid", {})
            or me in self._avoid_view.get("draining", [])
        )
        if avoided != self._self_avoided:
            self._self_avoided = avoided
            if avoided:
                logger.warning(
                    "this node is now avoided/draining (pushed via topic "
                    "bus) — existing leases keep running; no new placements"
                )
            else:
                logger.warning("this node's avoid/drain standing cleared")

    def rpc_resource_view(self, peer):
        """The agent's push-fed mirror, for tests and `ray-tpu` debug
        tooling (equivalence vs. the controller's authoritative view)."""
        return {
            "nodes": self.resource_mirror.nodes,
            "applied": self.resource_mirror.applied,
            "stale": self.resource_mirror.stale,
            "reconciles": self.resource_mirror.reconciles,
            "avoid_view": self._avoid_view,
        }

    def rpc_start_workers(self, peer, n: int, container_image: str = None,
                          preset_env_hash: str = ""):
        extra = {"RAY_TPU_PRESET_ENV_HASH": preset_env_hash} if preset_env_hash else None
        for _ in range(n):
            spawn_worker(self.session_dir, self.controller_addr, self.node_id,
                         self.store.shm_dir, extra_env=extra,
                         container_image=container_image)
        # Ship SPAWNED promptly: the worker's REGISTERED hits the
        # controller directly, and the spawn half must arrive first for
        # the startup dwell to pair (the telemetry loop is the backstop).
        asyncio.ensure_future(self._flush_lifecycle_events())

    async def _flush_lifecycle_events(self):
        peer = self._controller_peer
        if peer is None or peer.closed:
            return  # not connected yet: leave events queued for the backstop
        batch = []
        while _lifecycle_events:
            batch.append(_lifecycle_events.popleft())
        if not batch:
            return
        try:
            await peer.notify("task_events", batch)
        except Exception as e:  # noqa: BLE001 — transient controller hiccup
            # Re-queue for the telemetry backstop if there's room (the
            # deque is bounded; a full queue drops this batch rather than
            # displacing newer spawn events).
            if (_lifecycle_events.maxlen or 0) - len(_lifecycle_events) >= len(batch):
                _lifecycle_events.extendleft(reversed(batch))
            logger.debug("lifecycle event ship failed: %s", e)

    def rpc_delete_object(self, peer, oid: ObjectID):
        self._chunk_reader.invalidate(oid)
        self.store.delete(oid)

    def rpc_adopt_object(self, peer, oid: ObjectID, size: int):
        self.store.adopt(oid, size)

    def rpc_ensure_local(self, peer, oid: ObjectID) -> bool:
        return self.store.ensure_local(oid)

    # -- object data plane (reference: object_manager.cc Push/Pull) -----
    async def rpc_fetch_chunk(self, peer, oid: ObjectID, offset: int, length: int):
        delay = getattr(self, "_config", {}).get("chaos_fetch_delay_ms", 0)
        if delay:
            await asyncio.sleep(delay / 1000.0)  # fault injection (tests)
        # Raw: the chunk crosses as an out-of-band frame (no pickle copy)
        ip = self._inflight_pulls.get(oid)
        if ip is not None:
            # mid-broadcast hop: serve from the in-progress buffer once
            # the contiguous watermark covers the range
            await ip.wait_for(offset + length)
            ip = self._inflight_pulls.get(oid)
            if ip is not None and ip.view is not None:
                return rpc.Raw(ip.read(offset, length))
        return rpc.Raw(self._chunk_reader.read(oid, offset, length))

    async def rpc_pull_object(self, peer, oid: ObjectID, size: int, src_addr: str) -> bool:
        """Pull a remote object into this node's store, chunked over the
        network (reference: PullManager → ObjectBufferPool chunk
        reassembly). ``src_addr`` is another agent's listener, or
        "controller" for head-node objects (fetched over the existing
        controller connection)."""
        from ray_tpu.core.object_transfer import pull_into_store

        src_peer = await self._peer_for(src_addr)
        return await pull_into_store(self.store, oid, size, src_peer, self._chunk_bytes)

    async def _peer_for(self, addr: str) -> rpc.Peer:
        if addr == "controller":
            return self._controller_peer
        p = await self._fetch_peers.get(addr)
        if p is None:
            raise ConnectionError(f"cannot reach source agent at {addr}")
        return p

    async def rpc_pull_chain(self, peer, oid: ObjectID, size: int, src_addr: str,
                             next_addrs: list) -> bool:
        """One hop of a 1→N broadcast chain (reference: push_manager.h —
        the reference rate-limits a fan-out push; a pipelined CHAIN moves
        1 GiB to N nodes in ~1 transfer time because every link runs at
        full bandwidth concurrently, each hop forwarding chunks as its
        contiguous watermark grows). Kicks the downstream hop FIRST so it
        pulls from this node's in-progress buffer, then pulls from
        upstream; resolves when this hop AND everything downstream hold
        the object."""
        from ray_tpu.core.object_transfer import InflightPull, fetch_into, pull_into_store

        already = self.store.contains(oid) and self.store.ensure_local(oid)
        # Register the inflight entry BEFORE the downstream hop is kicked:
        # the downstream's first fetch_chunk can arrive before our own
        # upstream pull has created the buffer, and must park on the
        # watermark instead of hitting a store miss in ChunkReader.
        entry = None
        if next_addrs and not already:
            entry = InflightPull(None, size)
            self._inflight_pulls[oid] = entry
        down_fut = None
        ok = True
        try:
            if next_addrs:
                nxt = await self._fetch_peers.get(next_addrs[0])
                if nxt is None:
                    raise ConnectionError(f"cannot reach next hop {next_addrs[0]}")
                down_fut = asyncio.ensure_future(
                    nxt.call("pull_chain", oid, size, self._listen_addr, next_addrs[1:])
                )
            if already:
                pass  # already local: just relay
            else:
                src_peer = await self._peer_for(src_addr)
                try:
                    buf = self.store.create(oid, size)
                except FileExistsError:
                    # concurrent regular pull owns the slot — wait for it
                    ok = await pull_into_store(
                        self.store, oid, size, src_peer, self._chunk_bytes
                    )
                    buf = None
                    # unpark downstream readers: the object is now stored
                    # (or the pull failed) — they re-check the store.
                    # Always settle OUR entry (a concurrent chain for the
                    # same oid may have overwritten the dict slot; its
                    # readers are parked on a different entry), and pop
                    # the slot only if it is still ours.
                    if entry is not None:
                        if self._inflight_pulls.get(oid) is entry:
                            self._inflight_pulls.pop(oid, None)
                        if ok:
                            entry.advance(size)
                        else:
                            entry.fail()
                        entry = None
                if buf is not None:
                    view = buf.view()
                    if entry is None:
                        entry = InflightPull(view, size)
                        if oid not in self._inflight_pulls:
                            self._inflight_pulls[oid] = entry
                    else:
                        entry.view = view
                    err = await fetch_into(
                        src_peer, oid, size, view, self._chunk_bytes,
                        progress=entry.advance,
                    )
                    # No awaits between here and seal/cleanup: readers on
                    # this loop can't observe the intermediate states.
                    entry.view = None
                    del view
                    buf.close()
                    if self._inflight_pulls.get(oid) is entry:
                        self._inflight_pulls.pop(oid, None)
                    if err is not None:
                        entry.fail()
                        self.store.delete(oid)
                        raise err
                    self.store.seal(oid)
                    entry.advance(size)
                if ok:
                    # register the new replica so the controller's object
                    # directory (and broadcast completion) sees it
                    await self._controller_peer.notify(
                        "object_sealed", oid, size, self.node_id
                    )
        except Exception:
            if entry is not None:
                if self._inflight_pulls.get(oid) is entry:
                    self._inflight_pulls.pop(oid, None)
                entry.fail()
            if down_fut is not None:
                down_fut.cancel()
            raise
        if down_fut is not None:
            ok_down = await down_fut
            return bool(ok) and bool(ok_down)
        return bool(ok)

    # -- direct-lease worker pool (reference: WorkerPool::PopWorker) ----
    def rpc_worker_attach(self, peer, worker_id_hex: str, listen_addr: str):
        """A direct-pool worker this agent spawned announces itself."""
        self._direct_starting = max(0, self._direct_starting - 1)
        if self._direct_spawns:
            self._direct_spawns.pop(0)  # count-based pairing with spawns
        w = _DirectWorker(worker_id_hex, listen_addr, peer)
        self._direct[worker_id_hex] = w
        peer.meta["direct_wid"] = worker_id_hex
        self._hand_to_waiter(w)

    def _hand_to_waiter(self, w: _DirectWorker) -> bool:
        for i, (ehash, _lid, fut) in enumerate(self._direct_waiters):
            if not fut.done() and w.env_hash in ("", ehash):
                del self._direct_waiters[i]
                w.busy = True
                w.env_hash = ehash or w.env_hash
                fut.set_result(w)
                return True
        return False

    def _pop_free(self, ehash: str):
        fallback = None
        for w in self._direct.values():
            if w.busy:
                continue
            if w.env_hash == ehash:
                return w
            if w.env_hash == "" and fallback is None:
                fallback = w
        return fallback

    def rpc_claim_direct_worker(self, peer, ehash: str):
        """Controller claims a free pooled worker for ACTOR CREATION
        (reference: PopWorker serves actors too, worker_pool.h:363-374).
        Non-blocking: None when the pool has nothing compatible — the
        controller falls back to its spawn path."""
        w = self._pop_free(ehash)
        if w is None:
            return None
        w.busy = True
        w.env_hash = ehash or w.env_hash
        return w.wid

    def rpc_release_direct_worker(self, peer, wid: str):
        """Undo an actor claim that never dispatched (scheduling race)."""
        w = self._direct.get(wid)
        if w is not None:
            w.busy = False
            self._hand_to_waiter(w)

    async def rpc_lease_worker(self, peer, lease_id: bytes, ehash: str):
        """Hand out (or spawn) a worker for a controller-granted lease.
        The controller reserved the lease's resources; this side only
        manages processes (reference: LocalTaskManager dispatch popping
        from the WorkerPool, local_task_manager.cc:122)."""
        lid = bytes(lease_id)
        self._granting.add(lid)
        try:
            w = self._pop_free(ehash)
            if w is None:
                if len(self._direct) + self._direct_starting < self._max_direct:
                    self._spawn_direct()
                else:
                    self._retire_mismatched(ehash)
                fut = asyncio.get_running_loop().create_future()
                self._direct_waiters.append((ehash, lid, fut))
                w = await fut
            else:
                w.busy = True
                w.env_hash = ehash or w.env_hash
            # The await races lease_release: the caller's 30s lease RPC may
            # have timed out (controller relayed the release before any
            # binding existed). Binding the worker to the dead lease would
            # strand it busy forever — pool it instead.
            if lid in self._released_leases:
                w.busy = False
                self._hand_to_waiter(w)
                raise ConnectionError(
                    f"lease {lid!r} released while waiting for a worker"
                )
            # lease→worker binding lets the CONTROLLER free this worker when
            # the lease-holder dies without ever sending lease_return (its
            # disconnect cleanup relays rpc_lease_release here)
            self._lease_workers[lid] = w.wid
            return {"worker_addr": w.addr, "worker_id": w.wid}
        finally:
            self._granting.discard(lid)
            self._released_leases.discard(lid)

    def rpc_lease_worker_batch(self, peer, lease_ids: list, ehash: str):
        """Hand out workers for a BATCH of controller-granted leases in
        one round-trip (round 17). Strictly non-blocking: no await
        between pop and bind, so the lease-release race rpc_lease_worker
        parks against cannot happen here. Misses return None in place —
        the caller falls back to the parking single-worker path for
        those — and each miss triggers one spawn/retire so pool capacity
        catches up with the window."""
        out = []
        misses = 0
        for lease_id in lease_ids:
            lid = bytes(lease_id)
            if lid in self._released_leases:
                self._released_leases.discard(lid)
                out.append(None)
                continue
            w = self._pop_free(ehash)
            if w is None:
                out.append(None)
                misses += 1
                continue
            w.busy = True
            w.env_hash = ehash or w.env_hash
            self._lease_workers[lid] = w.wid
            out.append({"worker_addr": w.addr, "worker_id": w.wid})
        for _ in range(misses):
            if len(self._direct) + self._direct_starting < self._max_direct:
                self._spawn_direct()
            else:
                self._retire_mismatched(ehash)
        return out

    def _spawn_direct(self):
        self._direct_starting += 1
        proc = spawn_worker(
            self.session_dir, self.controller_addr, self.node_id,
            self.store.shm_dir,
            extra_env={
                "RAY_TPU_WORKER_POOL": "direct",
                "RAY_TPU_AGENT_ADDR": self._listen_addr,
            },
        )
        self._direct_spawns.append(proc)
        asyncio.ensure_future(self._flush_lifecycle_events())

    def _reap_direct_spawns(self):
        """A direct worker that died BEFORE attaching (import error, OOM)
        must not inflate _direct_starting forever — that would wedge the
        pool at a phantom cap with every waiter parked. Count-based: the
        spawn list length mirrors _direct_starting; attach pops one."""
        dead = [p for p in self._direct_spawns if p.poll() is not None]
        for p in dead:
            self._direct_spawns.remove(p)
            self._direct_starting = max(0, self._direct_starting - 1)
        if dead and self._direct_waiters:
            # retry the spawn the dead process was supposed to satisfy
            if len(self._direct) + self._direct_starting < self._max_direct:
                self._spawn_direct()

    def _retire_mismatched(self, ehash: str):
        """Pool at cap with no usable free worker: retire one free worker
        locked to a different env so a pristine replacement can spawn."""
        for wid, w in list(self._direct.items()):
            if not w.busy and w.env_hash and w.env_hash != ehash:
                self._direct.pop(wid, None)
                if w.peer is not None and not w.peer.closed:
                    asyncio.ensure_future(w.peer.notify("exit"))
                self._spawn_direct()
                return

    def rpc_lease_return(self, peer, worker_id_hex: str, lease_id: bytes = None):
        if lease_id is not None:
            self._lease_workers.pop(bytes(lease_id), None)
        w = self._direct.get(worker_id_hex)
        if w is None:
            return
        w.busy = False
        self._hand_to_waiter(w)

    def rpc_lease_release(self, peer, lease_id: bytes, kill_worker: bool = False):
        """Controller relay on lease-holder death: reclaim the bound
        worker (idempotent vs. a caller's own lease_return, which pops
        the binding first). With ``kill_worker`` the worker may be
        mid-task on an orphaned push — exit it rather than pooling a
        busy worker."""
        lid = bytes(lease_id)
        wid = self._lease_workers.pop(lid, None)
        if wid is None:
            # The caller may still be parked in rpc_lease_worker (its
            # lease RPC timed out): fail the waiter so a later worker
            # never binds to the dead lease, or — if the hand-off already
            # happened but the binding hasn't been written — flag the
            # lease so the grant path pools the worker instead.
            for i, (_ehash, wlid, fut) in enumerate(self._direct_waiters):
                if wlid == lid:
                    del self._direct_waiters[i]
                    if not fut.done():
                        fut.set_exception(
                            ConnectionError("lease released while parked")
                        )
                    return
            if lid in self._granting:
                self._released_leases.add(lid)
            return
        w = self._direct.get(wid)
        if w is None:
            return
        if kill_worker:
            self._direct.pop(wid, None)
            if w.peer is not None and not w.peer.closed:
                asyncio.ensure_future(w.peer.notify("exit"))
            # parked lease_worker callers must not hang on the shrunken
            # pool — pair the pop with a replacement spawn (same contract
            # as _retire_mismatched)
            if self._direct_waiters and (
                len(self._direct) + self._direct_starting < self._max_direct
            ):
                self._spawn_direct()
            return
        w.busy = False
        self._hand_to_waiter(w)

    def rpc_exit(self, peer):
        self._exit.set()

    def rpc_ping(self, peer):
        return "pong"

    def rpc_stack_dump(self, peer):
        from ray_tpu.utils.stack_dump import dump_all_threads

        return dump_all_threads()

    def rpc_dump_stacks(self, peer):
        from ray_tpu.util import profiling

        return profiling.dump_stacks()

    def rpc_profile_cpu(self, peer, duration_s: float = 10.0, hz: float = 100.0):
        from ray_tpu.util import profiling

        return profiling.sample_async(duration_s, hz)

    def rpc_spill_store(self, peer, fraction: float = 0.6):
        """Health-plane pressure actuator: proactively spill this node's
        store down to ``fraction`` of capacity (both tiers). Runs off-loop
        — a large arena drain copies bytes and must not stall heartbeats."""
        return asyncio.to_thread(self.store.spill_to_fraction, fraction)

    def rpc_dump_memory(self, peer, limit: int = 1000):
        """This node's store leg of the memory census fan-out: live
        store stats (occupancy, spill-dir bytes, pins, deferred deletes)
        plus per-object rows for tier attribution."""
        return {
            "kind": "store",
            "node_id": self.node_id.hex(),
            "store": self.store.stats(),
            "objects": self.store.object_rows(limit),
        }

    # -- log plane fan-out legs (core/log_plane.py; reference: the
    # dashboard agent's per-node logs grpc service) ---------------------
    def _log_dir(self) -> str:
        return os.path.join(self.session_dir, "logs")

    # File I/O runs off-loop (to_thread): a grep over sidecars near the
    # 64 MB rotation cap must not stall the agent's control channel —
    # heartbeats, worker RPCs, and spawns share this event loop.
    async def rpc_list_logs(self, peer):
        from ray_tpu.core import log_plane

        files = await asyncio.to_thread(log_plane.list_local, self._log_dir())
        return {"node_id": self.node_id.hex(), "files": files}

    async def rpc_get_log(self, peer, filename: str, tail: int = 1000):
        from ray_tpu.core import log_plane

        return await asyncio.to_thread(
            log_plane.read_local, self._log_dir(), filename, tail
        )

    async def rpc_search_logs(self, peer, **filters):
        from ray_tpu.core import log_plane

        return await asyncio.to_thread(
            log_plane.search_local, self._log_dir(), **filters
        )

    def rpc_install_fault_plan(self, peer, plan_json: str):
        """Install (or clear, empty string) a deterministic fault plan in
        THIS agent process at runtime — the slow-node throttle lever
        (`chaos.install_plan_on_node` via the controller fan-out)."""
        from ray_tpu.util import chaos

        chaos.install_fault_plan(plan_json or None)
        return True

    def on_disconnect(self, peer):
        wid = peer.meta.get("direct_wid")
        if wid is not None:
            self._direct.pop(wid, None)  # direct-pool worker died
            return
        # Only the controller connection is load-bearing; fetch peers
        # (other agents pulling from us) come and go.
        if peer is self._controller_peer or self._controller_peer is None:
            window = float(
                getattr(self, "_config", {}).get("controller_reconnect_window_s", 0.0)
            )
            if window <= 0:
                self._exit.set()
            else:
                asyncio.ensure_future(self._reconnect_controller(window))

    async def _reconnect_controller(self, window: float):
        """Bounded jittered-backoff reconnect + re-register after the
        controller connection dropped (rides through a controller
        restart; a controller that is truly gone still exits this agent,
        one window later). Workers this agent spawned reconnect on their
        own — their records re-form controller-side as they re-register."""
        import random as _random

        host, port = self.controller_addr.rsplit(":", 1)
        # monotonic: a wall-clock step (NTP) must not stretch or collapse
        # the reconnect window
        deadline = time.monotonic() + window
        wait = 0.1
        while time.monotonic() < deadline and not self._exit.is_set():
            try:
                peer = await rpc.connect(host, int(port), self, retries=1)
                await self._register(peer)
                self._controller_peer = peer
                logger.warning("reconnected to controller at %s", self.controller_addr)
                return
            except Exception as e:  # noqa: BLE001 — retry within the window
                if "re-registration refused" in str(e):
                    # Permanent: the live controller declared this node
                    # dead while we were away — burning the rest of the
                    # window on identical refusals helps nobody.
                    logger.error("controller refused re-registration: %s", e)
                    break
                logger.debug("controller reconnect attempt failed: %s", e)
                await asyncio.sleep(min(wait * (0.5 + _random.random()),
                                        max(0.0, deadline - time.monotonic())))
                wait = min(wait * 1.7, 2.0)
        logger.error("controller gone for %.0fs — agent exiting", window)
        self._exit.set()

    async def _register(self, peer: rpc.Peer):
        """Register (or RE-register after a controller restart) this node
        on ``peer`` and absorb the returned cluster config."""
        import socket

        from ray_tpu.utils.net import host_ip

        chunk_fallback = self._chunk_bytes
        labels = {}
        raw_labels = os.environ.get("RAY_TPU_NODE_LABELS", "")
        if raw_labels:
            try:
                parsed = json.loads(raw_labels)
            except ValueError:
                parsed = None
            # must be a str→str dict: dict(['ab']) would silently fabricate
            # phantom labels and non-dict JSON would fail registration
            if isinstance(parsed, dict) and all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in parsed.items()
            ):
                labels = parsed
            else:
                logger.warning(
                    "RAY_TPU_NODE_LABELS must be a JSON object of string "
                    "values, got %r — ignoring", raw_labels,
                )
        info = await peer.call(
            "register_node", self.node_id, self.resources, self.store.shm_dir,
            hostname=socket.gethostname(), pid=os.getpid(),
            fetch_addr=self._listen_addr,
            provider_instance_id=os.environ.get("RAY_TPU_PROVIDER_INSTANCE_ID", ""),
            labels=labels,
        )
        cfg = (info or {}).get("config") or {}
        self._chunk_bytes = int(cfg.get("object_transfer_chunk_bytes", chunk_fallback))
        self._config = cfg
        # Join the push-fed resource/avoid channels (round 17). Runs on
        # every (re-)register, so a controller restart re-subscribes and
        # the first snapshot re-seeds the mirror. Best-effort: an old
        # controller without the bus just leaves the mirror empty.
        try:
            from ray_tpu.core import pubsub as _ps

            await peer.call("subscribe", _ps.RESOURCES_CHANNEL)
            await peer.call("subscribe", _ps.AVOID_CHANNEL)
        except Exception as e:  # noqa: BLE001 — mirror is observability
            logger.debug("resource pubsub subscribe failed: %s", e)

    async def run(self):
        from ray_tpu.utils.net import bind_host, host_ip

        host, port = self.controller_addr.rsplit(":", 1)
        # Listener for sibling agents pulling object chunks (reference:
        # the ObjectManagerService gRPC server every node runs).
        # Loopback unless RAY_TPU_NODE_IP opts this host into multi-host.
        _server, fetch_port = await rpc.serve(self, bind_host(), 0)
        self._listen_addr = f"{host_ip()}:{fetch_port}"
        peer = await rpc.connect(host, int(port), self)
        await self._register(peer)
        self._controller_peer = peer
        cfg = self._config
        from ray_tpu.util import profiling

        profiling.ensure_continuous(
            hz=float(cfg.get("profiling_continuous_hz", 0.0)),
            ring_s=float(cfg.get("profiling_ring_s", 60.0)),
        )
        if cfg.get("log_structured", True):
            # Agent leg of the log plane: its own logging records become
            # a structured sidecar (handler-only — the agent's streams
            # are the session's agent-*.log already); ERROR records ship
            # with the telemetry heartbeat.
            from ray_tpu.core import log_plane

            log_plane.install(
                self.session_dir,
                node_id=self.node_id.hex(),
                proc=f"agent-{self.node_id.hex()[:8]}",
                capture_streams=False,
                rotate_bytes=int(cfg.get("log_rotate_bytes", 64 << 20)),
            )
        monitor_task = asyncio.get_running_loop().create_task(
            self._memory_monitor_loop()
        )
        telemetry_task = asyncio.get_running_loop().create_task(
            self._telemetry_loop()
        )
        try:
            while not self._exit.is_set():
                reap_children()
                self._reap_direct_spawns()
                try:
                    await asyncio.wait_for(self._exit.wait(), timeout=1.0)
                except asyncio.TimeoutError:
                    pass
        finally:
            monitor_task.cancel()
            telemetry_task.cancel()
            kill_children()
            self._chunk_reader.close()
            self.store.destroy()

    async def _telemetry_loop(self):
        """Periodic node telemetry heartbeat: host CPU/mem (cgroup-aware),
        object-store occupancy, and worker counts, shipped to the
        controller (reference: the raylet's ReportResourceUsage heartbeat
        + the dashboard reporter agent's host stats). Also drains this
        process's metric registry — the agent has no CoreWorker, so the
        normal metrics flusher can't reach the controller for it (the
        object-transfer histograms recorded here ride this loop)."""
        interval_ms = int(self._config.get("node_telemetry_interval_ms", 2000))
        if interval_ms <= 0:
            return
        from ray_tpu.core import node_telemetry
        from ray_tpu.core.memory_monitor import HostCpuSampler
        from ray_tpu.util import metrics as _metrics

        cpu = HostCpuSampler()
        cpu.sample()  # prime the delta
        while not self._exit.is_set():
            await asyncio.sleep(interval_ms / 1000.0)
            await self._flush_lifecycle_events()
            sample = node_telemetry.build_node_sample(cpu, self.store)
            sample["num_direct_workers"] = len(self._direct)
            sample["num_children"] = len(_children)
            sample["resource_mirror"] = {
                "nodes": len(self.resource_mirror.nodes),
                "applied": self.resource_mirror.applied,
                "stale": self.resource_mirror.stale,
                "reconciles": self.resource_mirror.reconciles,
            }
            records = _metrics.drain_records()
            from ray_tpu.core import log_plane as _lp

            errors = _lp.drain_ship()
            try:
                await self._controller_peer.notify(
                    "node_telemetry", self.node_id, sample
                )
                if records:
                    await self._controller_peer.notify("metrics_report", records)
                if errors:
                    await self._controller_peer.notify("log_errors", errors)
            except Exception as e:  # noqa: BLE001 — transient controller hiccup
                if self._exit.is_set():
                    return
                _metrics.requeue_records(records)
                _lp.requeue_ship(errors)
                if self._controller_peer.closed:
                    # reconnect in progress (on_disconnect) — keep ticking
                    # so heartbeats resume on the fresh peer; _exit ends
                    # us if the reconnect window runs out.
                    continue
                logger.warning("telemetry report failed: %s", e)

    async def _memory_monitor_loop(self):
        """Per-node OOM monitoring (reference: every raylet runs its own
        MemoryMonitor). Multi-host only — on single-host simulations all
        'nodes' see the same host memory and the head's monitor covers
        it; per-agent monitors there would mass-fire on one host spike."""
        from ray_tpu.utils.net import multihost_enabled

        if not multihost_enabled():
            return
        refresh_ms = int(self._config.get("memory_monitor_refresh_ms", 250))
        if refresh_ms <= 0:
            return
        from ray_tpu.core.memory_monitor import MemoryMonitor

        monitor = MemoryMonitor(
            threshold=float(self._config.get("memory_usage_threshold", 0.95))
        )
        while not self._exit.is_set():
            await asyncio.sleep(refresh_ms / 1000.0)
            if not monitor.should_kill():
                continue
            try:
                # victim choice needs task/actor context → the controller
                pid = await self._controller_peer.call(
                    "node_over_memory", self.node_id
                )
            except Exception as e:  # noqa: BLE001
                if self._controller_peer.closed or self._exit.is_set():
                    return  # controller gone; agent is exiting anyway
                # transient/remote error: OOM protection must SURVIVE it
                logger.warning("node_over_memory report failed: %s", e)
                continue
            if pid:
                try:
                    os.kill(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass


def main():
    from ray_tpu.util import chaos, lockwatch

    lockwatch.maybe_install()  # RAY_TPU_LOCKWATCH=1: watch locks created from here on
    chaos.install_fault_plan_from_env()  # RAY_TPU_FAULT_PLAN: deterministic chaos
    parser = argparse.ArgumentParser()
    parser.add_argument("--controller", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--store-capacity", type=int, default=1 << 30)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO, format="[node_agent] %(levelname)s %(message)s")
    agent = NodeAgent(args.controller, args.session_dir, json.loads(args.resources), args.store_capacity)

    loop = asyncio.new_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, agent._exit.set)
    try:
        loop.run_until_complete(agent.run())
    finally:
        loop.close()


if __name__ == "__main__":
    main()
