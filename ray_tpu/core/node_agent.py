"""Node agent: per-node daemon for non-head nodes.

Reference: the raylet (src/ray/raylet/main.cc, node_manager.cc) minus
scheduling (which is GCS-direct in this design — see controller.py): it
registers the node's resources, hosts the node's shared-memory store, and
spawns/kills worker processes on request (reference: worker_pool.cc:438
``StartWorkerProcess``).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import subprocess
import sys
from typing import Dict

from ray_tpu.core.object_store import PlasmaStore
from ray_tpu.utils import rpc
from ray_tpu.utils.ids import NodeID, ObjectID, WorkerID

logger = logging.getLogger("ray_tpu.node_agent")

_children: Dict[int, subprocess.Popen] = {}


def child_env(needs_tpu: bool) -> dict:
    """Environment for spawned processes.

    The host image hooks TPU runtime registration into every interpreter via
    sitecustomize (costing ~2s of jax import per process). Control-plane
    processes never touch jax, and CPU-mode workers don't need the TPU hook,
    so strip the trigger var for them — worker spawn drops from ~2.3s to
    ~0.4s.
    """
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    if not needs_tpu:
        env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def spawn_worker(session_dir: str, controller_addr: str, node_id: NodeID, shm_dir: str) -> subprocess.Popen:
    """Start a worker process (reference: python/ray/_private/workers/
    default_worker.py is the reference's equivalent entrypoint)."""
    worker_id = WorkerID.from_random()
    # Workers may run TPU compute tasks — keep the TPU hook unless the
    # session is pinned to CPU (tests).
    env = child_env(needs_tpu=os.environ.get("JAX_PLATFORMS", "") != "cpu")
    env.update(
        RAY_TPU_CONTROLLER=controller_addr,
        RAY_TPU_NODE_ID=node_id.hex(),
        RAY_TPU_WORKER_ID=worker_id.hex(),
        RAY_TPU_SHM_DIR=shm_dir,
        RAY_TPU_SESSION_DIR=session_dir,
        # Log-to-driver streaming tails the redirected stdout file; block
        # buffering would hold prints back until process exit.
        PYTHONUNBUFFERED="1",
    )
    log_dir = os.path.join(session_dir, "logs")
    os.makedirs(log_dir, exist_ok=True)
    out = open(os.path.join(log_dir, f"worker-{worker_id.hex()[:8]}.log"), "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.worker_main"],
        env=env,
        stdout=out,
        stderr=subprocess.STDOUT,
        start_new_session=False,
    )
    _children[proc.pid] = proc
    return proc


def reap_children():
    for pid, proc in list(_children.items()):
        if proc.poll() is not None:
            _children.pop(pid, None)


def kill_children():
    for proc in _children.values():
        try:
            proc.terminate()
        except Exception:
            pass


class NodeAgent:
    def __init__(self, controller_addr: str, session_dir: str, resources: Dict[str, float], capacity: int):
        self.controller_addr = controller_addr
        self.session_dir = session_dir
        self.resources = resources
        self.node_id = NodeID.from_random()
        self.store = PlasmaStore(session_dir, capacity, name=self.node_id.hex()[:8])
        self._exit = asyncio.Event()
        self._controller_peer = None
        from ray_tpu.core.object_transfer import ChunkReader, FetchPeerCache

        self._fetch_peers = FetchPeerCache()
        self._chunk_reader = ChunkReader(self.store)
        self._chunk_bytes = 8 * 1024 * 1024

    # -- notifications from the controller ------------------------------
    def rpc_start_workers(self, peer, n: int):
        for _ in range(n):
            spawn_worker(self.session_dir, self.controller_addr, self.node_id, self.store.shm_dir)

    def rpc_delete_object(self, peer, oid: ObjectID):
        self._chunk_reader.invalidate(oid)
        self.store.delete(oid)

    def rpc_adopt_object(self, peer, oid: ObjectID, size: int):
        self.store.adopt(oid, size)

    def rpc_ensure_local(self, peer, oid: ObjectID) -> bool:
        return self.store.ensure_local(oid)

    # -- object data plane (reference: object_manager.cc Push/Pull) -----
    def rpc_fetch_chunk(self, peer, oid: ObjectID, offset: int, length: int):
        # Raw: the chunk crosses as an out-of-band frame (no pickle copy)
        return rpc.Raw(self._chunk_reader.read(oid, offset, length))

    async def rpc_pull_object(self, peer, oid: ObjectID, size: int, src_addr: str) -> bool:
        """Pull a remote object into this node's store, chunked over the
        network (reference: PullManager → ObjectBufferPool chunk
        reassembly). ``src_addr`` is another agent's listener, or
        "controller" for head-node objects (fetched over the existing
        controller connection)."""
        from ray_tpu.core.object_transfer import pull_into_store

        src_peer = await self._peer_for(src_addr)
        return await pull_into_store(self.store, oid, size, src_peer, self._chunk_bytes)

    async def _peer_for(self, addr: str) -> rpc.Peer:
        if addr == "controller":
            return self._controller_peer
        p = await self._fetch_peers.get(addr)
        if p is None:
            raise ConnectionError(f"cannot reach source agent at {addr}")
        return p

    def rpc_exit(self, peer):
        self._exit.set()

    def rpc_ping(self, peer):
        return "pong"

    def rpc_stack_dump(self, peer):
        from ray_tpu.utils.stack_dump import dump_all_threads

        return dump_all_threads()

    def on_disconnect(self, peer):
        # Only the controller connection is load-bearing; fetch peers
        # (other agents pulling from us) come and go.
        if peer is self._controller_peer or self._controller_peer is None:
            self._exit.set()

    async def run(self):
        from ray_tpu.utils.net import bind_host, host_ip

        host, port = self.controller_addr.rsplit(":", 1)
        # Listener for sibling agents pulling object chunks (reference:
        # the ObjectManagerService gRPC server every node runs).
        # Loopback unless RAY_TPU_NODE_IP opts this host into multi-host.
        _server, fetch_port = await rpc.serve(self, bind_host(), 0)
        peer = await rpc.connect(host, int(port), self)
        self._controller_peer = peer
        config = self._chunk_bytes
        import socket

        info = await peer.call(
            "register_node", self.node_id, self.resources, self.store.shm_dir,
            hostname=socket.gethostname(), pid=os.getpid(),
            fetch_addr=f"{host_ip()}:{fetch_port}",
            provider_instance_id=os.environ.get("RAY_TPU_PROVIDER_INSTANCE_ID", ""),
        )
        cfg = (info or {}).get("config") or {}
        self._chunk_bytes = int(cfg.get("object_transfer_chunk_bytes", config))
        self._config = cfg
        monitor_task = asyncio.get_running_loop().create_task(
            self._memory_monitor_loop()
        )
        try:
            while not self._exit.is_set():
                reap_children()
                try:
                    await asyncio.wait_for(self._exit.wait(), timeout=1.0)
                except asyncio.TimeoutError:
                    pass
        finally:
            monitor_task.cancel()
            kill_children()
            self._chunk_reader.close()
            self.store.destroy()

    async def _memory_monitor_loop(self):
        """Per-node OOM monitoring (reference: every raylet runs its own
        MemoryMonitor). Multi-host only — on single-host simulations all
        'nodes' see the same host memory and the head's monitor covers
        it; per-agent monitors there would mass-fire on one host spike."""
        from ray_tpu.utils.net import multihost_enabled

        if not multihost_enabled():
            return
        refresh_ms = int(self._config.get("memory_monitor_refresh_ms", 250))
        if refresh_ms <= 0:
            return
        from ray_tpu.core.memory_monitor import MemoryMonitor

        monitor = MemoryMonitor(
            threshold=float(self._config.get("memory_usage_threshold", 0.95))
        )
        while not self._exit.is_set():
            await asyncio.sleep(refresh_ms / 1000.0)
            if not monitor.should_kill():
                continue
            try:
                # victim choice needs task/actor context → the controller
                pid = await self._controller_peer.call(
                    "node_over_memory", self.node_id
                )
            except Exception as e:  # noqa: BLE001
                if self._controller_peer.closed or self._exit.is_set():
                    return  # controller gone; agent is exiting anyway
                # transient/remote error: OOM protection must SURVIVE it
                logger.warning("node_over_memory report failed: %s", e)
                continue
            if pid:
                try:
                    os.kill(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--controller", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--store-capacity", type=int, default=1 << 30)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO, format="[node_agent] %(levelname)s %(message)s")
    agent = NodeAgent(args.controller, args.session_dir, json.loads(args.resources), args.store_capacity)

    loop = asyncio.new_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, agent._exit.set)
    try:
        loop.run_until_complete(agent.run())
    finally:
        loop.close()


if __name__ == "__main__":
    main()
