"""Node/host/device telemetry sampling.

Reference: the raylet's NodeManager heartbeats (resources + load) and the
dashboard reporter agent (python/ray/dashboard/modules/reporter/
reporter_agent.py — psutil host stats + per-GPU gauges). TPU twist: HBM
occupancy comes from jax's per-device ``memory_stats()`` (bytes_in_use /
peak_bytes_in_use / bytes_limit), which only the process that owns the
chips can read — so DEVICE samples are taken by workers (shipped via
``device_telemetry``) while HOST samples are taken by each node agent
(shipped inside its telemetry heartbeat) and by the controller for the
head node.

Sampling is deliberately jax-import-free: ``sample_devices`` reads
devices only when jax is ALREADY imported in this process (a control
plane process must never pay the TPU-runtime import, and must never
grab chips it doesn't own).
"""
from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger("ray_tpu.telemetry")


def sample_host(cpu_sampler=None) -> Dict:
    """Host CPU/memory snapshot (cgroup-aware via memory_monitor)."""
    from ray_tpu.core.memory_monitor import system_memory

    used, total = system_memory()
    out = {
        "mem_used_bytes": used,
        "mem_total_bytes": total,
        "cpu_percent": round(100.0 * cpu_sampler.sample(), 2)
        if cpu_sampler is not None
        else 0.0,
    }
    try:
        out["load_1m"] = os.getloadavg()[0]
    except OSError:  # pragma: no cover - non-unix
        out["load_1m"] = 0.0
    return out


def build_node_sample(cpu_sampler, store) -> Dict:
    """The node heartbeat body, shared by the agents' telemetry loop and
    the controller's head-node loop so the two can't drift — only the
    transport differs (agent: notify over its controller connection;
    controller: direct NodeRecord write)."""
    return {
        "host": sample_host(cpu_sampler),
        "object_store": store.stats(),
    }


def sample_devices() -> List[Dict]:
    """Per-device memory stats of THIS process's accelerators.

    Returns [] when jax is not imported here (never triggers the import)
    or when the backend doesn't expose memory_stats (CPU). Rows:
    {id, platform, kind, bytes_in_use, peak_bytes_in_use, bytes_limit}.
    """
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    try:
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — backend not initialized / gone
        return []
    rows = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend without memory_stats
            stats = None
        if not stats:
            continue
        rows.append(
            {
                "id": int(getattr(d, "id", len(rows))),
                "platform": getattr(d, "platform", "unknown"),
                "kind": getattr(d, "device_kind", ""),
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
                ),
                "bytes_limit": int(stats.get("bytes_limit", 0)),
            }
        )
    return rows


def peak_device_hbm_bytes() -> Optional[int]:
    """Max peak_bytes_in_use across local devices (bench reporting);
    None when no device exposes memory stats (CPU backends)."""
    rows = sample_devices()
    if not rows:
        return None
    return max(r["peak_bytes_in_use"] for r in rows)


def peak_device_hbm_gb() -> Optional[float]:
    """peak_device_hbm_bytes in GiB rounded for bench records."""
    peak = peak_device_hbm_bytes()
    return None if peak is None else round(peak / (1 << 30), 2)


class _DeviceGauges:
    """Lazy per-process HBM gauges, flushed by the normal metrics
    pipeline (tags: device id + platform — bounded cardinality; the
    node/process identity rides the controller-side aggregation, not
    Prometheus labels)."""

    def __init__(self):
        from ray_tpu.util.metrics import Gauge

        dk = ("device", "platform")
        self.used = Gauge(
            "tpu_hbm_used_bytes", "Device memory in use (bytes_in_use)", dk
        )
        self.peak = Gauge(
            "tpu_hbm_peak_bytes", "Peak device memory in use", dk
        )
        self.limit = Gauge(
            "tpu_hbm_limit_bytes", "Device memory capacity (bytes_limit)", dk
        )

    def set_from(self, rows: List[Dict]):
        for r in rows:
            tags = {"device": str(r["id"]), "platform": r["platform"]}
            self.used.set(r["bytes_in_use"], tags)
            self.peak.set(r["peak_bytes_in_use"], tags)
            self.limit.set(r["bytes_limit"], tags)


_gauges: Optional[_DeviceGauges] = None


def set_device_gauges(rows: List[Dict]):
    global _gauges
    if not rows:
        return
    if _gauges is None:
        _gauges = _DeviceGauges()
    _gauges.set_from(rows)


def start_process_telemetry(core) -> Optional[threading.Thread]:
    """Worker/driver-side device-telemetry thread: every poll interval,
    sample this process's devices + compile-tracker snapshot and ship
    them to the controller (``device_telemetry``). No-ops cheaply until
    jax is imported; the compile tracker auto-installs at that point so
    workers never need explicit instrumentation."""
    interval = core.config.get("node_telemetry_interval_ms", 2000) / 1000.0
    if interval <= 0:
        return None
    key = f"{core.node_id.hex() if core.node_id else 'head'}/{core.worker_id.hex()[:12]}"

    def loop():
        from ray_tpu.util import compile_tracker

        while True:
            time.sleep(interval)
            if "jax" in sys.modules:
                compile_tracker.maybe_install()
                rows = sample_devices()
                set_device_gauges(rows)
            else:
                rows = []
            # Ship whenever the compile tracker has ANYTHING — jax may be
            # absent while the tracker still carries data (its logging
            # hook fires through jax's pure-Python path, and the health
            # plane's storm actuator needs storms visible either way).
            snap = compile_tracker.snapshot()
            if (
                not rows
                and not snap.get("compiles")
                and not snap.get("functions")
                and not snap.get("active_storms")
            ):
                continue
            payload = {
                "node_id": core.node_id.hex() if core.node_id else None,
                "pid": os.getpid(),
                "mode": core.mode,
                "devices": rows,
                "compile": snap,
            }
            coro = core.peer.call("device_telemetry", key, payload)
            try:
                core.loop_runner.submit(coro)
            except Exception:  # noqa: BLE001 — controller gone; process exits soon
                coro.close()
                return

    t = threading.Thread(target=loop, daemon=True, name="device-telemetry")
    t.start()
    return t
