"""HTTP observability gateway on the controller.

Reference: the dashboard head (python/ray/dashboard/head.py:61) serving the
state API (dashboard/modules/state/state_head.py) and Prometheus metrics
endpoints. This rebuild keeps the head tiny: a stdlib ThreadingHTTPServer
bridging into the controller's asyncio loop.

Routes:
  GET /metrics              Prometheus text exposition of app metrics
  GET /api/v0/<what>        state JSON: nodes|workers|tasks|actors|objects|
                            events|placement_groups|cluster_resources|
                            available_resources|summarize_resources|
                            summarize_lifecycle|summarize_tasks|
                            summarize_objects|lifecycle_events|compile
  GET /api/v0/memory[?limit=&node=]      cluster memory census rollup
  GET /api/v0/object_refs[?limit=&node=] per-object census rows
  GET /api/serve/engine     serve LLM-engine flight-recorder snapshots
  GET /api/v0/profile/stacks[?node=&actor=]   cluster-wide stack dump
  GET /api/v0/profile/cpu[?duration=&hz=&node=]  sampling CPU profile
  GET /api/v0/profile/incidents[/<id>]        incident capture bundles
  GET /healthz              liveness probe
  Job submission REST (reference: dashboard/modules/job/job_head.py):
  POST /api/jobs/           {entrypoint, submission_id?, runtime_env?,
                            metadata?} → {submission_id}
  GET  /api/jobs/           list job infos
  GET  /api/jobs/<id>       job info
  GET  /api/jobs/<id>/logs  {logs}
  POST /api/jobs/<id>/stop  {stopped}
"""
from __future__ import annotations

import asyncio
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_STATE_ROUTES = {
    "nodes": "rpc_list_nodes",
    "workers": "rpc_list_workers",
    "tasks": "rpc_list_tasks",
    "actors": "rpc_list_actors",
    "objects": "rpc_list_objects",
    "events": "rpc_list_events",
    "placement_groups": "rpc_pg_table",
    "cluster_resources": "rpc_cluster_resources",
    "available_resources": "rpc_available_resources",
    "summarize_resources": "rpc_summarize_resources",
    "summarize_lifecycle": "rpc_summarize_lifecycle",
    "summarize_tasks": "rpc_summarize_tasks",
    "summarize_objects": "rpc_summarize_objects",
    # cluster-wide memory census (fan-out; reference: `ray memory` /
    # the dashboard memory view) — ?limit=&node= supported
    "memory": "rpc_summarize_memory",
    "object_refs": "rpc_list_object_refs",
    "lifecycle_events": "rpc_list_lifecycle_events",
    "compile": "rpc_compile_state",
    # error-signature index (cluster log plane; reference: the GCS's
    # error-event aggregation surfaced by the dashboard)
    "summarize_errors": "rpc_summarize_errors",
}

# routes accepting ?limit= (and ?node= where listed below)
_LIMIT_ROUTES = ("tasks", "objects", "events", "memory", "object_refs",
                 "summarize_objects", "summarize_errors")
_NODE_ROUTES = ("memory", "object_refs")


def start_http_gateway(controller, loop: asyncio.AbstractEventLoop, port: int) -> int:
    def call(method_name, _timeout: float = 10, **kwargs):
        coro = getattr(controller, method_name)(None, **kwargs)
        return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=_timeout)

    job_lock = threading.Lock()

    def job_manager():
        """Controller-hosted JobManager (reference: the JobManager lives in
        the dashboard head process). Thread-safe lazy init; the manager
        itself only runs subprocesses + threads, independent of the loop."""
        with job_lock:
            if getattr(controller, "_job_manager", None) is None:
                from ray_tpu.job.manager import JobManager

                controller._job_manager = JobManager(
                    controller.session_dir, f"127.0.0.1:{controller.port}"
                )
            return controller._job_manager

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def _send(self, code: int, body: bytes, ctype: str):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _json(self, obj, code: int = 200):
            self._send(code, json.dumps(obj, default=str).encode(), "application/json")

        def do_POST(self):
            try:
                path = self.path.split("?")[0].rstrip("/")
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length) or b"{}") if length else {}
                if path == "/api/jobs":
                    try:
                        job_id = job_manager().submit(
                            body["entrypoint"],
                            body.get("submission_id"),
                            body.get("runtime_env"),
                            body.get("metadata"),
                        )
                    except ValueError as e:
                        # duplicate submission_id → conflict, not a server fault
                        self._json({"error": str(e)}, 409)
                        return
                    self._json({"submission_id": job_id})
                elif path.startswith("/api/jobs/") and path.endswith("/stop"):
                    job_id = path[len("/api/jobs/") : -len("/stop")]
                    try:
                        self._json({"stopped": job_manager().stop(job_id)})
                    except (KeyError, ValueError):
                        self._json({"error": f"no job {job_id}"}, 404)
                else:
                    self._json({"error": "not found"}, 404)
            except KeyError as e:
                self._json({"error": f"missing field {e}"}, 400)
            except Exception as e:  # noqa: BLE001 — HTTP surface must not crash
                self._json({"error": str(e)}, 500)

        def do_GET(self):
            try:
                path = self.path.split("?")[0].rstrip("/")
                if path == "/healthz":
                    self._send(200, b"ok", "text/plain")
                elif path in ("", "/dashboard"):  # rstrip already folded "/"
                    from ray_tpu.core.dashboard_ui import DASHBOARD_HTML

                    self._send(200, DASHBOARD_HTML.encode(), "text/html; charset=utf-8")
                elif path == "/api/jobs":
                    self._json(job_manager().list_jobs())
                elif path.startswith("/api/jobs/") and path.endswith("/logs"):
                    job_id = path[len("/api/jobs/") : -len("/logs")]
                    try:
                        self._json({"logs": job_manager().get_logs(job_id)})
                    except (KeyError, ValueError):
                        self._json({"error": f"no job {job_id}"}, 404)
                elif path.startswith("/api/jobs/"):
                    job_id = path[len("/api/jobs/") :]
                    try:
                        self._json(job_manager().get_info(job_id))
                    except (KeyError, ValueError):
                        self._json({"error": f"no job {job_id}"}, 404)
                elif path == "/api/profiles":
                    from ray_tpu.util.state import list_profiles

                    self._json(list_profiles(controller.session_dir))
                elif path == "/api/serve/engine":
                    # Engine flight-recorder snapshots pushed by serve
                    # replicas (llm_engine.report_state): occupancy, step
                    # ring tails, recent-request latency breakdowns.
                    self._json(call("rpc_serve_state"))
                elif path == "/api/grafana/dashboard":
                    # Importable Grafana JSON generated from the live
                    # metric registry (reference: dashboard/modules/
                    # metrics/grafana_dashboard_factory.py).
                    from ray_tpu.util.grafana import generate_dashboard

                    self._json(generate_dashboard(call("rpc_metrics_snapshot")))
                elif path == "/profiles":
                    from ray_tpu.core.dashboard_ui import render_profiles_page
                    from ray_tpu.util.state import list_profiles

                    page = render_profiles_page(list_profiles(controller.session_dir))
                    self._send(200, page.encode(), "text/html; charset=utf-8")
                elif path == "/metrics":
                    from ray_tpu.util.metrics import prometheus_text

                    snap = call("rpc_metrics_snapshot")
                    snap = {
                        k: {**v, "series": [(tuple(map(tuple, t)), val) for t, val in v["series"]]}
                        for k, v in snap.items()
                    }
                    self._send(200, prometheus_text(snap).encode(), "text/plain; version=0.0.4")
                elif path.startswith("/api/v0/logs"):
                    # Cluster log plane (reference: the StateHead logs
                    # API): list / fetch / structured search, all fanned
                    # out to the node agents by the controller.
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)

                    def qget(key, cast, default):
                        return cast(q[key][0]) if q.get(key) else default

                    sub = path[len("/api/v0/logs"):].strip("/")
                    if sub == "":
                        self._json(call(
                            "rpc_list_logs", node=qget("node", str, None),
                            _timeout=30,
                        ))
                    elif sub == "file":
                        name = qget("name", str, None)
                        if not name:
                            self._json({"error": "missing ?name="}, 400)
                            return
                        try:
                            text = call(
                                "rpc_get_log", filename=name,
                                tail=qget("tail", int, 1000),
                                node=qget("node", str, None), _timeout=30,
                            )
                        except FileNotFoundError:
                            self._json({"error": f"no log {name}"}, 404)
                            return
                        except ValueError as e:
                            self._json({"error": str(e)}, 400)
                            return
                        self._json({"filename": name, "text": text})
                    elif sub == "search":
                        self._json(call(
                            "rpc_search_logs",
                            pattern=qget("pattern", str, None) or qget("grep", str, None),
                            severity=qget("severity", str, None),
                            task=qget("task", str, None),
                            actor=qget("actor", str, None),
                            node=qget("node", str, None),
                            since=qget("since", float, None),
                            until=qget("until", float, None),
                            limit=qget("limit", int, 1000),
                            _timeout=30,
                        ))
                    else:
                        self._json({"error": "unknown logs route"}, 404)
                elif path.startswith("/api/v0/profile"):
                    # On-demand profiling routes (each handler runs on a
                    # gateway thread; only /cpu blocks, for its duration).
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)

                    def qget(key, cast, default):
                        return cast(q[key][0]) if q.get(key) else default

                    sub = path[len("/api/v0/profile"):].strip("/")
                    if sub == "stacks":
                        self._json(call(
                            "rpc_profile_stacks",
                            node=qget("node", str, None),
                            actor=qget("actor", str, None),
                            _timeout=30,
                        ))
                    elif sub == "cpu":
                        duration = qget("duration", float, 5.0)
                        self._json(call(
                            "rpc_profile_cpu_all",
                            duration_s=duration,
                            hz=qget("hz", float, None),
                            node=qget("node", str, None),
                            _timeout=duration + 30,
                        ))
                    elif sub == "incidents":
                        self._json(call("rpc_profile_incidents"))
                    elif sub.startswith("incidents/"):
                        iid = sub[len("incidents/"):]
                        try:
                            self._json(call("rpc_get_incident", incident_id=iid))
                        except FileNotFoundError:
                            self._json({"error": f"no incident {iid}"}, 404)
                    else:
                        self._json({"error": "unknown profile route"}, 404)
                elif path.startswith("/api/v0/"):
                    what = path[len("/api/v0/") :]
                    method = _STATE_ROUTES.get(what)
                    if method is None:
                        self._send(404, b'{"error": "unknown resource"}', "application/json")
                        return
                    kwargs = {}
                    if "?" in self.path:
                        from urllib.parse import parse_qs, urlsplit

                        q = parse_qs(urlsplit(self.path).query)
                        if q.get("limit") and what in _LIMIT_ROUTES:
                            kwargs["limit"] = int(q["limit"][0])
                        if q.get("node") and what in _NODE_ROUTES:
                            kwargs["node"] = q["node"][0]
                    # the memory census fans out to every process — give
                    # it the profile-route timeout, not the default 10s
                    timeout = 30 if what in _NODE_ROUTES else 10
                    data = call(method, _timeout=timeout, **kwargs)
                    self._send(200, json.dumps(data, default=str).encode(), "application/json")
                else:
                    self._send(404, b"not found", "text/plain")
            except Exception as e:  # noqa: BLE001 — HTTP surface must not crash
                self._send(500, str(e).encode(), "text/plain")

    server = ThreadingHTTPServer(("127.0.0.1", max(port, 0)), Handler)
    threading.Thread(target=server.serve_forever, daemon=True, name="http-gateway").start()
    return server.server_address[1]
