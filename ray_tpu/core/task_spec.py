"""Task and actor specifications — the unit the scheduler moves around.

Reference: src/ray/common/task/task_spec.cc (TaskSpecification) and
src/ray/protobuf/common.proto (TaskSpec message). We keep a plain dataclass;
the function payload travels by value the first time and is cached by its
digest on each node afterwards (the reference exports functions through the
GCS KV — python/ray/_private/worker.py function table).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.resources import ResourceSet
from ray_tpu.utils.ids import ActorID, ObjectID, PlacementGroupID, TaskID, WorkerID


class TaskType(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2


@dataclass
class SchedulingStrategy:
    """Union of the reference's strategies (reference:
    python/ray/util/scheduling_strategies.py): default hybrid, spread,
    node-affinity, PG, node-label."""

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP
    node_id: Optional[str] = None
    soft: bool = False
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False
    node_labels: Optional[Dict[str, Any]] = None


@dataclass
class TaskSpec:
    task_id: TaskID
    task_type: TaskType
    name: str
    # Digest of the serialized function / actor class for per-node caching.
    func_digest: bytes
    # Serialized function (may be None if receiver already cached it).
    func_blob: Optional[bytes]
    # Serialized (args, kwargs) with ObjectID placeholders for ref args.
    args_blob: bytes
    # ObjectIDs this task depends on (must be local before dispatch).
    dependencies: List[ObjectID]
    num_returns: int
    resources: ResourceSet
    owner_id: WorkerID
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    max_retries: int = 3
    retry_exceptions: bool = False
    # Actor fields
    actor_id: Optional[ActorID] = None
    actor_method_name: Optional[str] = None
    actor_seq_no: int = 0
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    # Runtime env (env vars only in v0; reference has full plugin system).
    runtime_env: Optional[dict] = None
    # Actor lifetime: None (owner-scoped) or "detached" — detached actors
    # survive their creator and are journaled for controller-restart
    # recovery (reference: actor.py lifetime="detached" + GCS FT restore).
    lifetime: Optional[str] = None
    # Actor creation: hold the acquired resources until the actor dies
    # (reference semantics: explicitly-requested actor resources are held
    # for the actor's lifetime; the default 1 CPU is scheduling-only and
    # released once __init__ completes — python/ray/actor.py).
    hold_resources_while_alive: bool = False

    # num_returns == -1 ⇒ streaming generator (reference: num_returns=
    # "streaming", _raylet.pyx:1077 streaming generator returns).
    STREAMING = -1

    @property
    def is_streaming(self) -> bool:
        return self.num_returns == TaskSpec.STREAMING

    def return_ids(self) -> List[ObjectID]:
        if self.is_streaming:
            return []
        return [ObjectID.for_task_return(self.task_id, i) for i in range(self.num_returns)]

    def scheduling_class(self) -> Tuple:
        """Tasks with equal scheduling class share lease requests (reference:
        normal_task_submitter.h:40 SchedulingKey)."""
        return (
            tuple(sorted(self.resources.items_fp())),
            self.scheduling_strategy.kind,
            self.scheduling_strategy.node_id,
            str(self.scheduling_strategy.placement_group_id),
            self.func_digest,
        )
