"""Task and actor specifications — the unit the scheduler moves around.

Reference: src/ray/common/task/task_spec.cc (TaskSpecification) and
src/ray/protobuf/common.proto (TaskSpec message). We keep a plain dataclass;
the function payload travels by value the first time and is cached by its
digest on each node afterwards (the reference exports functions through the
GCS KV — python/ray/_private/worker.py function table).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.resources import ResourceSet
from ray_tpu.utils.ids import ActorID, ObjectID, PlacementGroupID, TaskID, WorkerID


class TaskType(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2


@dataclass
class SchedulingStrategy:
    """Union of the reference's strategies (reference:
    python/ray/util/scheduling_strategies.py): default hybrid, spread,
    node-affinity, PG, node-label."""

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP
    node_id: Optional[str] = None
    soft: bool = False
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False
    node_labels: Optional[Dict[str, Any]] = None

    def __reduce__(self):
        return (SchedulingStrategy, (
            self.kind, self.node_id, self.soft, self.placement_group_id,
            self.bundle_index, self.capture_child_tasks, self.node_labels,
        ))


@dataclass
class TaskSpec:
    task_id: TaskID
    task_type: TaskType
    name: str
    # Digest of the serialized function / actor class for per-node caching.
    func_digest: bytes
    # Serialized function (may be None if receiver already cached it).
    func_blob: Optional[bytes]
    # Serialized (args, kwargs) with ObjectID placeholders for ref args.
    args_blob: bytes
    # ObjectIDs this task depends on (must be local before dispatch).
    dependencies: List[ObjectID]
    num_returns: int
    resources: ResourceSet
    owner_id: WorkerID
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    max_retries: int = 3
    retry_exceptions: bool = False
    # Actor fields
    actor_id: Optional[ActorID] = None
    actor_method_name: Optional[str] = None
    actor_seq_no: int = 0
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    # Named concurrency groups (reference:
    # core_worker/transport/concurrency_group_manager.h:34): on actor
    # creation, {group_name: max_concurrency}; on actor tasks, the group
    # this call routes to (None → the method's declared group, else the
    # default pool).
    concurrency_groups: Optional[dict] = None
    concurrency_group: Optional[str] = None
    # Runtime env (env vars only in v0; reference has full plugin system).
    runtime_env: Optional[dict] = None
    # Actor lifetime: None (owner-scoped) or "detached" — detached actors
    # survive their creator and are journaled for controller-restart
    # recovery (reference: actor.py lifetime="detached" + GCS FT restore).
    lifetime: Optional[str] = None
    # Actor creation: hold the acquired resources until the actor dies
    # (reference semantics: explicitly-requested actor resources are held
    # for the actor's lifetime; the default 1 CPU is scheduling-only and
    # released once __init__ completes — python/ray/actor.py).
    hold_resources_while_alive: bool = False

    # num_returns == -1 ⇒ streaming generator (reference: num_returns=
    # "streaming", _raylet.pyx:1077 streaming generator returns).
    STREAMING = -1

    @property
    def is_streaming(self) -> bool:
        return self.num_returns == TaskSpec.STREAMING

    def return_ids(self) -> List[ObjectID]:
        # Memoized: the blake2b derivations are hot on the direct call
        # path (computed caller-side and worker-side several times each).
        cached = getattr(self, "_return_ids", None)
        if cached is not None:
            return cached
        if self.is_streaming:
            ids: List[ObjectID] = []
        else:
            ids = [ObjectID.for_task_return(self.task_id, i) for i in range(self.num_returns)]
        object.__setattr__(self, "_return_ids", ids)
        return ids

    # Compact tuple state: generic dataclass pickling (dict state, 20 keys
    # as strings) costs ~3x more time and bytes — specs are the hottest
    # wire object in the system.
    _FIELDS = (
        "task_id", "task_type", "name", "func_digest", "func_blob",
        "args_blob", "dependencies", "num_returns", "resources", "owner_id",
        "scheduling_strategy", "max_retries", "retry_exceptions", "actor_id",
        "actor_method_name", "actor_seq_no", "max_restarts",
        "max_task_retries", "max_concurrency", "runtime_env", "lifetime",
        "hold_resources_while_alive", "concurrency_groups",
        "concurrency_group",
    )
    # Defaults for trailing fields absent from tuples written by older
    # builds (journal replay across upgrades).
    _TAIL_DEFAULTS = {"concurrency_groups": None, "concurrency_group": None}

    def __getstate__(self):
        return tuple(getattr(self, f) for f in TaskSpec._FIELDS)

    def __setstate__(self, state):
        if isinstance(state, dict):  # journals written pre-tuple-state
            self.__dict__.update(state)
            for f, v in TaskSpec._TAIL_DEFAULTS.items():
                self.__dict__.setdefault(f, v)
            self.__dict__.pop("_return_ids", None)
            return
        for i, f in enumerate(TaskSpec._FIELDS):
            v = state[i] if i < len(state) else TaskSpec._TAIL_DEFAULTS[f]
            object.__setattr__(self, f, v)

    def scheduling_class(self) -> Tuple:
        """Tasks with equal scheduling class share lease requests (reference:
        normal_task_submitter.h:40 SchedulingKey). bundle_index matters:
        PG tasks pinned to different bundles translate to different group
        resources, so they must not share leases."""
        st = self.scheduling_strategy
        labels_key = None
        if st.node_labels:
            # canonical tuple form: label-different tasks must not share
            # leases (placement differs even when resources match)
            labels_key = tuple(
                (kind, tuple(sorted(
                    (k, op, tuple(sorted(vals)))
                    for k, (op, vals) in exprs.items()
                )))
                for kind, exprs in sorted(st.node_labels.items())
                if exprs
            )
        return (
            tuple(sorted(self.resources.items_fp())),
            st.kind,
            st.node_id,
            st.soft,
            str(st.placement_group_id),
            st.bundle_index,
            labels_key,
            self.func_digest,
        )


_EMPTY_RESOURCES = ResourceSet()


def pack_actor_task(spec: TaskSpec) -> tuple:
    """Flatten an actor-task spec to primitives for the direct push path —
    a plain tuple pickles ~5x faster and ~4x smaller than the full spec
    (every byte/μs here is per-call overhead; reference analogue: the
    PushTask proto carries a trimmed TaskSpec)."""
    return (
        spec.task_id.binary(),
        spec.actor_id.binary(),
        spec.name,
        spec.actor_method_name,
        spec.func_digest,
        spec.func_blob,
        spec.args_blob,
        spec.num_returns,
        spec.runtime_env,
        spec.actor_seq_no,
        spec.owner_id.binary() if spec.owner_id else None,
        spec.concurrency_group,
    )


def unpack_actor_task(t: tuple) -> TaskSpec:
    return TaskSpec(
        task_id=TaskID(t[0]),
        task_type=TaskType.ACTOR_TASK,
        name=t[2],
        func_digest=t[4],
        func_blob=t[5],
        args_blob=t[6],
        dependencies=[],
        num_returns=t[7],
        resources=_EMPTY_RESOURCES,
        owner_id=WorkerID(t[10]) if t[10] else None,
        actor_id=ActorID(t[1]),
        actor_method_name=t[3],
        actor_seq_no=t[9],
        runtime_env=t[8],
        concurrency_group=t[11] if len(t) > 11 else None,
    )


def pack_normal_task(spec: TaskSpec) -> tuple:
    """Trimmed wire form for the direct normal-task push (reference:
    PushTask carries a trimmed TaskSpec). Resources AND the scheduling
    strategy travel so lineage reconstruction (controller resubmit of
    shm results, rpc_task_lineage) can reschedule a PG-pinned or
    node-affinity task with its original placement; DEFAULT strategies
    (the common case) encode as None to keep the tuple cheap."""
    st = spec.scheduling_strategy
    packed_st = None
    if st.kind != "DEFAULT" or st.node_labels:
        packed_st = (
            st.kind, st.node_id, st.soft,
            st.placement_group_id.binary() if st.placement_group_id else None,
            st.bundle_index, st.node_labels,
        )
    return (
        spec.task_id.binary(),
        spec.name,
        spec.func_digest,
        spec.func_blob,
        spec.args_blob,
        spec.num_returns,
        spec.runtime_env,
        spec.owner_id.binary() if spec.owner_id else None,
        [d.binary() for d in spec.dependencies],
        tuple(spec.resources.items_fp()),
        spec.max_retries,
        packed_st,
        spec.retry_exceptions,
    )


def unpack_normal_task(t: tuple) -> TaskSpec:
    packed_st = t[11] if len(t) > 11 else None
    if packed_st is not None:
        strategy = SchedulingStrategy(
            kind=packed_st[0], node_id=packed_st[1], soft=packed_st[2],
            placement_group_id=(
                PlacementGroupID(packed_st[3]) if packed_st[3] else None
            ),
            bundle_index=packed_st[4], node_labels=packed_st[5],
        )
    else:
        strategy = SchedulingStrategy()
    return TaskSpec(
        task_id=TaskID(t[0]),
        task_type=TaskType.NORMAL_TASK,
        name=t[1],
        func_digest=t[2],
        func_blob=t[3],
        args_blob=t[4],
        dependencies=[ObjectID(d) for d in t[8]],
        num_returns=t[5],
        resources=ResourceSet(dict(t[9])) if t[9] else _EMPTY_RESOURCES,
        owner_id=WorkerID(t[7]) if t[7] else None,
        runtime_env=t[6],
        max_retries=t[10],
        scheduling_strategy=strategy,
        retry_exceptions=t[12] if len(t) > 12 else False,
    )
