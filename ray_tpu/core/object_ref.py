"""ObjectRef: the distributed future handed back by task submission / put.

Reference: python/ray/includes/object_ref.pxi + ownership in
src/ray/core_worker/reference_count.cc. Distributed ref counting: every
ObjectRef construction/destruction in a worker process updates a local
ref table (the reference's AddLocalReference/RemoveLocalReference,
reference_count.h:142); deserializing a ref in another process registers
that process as a *borrower* the same way — the zero-crossings are
batch-flushed to the controller, which frees objects nobody references
(see controller._gc_sweep).
"""
from __future__ import annotations

import contextvars
from typing import Optional

from ray_tpu.utils.ids import ObjectID

# Process-global local-ref tracker, installed by CoreWorker on connect
# (None inside the controller and before init).
_tracker = None

# Active capture list: while serializing a value, every ObjectRef pickled
# into it records its id here — how nested/contained refs become pins
# (reference: the borrowing protocol's "contained in owned object" edges).
_capture: contextvars.ContextVar[Optional[list]] = contextvars.ContextVar(
    "ray_tpu_ref_capture", default=None
)


def set_ref_tracker(tracker) -> None:
    global _tracker
    _tracker = tracker


class ObjectRef:
    __slots__ = ("id", "__weakref__")

    def __init__(self, oid: ObjectID):
        self.id = oid
        t = _tracker
        if t is not None:
            t.inc(oid)

    def __del__(self):
        t = _tracker
        if t is not None:
            try:
                t.dec(self.id)
            except Exception:  # interpreter teardown
                pass

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        lst = _capture.get()
        if lst is not None:
            lst.append(self.id)
        return (ObjectRef, (self.id,))

    def call_site(self) -> str:
        """The creation call-site the memory census recorded for this ref
        (``file.py:line:func`` for puts, ``(task) <name>`` for task
        returns; ``""`` for borrowed refs or with the census disabled).
        Reference: ``ObjectRef.call_site()`` backed by the reference
        counter's per-ref call_site string."""
        t = _tracker
        return t.site_of(self.id.binary()) if t is not None else ""

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        from ray_tpu.core.api import _require_worker

        return _require_worker().get_async([self])


class ObjectRefGenerator:
    """Iterator over a streaming task's return refs, yielding each ref as
    the producer yields (reference: _raylet.pyx:1077/:1206 streaming
    generators + ObjectRefGenerator in python/ray/_raylet.pyx).

    next() blocks until the producer has yielded the next item (or the
    stream ends → StopIteration). Works from the driver or inside tasks.
    """

    def __init__(self, task_id):
        self.task_id = task_id
        self._index = 0
        # Optional per-item wait bound (seconds); None blocks until the
        # producer yields. Consumers (e.g. serve streaming) set this so a
        # stalled generator cannot hang them forever.
        self.timeout = None

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        from ray_tpu.core.api import _require_worker

        status = _require_worker()._call(
            "stream_next", self.task_id, self._index, timeout=self.timeout
        )
        if status is None:
            raise StopIteration
        ref = ObjectRef(ObjectID.for_task_return(self.task_id, self._index))
        self._index += 1
        return ref

    def __reduce__(self):
        return (_rebuild_generator, (self.task_id, self._index))


def _rebuild_generator(task_id, index):
    g = ObjectRefGenerator(task_id)
    g._index = index
    return g


class _RefMarker:
    """Placeholder substituted for top-level ObjectRef args in a task's
    serialized arguments; the executing worker replaces it with the
    fetched value (reference: LocalDependencyResolver,
    src/ray/core_worker/transport/dependency_resolver.cc)."""

    __slots__ = ("oid",)

    def __init__(self, oid: ObjectID):
        self.oid = oid

    def __reduce__(self):
        return (_RefMarker, (self.oid,))
