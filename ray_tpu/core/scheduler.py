"""Cluster scheduling: policies + resource bookkeeping.

Reference: src/ray/raylet/scheduling/ — ``ClusterResourceScheduler``
(cluster_resource_scheduler.cc) picks nodes with pluggable policies
(policy/hybrid_scheduling_policy.h:50, scheduling_policy.h), and placement
groups reserve bundle resources through a 2-phase prepare/commit
(placement_group_resource_manager.h:44-84).

Architectural difference from the reference: scheduling here is
GCS-direct — the controller holds the authoritative resource view and
assigns leases itself (the reference supports this mode too:
gcs_actor_scheduler.cc:60 ``ScheduleByGcs``). Raylet-side spillover
scheduling can be reintroduced when nodes own their local view.
"""
from __future__ import annotations

import collections
import heapq
import itertools
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.config import get_config
from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.core.task_spec import SchedulingStrategy
from ray_tpu.util.guards import OWNER_THREAD, GuardedDict, GuardedSet
from ray_tpu.utils.ids import NodeID, PlacementGroupID

logger = logging.getLogger(__name__)

_sched_metrics: Optional[Dict[str, object]] = None


def _get_sched_metrics() -> Dict[str, object]:
    """Process-wide metric singletons (a scheduler re-created in tests
    must not duplicate series)."""
    global _sched_metrics
    if _sched_metrics is None:
        from ray_tpu.util.metrics import Counter

        _sched_metrics = {
            "fast": Counter(
                "scheduler_fast_path_total",
                "Placement decisions served by an O(1) path "
                "(native core or the demand-shape index)",
                ("strategy",),
            ),
            "full": Counter(
                "scheduler_full_scan_total",
                "Placement decisions that rescanned every node "
                "(label/affinity/PG strategies, exclude filters, cold shapes)",
            ),
        }
    return _sched_metrics


@dataclass
class ScheduleResult:
    node_id: Optional[NodeID]
    infeasible: bool = False  # no node could EVER run this → autoscaler hint
    # Why-pending attribution for a None placement (bounded vocabulary,
    # core/lifecycle.py PENDING_REASONS): "infeasible" vs
    # "insufficient_resources"; the pump layers pool/PG context on top.
    reason: Optional[str] = None


def _none_reason(node_id, infeasible: bool) -> Optional[str]:
    """Attribution for a native-core placement miss (the C++ core reports
    only the infeasible bit)."""
    if node_id is not None:
        return None
    return "infeasible" if infeasible else "insufficient_resources"


def match_label_expressions(exprs: Optional[Dict], labels: Dict[str, str]) -> bool:
    """Evaluate wire-form label expressions ({key: (op, values)}) against
    a node's labels (reference: util/scheduling_strategies.py:94-115
    In/NotIn/Exists/DoesNotExist)."""
    for key, (op, values) in (exprs or {}).items():
        present = key in labels
        val = labels.get(key)
        if op == "in":
            if not present or val not in values:
                return False
        elif op == "not_in":
            if present and val in values:
                return False
        elif op == "exists":
            if not present:
                return False
        elif op == "does_not_exist":
            if present:
                return False
        else:
            raise ValueError(f"unknown label operator {op!r}")
    return True


@dataclass
class _ShapeEntry:
    """Feasibility bucket for one demand shape (round 17, O(1) hot path).

    ``fits`` is the live set of nodes whose availability satisfies the
    shape RIGHT NOW, maintained incrementally by capacity-change
    callbacks; ``heap`` is a lazy-deleted min-heap over (pack-order
    position, node) of (a superset of) that set, so the hybrid policy's
    pack-first pick is a heap peek instead of a cluster rescan.
    Duplicate heap entries after a node leaves and re-enters ``fits``
    are harmless — membership in ``fits`` is the truth, stale tops are
    popped on peek. Any topology/drain/avoid change invalidates the
    whole cache (rare); only capacity changes are tracked per node.
    """

    demand: ResourceSet
    pos: Dict[NodeID, int] = field(default_factory=dict)
    fits: Set[NodeID] = field(default_factory=set)
    feasible: Set[NodeID] = field(default_factory=set)
    heap: List[Tuple[int, NodeID]] = field(default_factory=list)


class ClusterState:
    """Authoritative view of node resources (reference:
    ClusterResourceManager, cluster_resource_data.h).

    When the native toolchain is available the C++ scheduling core
    (ray_tpu/native/src/sched.cc) holds a write-through mirror and makes
    the hybrid/spread placement decisions over dense fixed-point arrays —
    the reference keeps this exact layer in C++ for the same reason.
    """

    def __init__(self):
        # Controller-loop single-writer state (no locks by design):
        # GuardedDict/GuardedSet give the ConcSan witness thread-affinity
        # checks when RAY_TPU_CONCSAN=1 and cost nothing otherwise.
        self.nodes: Dict[NodeID, NodeResources] = GuardedDict(
            OWNER_THREAD, owner=self, name="nodes"
        )
        # Stable ordering for deterministic pack behavior.
        self._order: List[NodeID] = []
        self._spread_rr = itertools.count()
        # Health-plane avoid set: node -> [monotonic deadline, hard].
        # hard = quarantine (drain semantics: no new placements at all),
        # soft = admission throttle (node moves to the back of the
        # placement order so other nodes absorb new work first). Expiry
        # is pruned lazily on read and by the health tick.
        self._avoid: Dict[NodeID, list] = GuardedDict(
            OWNER_THREAD, owner=self, name="avoid"
        )
        # Demand-shape feasibility index (round 17): shape key -> live
        # fits/feasible sets + pack-order heap, LRU-bounded. See
        # _ShapeEntry. Kept coherent by NodeResources watcher callbacks
        # (capacity) and wholesale invalidation (topology/drain/avoid).
        self._shape_cache: "collections.OrderedDict[tuple, _ShapeEntry]" = (
            collections.OrderedDict()
        )
        self._shape_cache_cap = 128
        # Nodes whose availability changed since the last resource-delta
        # broadcast (core/pubsub.py RESOURCES_CHANNEL) — the controller's
        # coalesced publisher drains this.
        self.dirty_nodes: Set[NodeID] = GuardedSet(
            OWNER_THREAD, owner=self, name="dirty_nodes"
        )
        self.native = None
        if not get_config().disable_native_sched:
            try:
                from ray_tpu.native import sched as _nsched

                if _nsched.available():
                    self.native = _nsched.NativeSched()
            except Exception:
                # available() already covers the no-toolchain case, so an
                # exception here is a real regression — say so instead of
                # silently dropping to the Python policy path.
                logger.warning("native scheduling core failed to load", exc_info=True)
                self.native = None

    def add_node(self, node_id: NodeID, resources: NodeResources):
        self.nodes[node_id] = resources
        if node_id not in self._order:  # re-registration keeps pack order
            self._order.append(node_id)
        if self.native is not None:
            self.native.add_node(node_id, resources.total.items_fp())
            resources.bind_native(self.native, node_id)
        resources.bind_watcher(self, node_id)
        self._invalidate_shapes()
        self.dirty_nodes.add(node_id)

    def remove_node(self, node_id: NodeID):
        res = self.nodes.pop(node_id, None)
        if res is not None:
            res.bind_native(None, None)
            res.bind_watcher(None, None)
        self._order = [n for n in self._order if n != node_id]
        self._avoid.pop(node_id, None)
        if self.native is not None:
            self.native.remove_node(node_id)
        self._invalidate_shapes()
        self.dirty_nodes.add(node_id)

    def set_draining(self, node_id: NodeID, draining: bool = True):
        """Graceful drain (reference: NodeManager drain / rpc::DrainNode):
        a draining node keeps its accounting (running work still releases
        correctly) but receives no new placements."""
        res = self.nodes.get(node_id)
        if res is not None:
            res.draining = draining
        if self.native is not None:
            self.native.set_draining(node_id, draining)
        self._invalidate_shapes()
        self.dirty_nodes.add(node_id)

    # -- health-plane avoids (core/health.py actuators) -----------------
    def set_avoid(self, node_id: NodeID, duration_s: float,
                  hard: bool = False) -> bool:
        """Quarantine (hard) or admission-throttle (soft) a node for
        ``duration_s``. Hard avoids mirror into the native core as
        draining so the C++ fast path honors them; the node's OWN
        ``draining`` flag (user drains) is never touched — an expiring
        quarantine must not un-drain a node the operator drained."""
        import time as _time

        res = self.nodes.get(node_id)
        if res is None:
            return False
        prev = self._avoid.get(node_id)
        self._avoid[node_id] = [_time.monotonic() + float(duration_s), bool(hard)]
        self._invalidate_shapes()
        if hard and self.native is not None and not res.draining:
            self.native.set_draining(node_id, True)
        elif not hard and prev is not None and prev[1]:
            # Downgrade hard -> soft: release the native drain mirror.
            if self.native is not None and not res.draining:
                self.native.set_draining(node_id, False)
        return True

    def clear_avoid(self, node_id: NodeID):
        entry = self._avoid.pop(node_id, None)
        if entry is None:
            return
        self._invalidate_shapes()
        res = self.nodes.get(node_id)
        if (
            entry[1]
            and self.native is not None
            and res is not None
            and not res.draining
        ):
            self.native.set_draining(node_id, False)

    def prune_avoids(self):
        import time as _time

        now = _time.monotonic()
        for nid in [n for n, (dl, _h) in self._avoid.items() if dl <= now]:
            self.clear_avoid(nid)

    def avoids(self) -> Dict[NodeID, tuple]:
        self.prune_avoids()
        return {n: (dl, h) for n, (dl, h) in self._avoid.items()}

    def soft_avoid_active(self) -> bool:
        if not self._avoid:
            return False
        self.prune_avoids()
        return any(not h for _dl, h in self._avoid.values())

    def ordered_nodes(self) -> List[NodeID]:
        if self._avoid:
            self.prune_avoids()
        front: List[NodeID] = []
        back: List[NodeID] = []
        for n in self._order:
            if n not in self.nodes or getattr(self.nodes[n], "draining", False):
                continue
            entry = self._avoid.get(n)
            if entry is None:
                front.append(n)
            elif entry[1]:
                continue  # quarantined: no new placements at all
            else:
                back.append(n)  # throttled: last resort only
        return front + back

    # -- demand-shape feasibility index (round 17) ----------------------
    def _invalidate_shapes(self):
        if self._shape_cache:
            self._shape_cache.clear()

    def note_capacity_changed(self, node_id: NodeID):
        """NodeResources watcher callback: availability (or capacity —
        PG commits add renamed group resources via add_total) changed on
        ``node_id``. O(#cached shapes) set/heap maintenance, never a
        cluster scan."""
        self.dirty_nodes.add(node_id)
        if not self._shape_cache:
            return
        nr = self.nodes.get(node_id)
        if nr is None:
            return
        for e in self._shape_cache.values():
            pos = e.pos.get(node_id)
            if pos is None:
                continue
            if nr.is_feasible(e.demand):
                e.feasible.add(node_id)
            else:
                e.feasible.discard(node_id)
            if nr.available.fits(e.demand):
                if node_id not in e.fits:
                    e.fits.add(node_id)
                    heapq.heappush(e.heap, (pos, node_id))
            else:
                e.fits.discard(node_id)

    def shape_entry(self, demand: ResourceSet) -> _ShapeEntry:
        """The feasibility bucket for ``demand``'s shape, building it
        with ONE full scan on first sight (amortized away across every
        later decision for the same shape)."""
        key = tuple(sorted(demand.items_fp()))
        e = self._shape_cache.get(key)
        if e is not None:
            self._shape_cache.move_to_end(key)
            return e
        e = _ShapeEntry(demand=ResourceSet(dict(demand.items_fp())))
        for i, nid in enumerate(self.ordered_nodes()):
            e.pos[nid] = i
            nr = self.nodes[nid]
            if nr.is_feasible(demand):
                e.feasible.add(nid)
                if nr.available.fits(demand):
                    e.fits.add(nid)
                    heapq.heappush(e.heap, (i, nid))
        while len(self._shape_cache) >= self._shape_cache_cap:
            self._shape_cache.popitem(last=False)
        self._shape_cache[key] = e
        return e


class ClusterResourceScheduler:
    def __init__(self, state: ClusterState):
        self.state = state
        self._spread_idx = 0
        # Fast-path vs full-scan decision accounting. Plain ints on the
        # decision path (a Counter.inc costs ~10us — the very overhead
        # the fast path removes); drain_counters() bulk-flushes into the
        # cluster metrics from the telemetry sweep.
        self._fast_counts: Dict[str, int] = {}
        self._full_scans = 0

    def _count_fast(self, strategy: str):
        self._fast_counts[strategy] = self._fast_counts.get(strategy, 0) + 1

    def drain_counters(self):
        """Flush accumulated decision counts into
        ``scheduler_fast_path_total{strategy}`` /
        ``scheduler_full_scan_total`` (called from the controller's
        telemetry sweep, and by summarize_lifecycle)."""
        fast, self._fast_counts = self._fast_counts, {}
        full, self._full_scans = self._full_scans, 0
        if not fast and not full:
            return
        m = _get_sched_metrics()
        for strategy, n in fast.items():
            # bounded vocabulary: hybrid_native/hybrid_shape/spread_native
            m["fast"].inc(n, {"strategy": strategy})  # ray-tpu: lint-ignore[RTL004] — bounded strategy vocabulary (fast-path kinds only)
        if full:
            m["full"].inc(full)

    # ------------------------------------------------------------------
    def schedule(self, demand: ResourceSet, strategy: SchedulingStrategy,
                 exclude: "Optional[set]" = None) -> ScheduleResult:
        """``exclude``: nodes the caller cannot use right now (worker pool
        exhausted) — the spillback filter (reference: raylet lease
        spillback re-requests with the rejecting node excluded)."""
        if strategy.kind == "NODE_AFFINITY":
            return self._node_affinity(demand, strategy, exclude)
        if strategy.kind == "SPREAD":
            return self._spread(demand, exclude)
        if strategy.kind == "PLACEMENT_GROUP":
            return self._placement_group(demand, strategy, exclude)
        if strategy.kind == "NODE_LABEL":
            return self._node_label(demand, strategy, exclude)
        return self._hybrid(demand, exclude)

    # ------------------------------------------------------------------
    def _feasible_nodes(self, demand: ResourceSet, exclude=None) -> List[NodeID]:
        return [
            nid
            for nid in self.state.ordered_nodes()
            if self.state.nodes[nid].is_feasible(demand)
            and not (exclude and nid in exclude)
        ]

    def _hybrid(self, demand: ResourceSet, exclude=None) -> ScheduleResult:
        """Pack onto the first nodes (stable order) while their utilization is
        below ``scheduler_spread_threshold``; otherwise pick the
        least-utilized available node (reference:
        hybrid_scheduling_policy.cc HybridPolicyWithFilter)."""
        threshold = get_config().scheduler_spread_threshold
        # The native fast path knows about quarantines (mirrored as
        # draining) but not soft throttles (an ORDER preference) — while
        # any throttle is live, placement takes the Python policy path.
        if (
            self.state.native is not None
            and not exclude
            and not self.state.soft_avoid_active()
        ):
            self._count_fast("hybrid_native")
            node_id, infeasible = self.state.native.schedule_hybrid(
                demand.items_fp(), threshold
            )
            return ScheduleResult(node_id, infeasible=infeasible,
                                  reason=_none_reason(node_id, infeasible))
        if not exclude:
            # Demand-shape index: the common no-filter decision is a
            # heap peek + one utilization check instead of a cluster
            # rescan. ``exclude`` (spillback) takes the scan path — the
            # filter is per-request and must not pollute shared buckets.
            e = self.state.shape_entry(demand)
            self._count_fast("hybrid_shape")
            if not e.fits:
                if e.feasible:
                    return ScheduleResult(None, infeasible=False,
                                          reason="insufficient_resources")
                return ScheduleResult(None, infeasible=True,
                                      reason="infeasible")
            heap = e.heap
            while heap and heap[0][1] not in e.fits:
                heapq.heappop(heap)  # lazy-deleted / duplicate entries
            first = heap[0][1]
            if self.state.nodes[first].utilization() < threshold:
                return ScheduleResult(first)
            # Past-threshold tail (rare): same semantics as the scan
            # path, but over the fits set only.
            for _p, nid in sorted((e.pos[n], n) for n in e.fits):
                if self.state.nodes[nid].utilization() < threshold:
                    return ScheduleResult(nid)
            best = min(e.fits, key=lambda n: self.state.nodes[n].utilization())
            return ScheduleResult(best)
        self._full_scans += 1
        feasible = self._feasible_nodes(demand, exclude)
        if not feasible:
            return ScheduleResult(None, infeasible=True, reason="infeasible")
        available = [n for n in feasible if self.state.nodes[n].fits(demand)]
        if not available:
            return ScheduleResult(None, infeasible=False,
                                  reason="insufficient_resources")
        for nid in available:
            if self.state.nodes[nid].utilization() < threshold:
                return ScheduleResult(nid)
        best = min(available, key=lambda n: self.state.nodes[n].utilization())
        return ScheduleResult(best)

    def _spread(self, demand: ResourceSet, exclude=None) -> ScheduleResult:
        if self.state.native is not None and not exclude:
            self._count_fast("spread_native")
            node_id, infeasible = self.state.native.schedule_spread(demand.items_fp())
            return ScheduleResult(node_id, infeasible=infeasible,
                                  reason=_none_reason(node_id, infeasible))
        self._full_scans += 1
        feasible = self._feasible_nodes(demand, exclude)
        if not feasible:
            return ScheduleResult(None, infeasible=True, reason="infeasible")
        available = [n for n in feasible if self.state.nodes[n].fits(demand)]
        if not available:
            return ScheduleResult(None, reason="insufficient_resources")
        pick = available[self._spread_idx % len(available)]
        self._spread_idx += 1
        return ScheduleResult(pick)

    def _node_affinity(self, demand: ResourceSet, strategy: SchedulingStrategy, exclude=None) -> ScheduleResult:
        nid = NodeID.from_hex(strategy.node_id) if isinstance(strategy.node_id, str) else strategy.node_id
        if exclude and nid in exclude:
            if strategy.soft:
                # soft affinity is a preference — spill elsewhere
                return self._hybrid(demand, exclude)
            # hard pin: the node cannot take the task right now — wait
            return ScheduleResult(None, infeasible=False, reason="no_idle_worker")
        node = self.state.nodes.get(nid)
        if node is not None and not node.draining and node.fits(demand):
            return ScheduleResult(nid)
        if strategy.soft:
            return self._hybrid(demand, exclude)
        if node is None:
            return ScheduleResult(None, infeasible=True, reason="infeasible")
        return ScheduleResult(None, reason="insufficient_resources")

    def _node_label(self, demand: ResourceSet, strategy: SchedulingStrategy,
                    exclude=None) -> ScheduleResult:
        """Hard label expressions filter candidates (no match anywhere →
        infeasible, surfaced to the autoscaler with the label demand);
        soft expressions rank the survivors."""
        labels = strategy.node_labels or {}
        hard, soft = labels.get("hard"), labels.get("soft")
        self._full_scans += 1
        candidates = [
            nid for nid in self.state.ordered_nodes()
            if match_label_expressions(hard, self.state.nodes[nid].labels)
            and not (exclude and nid in exclude)
        ]
        if not candidates:
            return ScheduleResult(None, infeasible=True, reason="infeasible")
        feasible = [n for n in candidates if self.state.nodes[n].is_feasible(demand)]
        if not feasible:
            return ScheduleResult(None, infeasible=True, reason="infeasible")
        available = [n for n in feasible if self.state.nodes[n].fits(demand)]
        if not available:
            return ScheduleResult(None, reason="insufficient_resources")
        if soft:
            preferred = [
                n for n in available
                if match_label_expressions(soft, self.state.nodes[n].labels)
            ]
            if preferred:
                available = preferred
        best = min(available, key=lambda n: self.state.nodes[n].utilization())
        return ScheduleResult(best)

    def _placement_group(self, demand: ResourceSet, strategy: SchedulingStrategy, exclude=None) -> ScheduleResult:
        """Translate demand into the PG's renamed group resources
        (reference: placement_group_resource_manager.h — ``CPU`` →
        ``CPU_group_<pgid>`` / ``CPU_group_<i>_<pgid>``)."""
        pgid = strategy.placement_group_id
        suffix = (
            f"_group_{strategy.bundle_index}_{pgid.hex()}"
            if strategy.bundle_index >= 0
            else f"_group_{pgid.hex()}"
        )
        translated = ResourceSet({k + suffix: v for k, v in demand.items_fp()})
        # Also consume the wildcard pool when a specific bundle was requested,
        # so pg-wide accounting stays consistent with the reference.
        if strategy.bundle_index >= 0:
            wildcard = ResourceSet({f"{k}_group_{pgid.hex()}": v for k, v in demand.items_fp()})
            translated = translated + wildcard
        self._full_scans += 1
        for nid in self.state.ordered_nodes():
            if exclude and nid in exclude:
                continue
            if self.state.nodes[nid].fits(translated):
                return ScheduleResult(nid)
        # The renamed group resources exist only once the PG committed —
        # the pump refines this to "pg_unready" when the PG isn't CREATED.
        return ScheduleResult(None, reason="insufficient_resources")

    def translated_pg_demand(self, demand: ResourceSet, strategy: SchedulingStrategy) -> ResourceSet:
        if strategy.kind != "PLACEMENT_GROUP":
            return demand
        pgid = strategy.placement_group_id
        parts = {}
        for k, v in demand.items_fp():
            if strategy.bundle_index >= 0:
                parts[f"{k}_group_{strategy.bundle_index}_{pgid.hex()}"] = v
                parts[f"{k}_group_{pgid.hex()}"] = parts.get(f"{k}_group_{pgid.hex()}", 0) + v
            else:
                parts[f"{k}_group_{pgid.hex()}"] = v
        return ResourceSet(parts)


def schedule_bundles(
    state: ClusterState,
    bundles: List[ResourceSet],
    strategy: str,
    occupied: Optional[set] = None,
) -> Optional[List[NodeID]]:
    """Place PG bundles per PACK/SPREAD/STRICT_PACK/STRICT_SPREAD
    (reference: raylet/scheduling/policy/bundle_scheduling_policy.h:82-106).

    Returns one node per bundle or None if infeasible. Trial placement is
    done against a scratch copy of availability so multi-bundle-per-node
    accounting is correct.

    ``occupied`` is the node set already holding this group's SURVIVING
    bundles during a partial re-place (host-death rescheduling): for
    STRICT_PACK the missing bundles MUST land there (one node), for
    STRICT_SPREAD they must NOT, and SPREAD prefers fresh nodes first —
    mirroring how those nodes would look to a full placement.
    """
    # Scratch availability.
    avail: Dict[NodeID, ResourceSet] = {
        nid: ResourceSet(dict(state.nodes[nid].available.items_fp()))
        for nid in state.ordered_nodes()
    }
    order = state.ordered_nodes()
    occupied = occupied or set()
    if occupied:
        if strategy == "STRICT_PACK":
            order = [n for n in order if n in occupied]
        elif strategy == "STRICT_SPREAD":
            order = [n for n in order if n not in occupied]

    def try_place(nid: NodeID, demand: ResourceSet) -> bool:
        if avail[nid].fits(demand):
            avail[nid] = avail[nid] - demand
            return True
        return False

    placement: List[Optional[NodeID]] = [None] * len(bundles)

    if strategy in ("STRICT_PACK", "PACK"):
        # STRICT_PACK: all bundles on one node (one ICI slice on TPU).
        for nid in order:
            ok = all(avail[nid].fits(b) for b in _stack(bundles))
            if ok and _fits_all(avail[nid], bundles):
                return [nid] * len(bundles)
        if strategy == "STRICT_PACK":
            return None
        # PACK fallback: greedy fill nodes in order.
        for i, b in enumerate(bundles):
            placed = False
            for nid in order:
                if try_place(nid, b):
                    placement[i] = nid
                    placed = True
                    break
            if not placed:
                return None
        return placement  # type: ignore[return-value]

    if strategy in ("SPREAD", "STRICT_SPREAD"):
        used_nodes: set = set(occupied) if strategy == "SPREAD" else set()
        for i, b in enumerate(bundles):
            candidates = [n for n in order if n not in used_nodes] + (
                [] if strategy == "STRICT_SPREAD" else [n for n in order if n in used_nodes]
            )
            placed = False
            for nid in candidates:
                if try_place(nid, b):
                    placement[i] = nid
                    used_nodes.add(nid)
                    placed = True
                    break
            if not placed:
                return None
        return placement  # type: ignore[return-value]

    raise ValueError(f"unknown bundle strategy {strategy}")


def _stack(bundles: List[ResourceSet]) -> List[ResourceSet]:
    total = ResourceSet()
    for b in bundles:
        total = total + b
    return [total]


def _fits_all(avail: ResourceSet, bundles: List[ResourceSet]) -> bool:
    total = ResourceSet()
    for b in bundles:
        total = total + b
    return avail.fits(total)
