"""Structured cluster log plane: attribution at the source.

Reference: python/ray/_private/log_monitor.py plus the dashboard
StateHead's logs API (``ray logs --actor-id/--task-id --follow``) and the
error-event aggregation the GCS keeps per job. The reference attributes
log lines to workers by file name and to tasks by magic prefix tokens;
here every record is stamped structurally at the source:

* **capture** — :func:`install` adds a :class:`logging.Handler` to the
  root logger and (workers only) wraps ``sys.stdout``/``sys.stderr`` in
  write-through proxies, so logger calls, ``print()`` inside tasks, and
  uncaught-exception tracebacks all land — attributed — in a bounded
  JSONL sidecar (``worker-<id>.jsonl``) next to the raw log. Task/actor
  attribution reuses the per-thread tag ``profiling.set_thread_task``
  installs around every task execution (PR 9) plus the thread-local
  task/actor ids in ``runtime_context``.
* **bounding** — the sidecar rotates by rename at ``log_rotate_bytes``
  (one ``.1`` half kept, the PR 6 span-sink pattern); the RAW
  ``worker-*.log`` is rotated copy-truncate by a maintenance thread (the
  redirected-stdout fd keeps appending; rename would chase the fd). The
  proxies' write-through shares the raw-file lock with the rotator, so
  no line this process writes is lost to the copy/truncate window.
* **shipping** — ERROR/exception records also enqueue for the worker's
  controller connection (:func:`drain_ship`); the controller folds them
  into its error-signature index (``state.summarize_errors()``). The
  full firehose never crosses the wire — cluster search fans out to the
  node-local sidecars instead (:func:`search_local`).

Disabled via the ``log_structured`` config (the envelope A/B knob):
capture becomes write-through-only and the sidecar goes quiet.
"""
from __future__ import annotations

import collections
import io
import json
import logging
import os
import re
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

logger = logging.getLogger("ray_tpu.log_plane")

# Severity vocabulary (bounded — these become metric tags and filter
# values). STDOUT/STDERR mark raw stream lines that carry no logger level.
SEVERITY_RANK = {
    "DEBUG": 10,
    "INFO": 20,
    "STDOUT": 20,
    "WARNING": 30,
    "STDERR": 30,
    "ERROR": 40,
    "CRITICAL": 50,
}
MAX_MSG_BYTES = 8192

_enabled = True
_writer: Optional["StructuredLogWriter"] = None
_raw_log_path: Optional[str] = None
_raw_lock = threading.Lock()  # serializes raw write-through vs. rotation
# pid cached at install: os.getpid() is a real syscall (~15us under
# gVisor-class sandboxes) and _build_record runs per captured line
_context: Dict[str, Any] = {"node": None, "worker": None, "proc": "",
                            "pid": 0}
# Per-severity record counts, folded into log_records_total by the
# maintenance thread — a per-line Counter.inc would pay the global
# metrics lock + cap resolution on every print (GIL-atomic dict ops;
# a lost increment under a rare race is acceptable for a rate metric).
_sev_counts: Dict[str, int] = {}
# ERROR/exception records awaiting the ship loop (bounded: a controller
# outage must not grow worker memory; oldest drop first).
_ship: "collections.deque" = collections.deque(maxlen=2000)
_installed = False
_tls = threading.local()  # re-entrancy guard for the capture paths
_metrics = None


def set_enabled(flag: bool):
    """Runtime toggle (the bench A/B): capture paths become write-through
    no-ops when off."""
    global _enabled
    _enabled = bool(flag)


def enabled() -> bool:
    return _enabled


def _get_metrics():
    global _metrics
    if _metrics is None:
        from ray_tpu.util.metrics import Counter

        _metrics = {
            "records": Counter(
                "log_records_total",
                "Structured log records captured in this process, by severity",
                ("severity",),
            ),
        }
    return _metrics


def _config_value(name: str, default):
    from ray_tpu.util.profiling import _config_value as cv

    return cv(name, default)


# ---------------------------------------------------------------------------
# Sidecar writer (rename rotation — this process owns the handle)
# ---------------------------------------------------------------------------
def _encode_record(rec: dict) -> bytes:
    """Hand-rolled JSONL encoding for the capture hot path: fixed keys,
    only user-controlled strings (msg/task/logger) pay a real
    ``json.dumps``; id/hex fields interpolate directly. ~2.5x cheaper
    than dumps() of the whole dict — this runs once per captured line.
    Falls back to full dumps on anything surprising."""
    try:
        parts = [
            f'"ts":{rec["ts"]:.6f}',
            f'"sev":"{rec["sev"]}"',
            f'"msg":{json.dumps(rec["msg"])}',
        ]
        for key in ("node", "worker"):
            v = rec.get(key)
            if v is not None:
                parts.append(f'"{key}":"{v}"')
        parts.append(f'"pid":{rec.get("pid", 0)}')
        task = rec.get("task")
        if task is not None:
            parts.append(f'"task":{json.dumps(task)}')
        for key in ("task_id", "actor_id"):
            v = rec.get(key)
            if v is not None:
                parts.append(f'"{key}":"{v}"')
        for key in ("logger", "exc"):
            v = rec.get(key)
            if v is not None:
                parts.append(f'"{key}":{json.dumps(v)}')
        return ("{" + ",".join(parts) + "}\n").encode("utf-8", "replace")
    except (TypeError, ValueError, KeyError):
        line = json.dumps(rec, separators=(",", ":"), default=str) + "\n"
        return line.encode("utf-8", "replace")


class StructuredLogWriter:
    """Append-only JSONL sink, size-capped with ONE rotated half
    (``<path>.1``, the span-sink pattern): disk use is bounded at ~2x
    ``rotate_bytes``.

    The hot path (``emit``) only encodes and appends to a bounded
    in-memory queue; the maintenance thread drains it to disk every
    ~0.25 s. ERROR-and-above records drain inline so incident/error
    tails are never stale. A hard crash can lose the last <=0.25 s of
    INFO-level sidecar lines — the raw log's write-through is
    synchronous, so the lines themselves survive (the reference's
    TaskEventBuffer makes the same trade)."""

    MAX_QUEUED = 100_000

    def __init__(self, path: str, rotate_bytes: int):
        self.path = path
        self.rotate_bytes = max(64 * 1024, int(rotate_bytes))
        self._lock = threading.Lock()
        self._fh = None
        self._written = 0
        self._queue: "collections.deque" = collections.deque(
            maxlen=self.MAX_QUEUED
        )

    def _open(self):
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._fh = open(self.path, "ab")
        try:
            self._written = os.fstat(self._fh.fileno()).st_size
        except OSError:
            self._written = 0

    def emit(self, record: dict, flush: bool = False):
        self._queue.append(_encode_record(record))
        if flush:
            self.flush()

    def _drain_locked(self):
        while self._queue:
            batch: List[bytes] = []
            size = 0
            # chunk drains at the rotation cap so one huge backlog still
            # rotates at the right boundaries
            while self._queue and size < self.rotate_bytes // 2:
                data = self._queue.popleft()
                batch.append(data)
                size += len(data)
            if self._fh is None:
                self._open()
            if self._written + size > self.rotate_bytes and self._written:
                self._fh.close()
                os.replace(self.path, self.path + ".1")
                self._open()
            self._fh.write(b"".join(batch))
            self._written += size
        self._fh.flush()

    def flush(self):
        with self._lock:
            if not self._queue:
                return
            try:
                self._drain_locked()
            except OSError as e:
                logger.debug("sidecar drain failed: %s", e)

    def close(self):
        self.flush()
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# ---------------------------------------------------------------------------
# Record construction + capture legs
# ---------------------------------------------------------------------------
_task_tags: Dict[int, str] = {}  # rebound to profiling._task_tags at install
_task_local = threading.local()  # rebound to runtime_context._task_local


def _build_record(severity: str, msg: str, *, logger_name: str = "",
                  exc_type: str = "") -> dict:
    if len(msg) > MAX_MSG_BYTES:
        msg = msg[:MAX_MSG_BYTES] + "...(truncated)"
    rec = {
        "ts": time.time(),
        "sev": severity,
        "msg": msg,
        "node": _context["node"],
        "worker": _context["worker"],
        "pid": _context["pid"],
        # per-thread task attribution: profiling tags carry the executing
        # task/actor-method NAME; runtime_context the thread-local ids
        "task": _task_tags.get(threading.get_ident()),
        "task_id": getattr(_task_local, "task_id", None),
        "actor_id": getattr(_task_local, "actor_id", None),
    }
    if logger_name:
        rec["logger"] = logger_name
    if exc_type:
        rec["exc"] = exc_type
    return rec


def _record(severity: str, msg: str, *, logger_name: str = "",
            exc_type: str = ""):
    """One captured line → sidecar (+ ship queue for ERROR-and-above).
    Re-entrancy-guarded: a failure inside the capture path logging about
    itself must not recurse."""
    if not _enabled or _writer is None or not msg:
        return
    if getattr(_tls, "capturing", False):
        return
    _tls.capturing = True
    try:
        rec = _build_record(severity, msg, logger_name=logger_name,
                            exc_type=exc_type)
        is_err = SEVERITY_RANK.get(severity, 20) >= SEVERITY_RANK["ERROR"] or exc_type
        _writer.emit(rec, flush=bool(is_err))
        if is_err:
            _ship.append(rec)
        _sev_counts[severity] = _sev_counts.get(severity, 0) + 1
    except Exception as e:  # noqa: BLE001 — capture must never take the app down
        logger.debug("log capture failed: %s", e)
    finally:
        _tls.capturing = False


class _LogHandler(logging.Handler):
    """Root-logger leg: every logging record, attributed and leveled."""

    def emit(self, record: logging.LogRecord):
        try:
            msg = record.getMessage()
            exc_type = ""
            if record.exc_info and record.exc_info[0] is not None:
                exc_type = record.exc_info[0].__name__
                msg += "\n" + "".join(traceback.format_exception(*record.exc_info))
            _record(record.levelname, msg, logger_name=record.name,
                    exc_type=exc_type)
        # reporting a failure here would re-enter this very handler
        # (unbounded recursion); silence is the only safe exit
        # ray-tpu: lint-ignore[RTL006]
        except Exception:  # noqa: BLE001 — logging must never raise
            pass


_LOGGING_FILE = getattr(logging, "__file__", "<logging>")


class _StreamProxy(io.TextIOBase):
    """Write-through stdout/stderr wrapper: the raw log file keeps
    receiving everything (log-to-driver tailing unchanged), and complete
    lines additionally become structured records. Lines written by the
    logging module's own StreamHandler are skipped — the handler leg
    already recorded them with their real level."""

    def __init__(self, orig, severity: str):
        self._orig = orig
        self._severity = severity
        self._buffers: Dict[int, str] = {}  # per-thread partial lines

    def write(self, s):
        with _raw_lock:
            n = self._orig.write(s)
        if not _enabled or _writer is None or not s:
            return n
        # One-frame peek: logging.StreamHandler.emit's write call comes
        # from logging/__init__.py — skip (already captured, leveled).
        try:
            if sys._getframe(1).f_code.co_filename == _LOGGING_FILE:
                return n
        except ValueError:
            pass
        ident = threading.get_ident()
        buf = self._buffers.get(ident, "") + s
        if "\n" in buf:
            lines = buf.split("\n")
            buf = lines[-1]
            for line in lines[:-1]:
                if line:
                    _record(self._severity, line)
        if buf:
            self._buffers[ident] = buf
        else:
            self._buffers.pop(ident, None)
        return n

    def flush(self):
        self._orig.flush()

    def fileno(self):
        return self._orig.fileno()

    def isatty(self):
        try:
            return self._orig.isatty()
        except (OSError, ValueError):
            return False

    def writable(self):
        return True

    @property
    def buffer(self):
        return self._orig.buffer

    @property
    def encoding(self):
        return getattr(self._orig, "encoding", "utf-8")


def record_task_error(task_name: str, task_id: Optional[str], exc: BaseException,
                      tb_text: str):
    """Attribution hook for task/actor failures: worker_main calls this
    with the formatted traceback BEFORE the error crosses the wire, so
    the error index sees every failure even when the caller swallows the
    ref (reference: the GCS's per-job error events)."""
    _record(
        "ERROR",
        f"task {task_name} failed: {tb_text}",
        exc_type=type(exc).__name__,
    )


def drain_ship(max_records: int = 500) -> List[dict]:
    """Pop queued ERROR records for the controller ship loop."""
    out: List[dict] = []
    while _ship and len(out) < max_records:
        out.append(_ship.popleft())
    return out


def requeue_ship(batch: List[dict]):
    """Put a failed ship batch back if there is room (bounded deque —
    a full queue keeps the NEWER records instead)."""
    room = (_ship.maxlen or 0) - len(_ship)
    if room >= len(batch):
        _ship.extendleft(reversed(batch))


def start_ship_loop(core):
    """Ship queued ERROR records over the process's existing controller
    connection every ``log_ship_interval_ms`` (async on the RPC loop —
    the PR 6 task-event flush pattern)."""
    import asyncio

    interval = float(core.config.get("log_ship_interval_ms", 1000)) / 1000.0

    async def loop():
        while True:
            await asyncio.sleep(interval)
            batch = drain_ship()
            if not batch:
                continue
            try:
                await core.peer.notify("log_errors", batch)
            except Exception:  # noqa: BLE001 — controller gone
                requeue_ship(batch)
                if core.peer.closed:
                    # Keep ticking while a reconnect may still swap in a
                    # fresh peer (core.try_reconnect); stop when no window
                    # is configured OR the reconnect already gave up for
                    # good — retrying a permanently-dead peer forever is
                    # just noise (loop_runner teardown cancels us
                    # regardless on exit).
                    if getattr(core, "_reconnect_dead", False) or not float(
                        core.config.get("controller_reconnect_window_s", 0.0)
                    ):
                        return

    core.loop_runner.submit(loop())


# ---------------------------------------------------------------------------
# Install / maintenance
# ---------------------------------------------------------------------------
def _stdout_path() -> Optional[str]:
    """Where this process's stdout actually goes (the spawn-redirected
    worker-*.log) — via /proc so rotation needs no path plumbing."""
    try:
        path = os.readlink("/proc/self/fd/1")
    except OSError:
        return None
    if path.endswith(".log") and os.path.isfile(path):
        return path
    return None


def _rotate_raw(path: str, cap: int):
    """Copy-truncate rotation for the raw log: the writing fd was
    inherited O_APPEND by this process at spawn, so rename would chase it
    — instead copy the content to ``.1`` and truncate in place (O_APPEND
    writers continue at the new EOF). The raw-file lock closes the
    copy→truncate window against this process's own (proxied) writers;
    direct-fd writers in child subprocesses can lose a line across
    rotation, like any copytruncate logrotate."""
    import shutil

    with _raw_lock:
        try:
            for stream in (sys.stdout, sys.stderr):
                try:
                    stream.flush()
                except (OSError, ValueError):
                    pass
            if os.path.getsize(path) <= cap:
                return
            with open(path, "rb") as src, open(path + ".1", "wb") as dst:
                shutil.copyfileobj(src, dst)
            with open(path, "r+b") as f:
                f.truncate(0)
        except OSError as e:
            logger.debug("raw log rotation failed: %s", e)


def _flush_sev_counts():
    if not _sev_counts:
        return
    try:
        m = _get_metrics()["records"]
        for sev in list(_sev_counts):
            n = _sev_counts.pop(sev, 0)
            if n:
                m.inc(n, {"severity": sev})  # ray-tpu: lint-ignore[RTL004] — fixed SEVERITY_RANK vocabulary
    except Exception as e:  # noqa: BLE001 — metrics must not kill maintenance
        logger.debug("severity count flush failed: %s", e)


def _maintenance_loop(stop: threading.Event):
    while not stop.wait(0.25):
        w = _writer
        if w is not None:
            w.flush()
        _flush_sev_counts()
        path = _raw_log_path
        if path is not None:
            # cap re-read each sweep: at install time the cluster config
            # may not be attached yet (worker_main installs before
            # api._attach_worker), and the writer's cap is authoritative
            # for the sidecar anyway
            cap = int(
                w.rotate_bytes if w is not None
                else _config_value("log_rotate_bytes", 64 * 1024 * 1024)
            )
            try:
                if os.path.getsize(path) > cap:
                    _rotate_raw(path, cap)
            except OSError:
                pass


_maintenance_stop: Optional[threading.Event] = None
_prev_threading_hook = None
_handler: Optional[_LogHandler] = None


def install(session_dir: str, *, node_id: Optional[str] = None,
            worker_id: Optional[str] = None, proc: str = "",
            capture_streams: bool = True, rotate_bytes: Optional[int] = None):
    """Wire this process into the log plane. Idempotent. Workers pass
    ``capture_streams=True`` (their stdout IS the spawn-redirected log
    file); drivers/controller/agents install the logging-handler leg
    only."""
    global _writer, _raw_log_path, _installed, _maintenance_stop
    global _prev_threading_hook, _handler
    if _installed:
        return
    _installed = True
    _context["node"] = node_id[:12] if node_id else None
    _context["worker"] = worker_id[:8] if worker_id else None
    _context["proc"] = proc
    _context["pid"] = os.getpid()
    # bind the attribution sources once (hot path: one dict.get + one
    # getattr per record instead of two module imports)
    global _task_tags, _task_local
    from ray_tpu import runtime_context
    from ray_tpu.util import profiling

    _task_tags = profiling._task_tags
    _task_local = runtime_context._task_local
    if rotate_bytes is None:
        rotate_bytes = int(_config_value("log_rotate_bytes", 64 * 1024 * 1024))
    name = f"worker-{worker_id[:8]}" if worker_id else (proc or f"driver-{os.getpid()}")
    _writer = StructuredLogWriter(
        os.path.join(session_dir, "logs", f"{name}.jsonl"), rotate_bytes
    )
    _handler = _LogHandler()
    logging.getLogger().addHandler(_handler)
    if capture_streams:
        _raw_log_path = _stdout_path()
        sys.stdout = _StreamProxy(sys.stdout, "STDOUT")
        sys.stderr = _StreamProxy(sys.stderr, "STDERR")

        _prev_threading_hook = threading.excepthook

        def _thread_hook(args):
            try:
                _record(
                    "ERROR",
                    "uncaught exception in thread "
                    f"{getattr(args.thread, 'name', '?')}: "
                    + "".join(traceback.format_exception(
                        args.exc_type, args.exc_value, args.exc_traceback)),
                    exc_type=args.exc_type.__name__,
                )
            finally:
                _prev_threading_hook(args)

        threading.excepthook = _thread_hook
    _maintenance_stop = threading.Event()
    threading.Thread(
        target=_maintenance_loop, args=(_maintenance_stop,),
        daemon=True, name="log-plane-maintenance",
    ).start()


def uninstall():
    """Detach (driver shutdown): remove the handler, restore hooks, close
    the sidecar. Stream proxies stay (write-through is inert) — workers
    exit instead of uninstalling."""
    global _writer, _installed, _maintenance_stop, _prev_threading_hook
    global _handler, _raw_log_path
    if not _installed:
        return
    _installed = False
    if _maintenance_stop is not None:
        _maintenance_stop.set()
        _maintenance_stop = None
    if _handler is not None:
        logging.getLogger().removeHandler(_handler)
        _handler = None
    if _prev_threading_hook is not None:
        threading.excepthook = _prev_threading_hook
        _prev_threading_hook = None
    w, _writer = _writer, None
    _raw_log_path = None
    if w is not None:
        w.close()
    _ship.clear()


# ---------------------------------------------------------------------------
# Node-local query legs (answered by agents and the controller's head leg)
# ---------------------------------------------------------------------------
def list_local(log_dir: str) -> List[dict]:
    """Rows for every log file under ``log_dir``: {filename, size, mtime,
    structured} (rotated ``.1`` halves are folded into their live file's
    size rather than listed)."""
    rows: List[dict] = []
    if not os.path.isdir(log_dir):
        return rows
    names = sorted(os.listdir(log_dir))
    live = {n for n in names if not n.endswith(".1")}
    for name in names:
        if name.endswith(".1"):
            continue
        path = os.path.join(log_dir, name)
        try:
            st = os.stat(path)
        except OSError:
            continue
        size = st.st_size
        try:
            size += os.path.getsize(path + ".1")
        except OSError:
            pass
        rows.append(
            {
                "filename": name,
                "size": size,
                "mtime": st.st_mtime,
                "structured": (
                    name.endswith(".jsonl")
                    or os.path.splitext(name)[0] + ".jsonl" in live
                ),
            }
        )
    return rows


def read_local(log_dir: str, filename: str, tail: int = 1000) -> str:
    """Last ``tail`` lines of one log file (rotation-aware: short files
    borrow their ``.1`` half's tail first). Raises ValueError on paths
    escaping the log dir."""
    root = os.path.realpath(log_dir)
    path = os.path.realpath(os.path.join(log_dir, filename))
    if os.path.commonpath([path, root]) != root:
        raise ValueError("log path escapes the session log dir")
    lines: List[str] = []
    for p in (path + ".1", path):
        if not os.path.isfile(p):
            continue
        try:
            with open(p, errors="replace") as f:
                lines.extend(f.readlines())
        except OSError:
            continue
    if not lines and not os.path.exists(path):
        raise FileNotFoundError(filename)
    return "".join(lines[-max(1, tail):])


_FILTER_KEYS = ("pattern", "severity", "task", "actor", "node", "since",
                "until")


def match_record(rec: dict, *, pattern=None, severity: Optional[str] = None,
                 task: Optional[str] = None, actor: Optional[str] = None,
                 node: Optional[str] = None, since: Optional[float] = None,
                 until: Optional[float] = None) -> bool:
    """The one filter rule shared by search, follow, and the CLI:
    regex over msg, severity floor, time range, and entity (task name /
    task-id / actor-id prefix) + node prefix filters."""
    if severity:
        floor = SEVERITY_RANK.get(severity.upper(), 20)
        if SEVERITY_RANK.get(str(rec.get("sev", "")).upper(), 20) < floor:
            return False
    ts = rec.get("ts")
    if since is not None and (ts is None or ts < since):
        return False
    if until is not None and (ts is None or ts > until):
        return False
    if node and not str(rec.get("node") or "").startswith(node[:12]):
        return False
    if task:
        name = str(rec.get("task") or "")
        tid = str(rec.get("task_id") or "")
        if task not in name and not tid.startswith(task):
            return False
    if actor:
        aid = str(rec.get("actor_id") or "")
        name = str(rec.get("task") or "")
        if not aid.startswith(actor) and not name.startswith(actor):
            return False
    if pattern is not None:
        if isinstance(pattern, str):
            pattern = re.compile(pattern)
        if not pattern.search(str(rec.get("msg", ""))):
            return False
    return True


def search_local(log_dir: str, *, pattern: Optional[str] = None,
                 severity: Optional[str] = None, task: Optional[str] = None,
                 actor: Optional[str] = None, node: Optional[str] = None,
                 since: Optional[float] = None, until: Optional[float] = None,
                 limit: int = 1000, include_raw: bool = True) -> List[dict]:
    """Grep this node's sidecars (rotated halves included, oldest first)
    for records passing the filters; bounded result size. Raw ``.log``
    files WITHOUT a sidecar (controller.log, agent logs before install)
    fall back to plain grep when only pattern/time filters apply —
    severity/entity filters need structure and skip them."""
    limit = max(1, min(int(limit), 10000))
    rx = re.compile(pattern) if pattern else None
    out: List[dict] = []
    if not os.path.isdir(log_dir):
        return out
    names = sorted(os.listdir(log_dir))
    sidecars = [n for n in names
                if n.endswith(".jsonl") and not n.startswith("spans-")]
    structured_stems = {os.path.splitext(n)[0] for n in sidecars}
    for name in sidecars:
        base = os.path.join(log_dir, name)
        for path, fname in ((base + ".1", name + ".1"), (base, name)):
            # rotated halves keep their ".1" suffix in the result rows:
            # the cross-node merge dedups on (file, line), and a live
            # line 5 must not collide with the rotated half's line 5
            if len(out) >= limit or not os.path.isfile(path):
                continue
            try:
                with open(path, errors="replace") as f:
                    for lineno, line in enumerate(f, 1):
                        if len(out) >= limit:
                            break
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if match_record(rec, pattern=rx, severity=severity,
                                        task=task, actor=actor, node=node,
                                        since=since, until=until):
                            rec["file"] = fname
                            rec["line"] = lineno
                            out.append(rec)
            except OSError:
                continue
    if include_raw and rx is not None and not (severity or task or actor):
        for name in names:
            if (not name.endswith(".log")
                    or os.path.splitext(name)[0] in structured_stems):
                continue
            path = os.path.join(log_dir, name)
            try:
                with open(path, errors="replace") as f:
                    for lineno, line in enumerate(f, 1):
                        if len(out) >= limit:
                            break
                        if rx.search(line):
                            out.append(
                                {"ts": None, "sev": None,
                                 "msg": line.rstrip("\n"),
                                 "node": None, "worker": None,
                                 "file": name, "line": lineno}
                            )
            except OSError:
                continue
    out.sort(key=lambda r: (r.get("ts") or 0.0, r.get("file", ""),
                            r.get("line", 0)))
    return out[:limit]


def format_record(rec: dict) -> str:
    """One search/follow record as a human line (the CLI's renderer)."""
    ts = rec.get("ts")
    when = (
        time.strftime("%H:%M:%S", time.localtime(ts)) + f".{int(ts % 1 * 1000):03d}"
        if ts else "--:--:--"
    )
    who = rec.get("worker") or rec.get("file") or "?"
    node = rec.get("node") or "?"
    head = f"{when} {str(rec.get('sev') or '-'):8s} {node[:8]}/{who}"
    if rec.get("task"):
        head += f" [{rec['task']}]"
    return f"{head}  {rec.get('msg', '')}"


# ---------------------------------------------------------------------------
# Error signatures (controller-side aggregation helper)
# ---------------------------------------------------------------------------
_FRAME_RE = re.compile(r'File "([^"]+)", line \d+, in (\S+)')
_NOISE_RE = re.compile(r"0x[0-9a-fA-F]+|[0-9a-f]{6,}|\d+")
_PKG_MARKER = os.sep + "ray_tpu" + os.sep


def error_signature(rec: dict, max_frames: int = 3) -> str:
    """Bounded signature for an ERROR record: exception type + the top
    (deepest) user frames from its traceback, file-basenamed and
    line-number-free so signatures survive line drift; records without a
    traceback group by their digit-normalized message head. The caller
    interns the result (bounded vocabulary — the PR 10 CallsiteTable
    pattern)."""
    msg = str(rec.get("msg", ""))
    frames = _FRAME_RE.findall(msg)
    user = [(f, fn) for f, fn in frames if _PKG_MARKER not in f]
    pick = (user or frames)[-max_frames:]
    exc = rec.get("exc") or ""
    if pick:
        chain = ";".join(
            f"{os.path.basename(f)}:{fn}" for f, fn in pick
        )
        return f"{exc or 'Error'}@{chain}"
    head = _NOISE_RE.sub("#", msg.splitlines()[0][:80]) if msg else ""
    return f"{exc or 'ERROR'}@{head}"


class ErrorIndex:
    """Controller-side error aggregation: ERROR records dedupe by bounded
    :func:`error_signature` into {count, first/last seen, sample
    traceback, lifecycle entity link} rows — the answer to "what errors
    is the cluster seeing right now" without reading a single log file
    (reference: the GCS's per-job error-event table + the dashboard's
    event aggregation).

    Bounded twice over: signatures intern through a CallsiteTable
    (``log_error_index_size``; overflow collapses into ``(other)``) and
    sample tracebacks truncate at 8 KB. ``log_errors_total{signature}``
    rides the normal metric pipeline (registry cardinality cap
    backstops)."""

    def __init__(self, cap: int = 256):
        from ray_tpu.core.memory_census import CallsiteTable

        self._intern = CallsiteTable(cap=cap)
        self._rows: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self.total = 0
        self._recent: "collections.deque" = collections.deque(maxlen=200)
        self._metric = None

    def _counter(self):
        if self._metric is None:
            from ray_tpu.util.metrics import Counter

            self._metric = Counter(
                "log_errors_total",
                "ERROR log records ingested by the cluster error index, "
                "by bounded signature",
                ("signature",),
            )
        return self._metric

    def ingest(self, rec: dict, source: str = ""):
        sig = self._intern.intern(error_signature(rec))
        now = rec.get("ts") or time.time()
        with self._lock:
            self.total += 1
            row = self._rows.get(sig)
            if row is None:
                row = self._rows[sig] = {
                    "signature": sig,
                    "exc_type": rec.get("exc") or "",
                    "count": 0,
                    "first_seen": now,
                    "last_seen": now,
                    "sample": str(rec.get("msg", ""))[:MAX_MSG_BYTES],
                    "entity": {
                        "task": rec.get("task"),
                        "task_id": rec.get("task_id"),
                        "actor_id": rec.get("actor_id"),
                        "worker": rec.get("worker"),
                        "node": rec.get("node"),
                    },
                    "nodes": set(),
                }
            row["count"] += 1
            row["last_seen"] = max(row["last_seen"], now)
            if rec.get("node"):
                row["nodes"].add(rec["node"])
            self._recent.append(rec)
        try:
            self._counter().inc(1, {"signature": sig[:80]})  # ray-tpu: lint-ignore[RTL004] — interned under log_error_index_size + registry cap
        except Exception as e:  # noqa: BLE001 — metrics must not break ingest
            logger.debug("error index metric failed: %s", e)

    def summarize(self, limit: int = 50) -> dict:
        with self._lock:
            rows = sorted(self._rows.values(), key=lambda r: -r["count"])
            keep = rows[: max(1, limit)]
            out = {
                "total": self.total,
                "distinct": len(rows),
                "truncated": len(rows) > len(keep),
                "signatures": {
                    r["signature"]: {**r, "nodes": sorted(r["nodes"])}
                    for r in keep
                },
            }
        return out

    def recent_tail(self, n: int = 100) -> List[dict]:
        """Newest ingested ERROR records — the spike incident's attached
        log tail."""
        with self._lock:
            return list(self._recent)[-n:]
