"""Placement group manager (control-plane side).

Reference: src/ray/gcs/gcs_server/gcs_placement_group_manager.cc (lifecycle
FSM) + src/ray/raylet/placement_group_resource_manager.h:44-84 (2-phase
bundle reservation: Prepare atomically holds base resources, Commit renames
them into group resources ``CPU_group_<pgid>`` / ``CPU_group_<i>_<pgid>``).

TPU-specific: ``STRICT_PACK`` is the gang-scheduling primitive for an ICI
slice — a multi-chip pjit program needs all its chips on one slice, so the
TPU trainer always reserves its chips via STRICT_PACK per host plus a
pod-level SPREAD across hosts (see ray_tpu.train).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.core.scheduler import ClusterState, schedule_bundles
from ray_tpu.utils.ids import NodeID, PlacementGroupID


class PGState(enum.Enum):
    PENDING = 0
    CREATED = 1
    REMOVED = 2
    RESCHEDULING = 3


@dataclass
class PlacementGroupRecord:
    pg_id: PlacementGroupID
    bundles: List[ResourceSet]
    strategy: str
    name: str = ""
    state: PGState = PGState.PENDING
    # node per bundle once placed
    bundle_nodes: List[Optional[NodeID]] = field(default_factory=list)
    # Bundle indices retired by an elastic re-mesh (shrink): never
    # re-placed, never counted as missing. Kept as indices (not list
    # surgery) so surviving bundles' interned group resource names —
    # which embed the original index — stay valid.
    retired: set = field(default_factory=set)

    def to_dict(self):
        return {
            "placement_group_id": self.pg_id.hex(),
            "name": self.name,
            "strategy": self.strategy,
            "state": self.state.name,
            "bundles": [b.to_dict() for b in self.bundles],
            "bundle_nodes": [n.hex() if n else None for n in self.bundle_nodes],
            "retired": sorted(self.retired),
        }


def _group_resources(pg_id: PlacementGroupID, index: int, bundle: ResourceSet) -> ResourceSet:
    parts: Dict[str, int] = {}
    for k, v in bundle.items_fp():
        parts[f"{k}_group_{index}_{pg_id.hex()}"] = v
        parts[f"{k}_group_{pg_id.hex()}"] = parts.get(f"{k}_group_{pg_id.hex()}", 0) + v
    return ResourceSet(parts)


class PlacementGroupManager:
    def __init__(self, state: ClusterState, recorder=None):
        self.state = state
        self.groups: Dict[PlacementGroupID, PlacementGroupRecord] = {}
        # Control-plane flight recorder (core/lifecycle.py); None when the
        # manager is constructed standalone (tests).
        self.recorder = recorder

    def _record(self, rec: PlacementGroupRecord, state: str):
        if self.recorder is not None:
            self.recorder.record("pg", rec.pg_id.hex(), state, name=rec.name)

    # ------------------------------------------------------------------
    def create(self, pg_id: PlacementGroupID, bundles: List[ResourceSet], strategy: str, name: str = "") -> PlacementGroupRecord:
        rec = PlacementGroupRecord(pg_id=pg_id, bundles=bundles, strategy=strategy, name=name)
        self.groups[pg_id] = rec
        self._record(rec, "PENDING")
        self.try_place(rec)
        return rec

    def try_place(self, rec: PlacementGroupRecord) -> bool:
        """Prepare + commit. Placement is atomic against the cluster view; if
        any bundle can't be prepared nothing is reserved (the 2PC invariant
        from the reference).

        RESCHEDULING groups with surviving placed bundles (elastic gang
        repair after a host death) re-place ONLY the missing bundles —
        survivors keep their reservations and the actors inside them keep
        running.
        """
        if rec.state == PGState.CREATED:
            return True
        if len(rec.bundle_nodes) != len(rec.bundles):
            rec.bundle_nodes = [None] * len(rec.bundles)
        missing = [
            i for i, n in enumerate(rec.bundle_nodes)
            if n is None and i not in rec.retired
        ]
        if not missing:
            rec.state = PGState.CREATED
            return True
        occupied = {n for n in rec.bundle_nodes if n is not None}
        nodes = schedule_bundles(
            self.state,
            [rec.bundles[i] for i in missing],
            rec.strategy,
            occupied=occupied,
        )
        if nodes is None:
            if self.recorder is not None:
                self.recorder.pending_reason(
                    "pg", rec.pg_id.hex(), "insufficient_resources"
                )
            return False
        # Prepare: acquire base resources on each node.
        acquired: List[tuple] = []
        ok = True
        for nid, idx in zip(nodes, missing):
            node = self.state.nodes.get(nid)
            if node is None or not node.acquire(rec.bundles[idx]):
                ok = False
                break
            acquired.append((nid, rec.bundles[idx], idx))
        if not ok:
            for nid, bundle, _ in acquired:
                if nid in self.state.nodes:
                    self.state.nodes[nid].release(bundle)
            if self.recorder is not None:
                self.recorder.pending_reason(
                    "pg", rec.pg_id.hex(), "insufficient_resources"
                )
            return False
        # 2-phase dwell: RESERVED marks prepare (base resources held),
        # CREATED marks commit (group resources renamed in).
        self._record(rec, "RESERVED")
        # Commit: add renamed group resources.
        for nid, bundle, idx in acquired:
            self.state.nodes[nid].add_total(_group_resources(rec.pg_id, idx, bundle))
            rec.bundle_nodes[idx] = nid
        rec.state = PGState.CREATED
        self._record(rec, "CREATED")
        return True

    # ------------------------------------------------------------------
    def remove(self, pg_id: PlacementGroupID):
        rec = self.groups.get(pg_id)
        if rec is None or rec.state == PGState.REMOVED:
            return
        if rec.state in (PGState.CREATED, PGState.RESCHEDULING):
            # RESCHEDULING keeps SURVIVING bundles reserved (partial
            # re-place after a host death) — release those too.
            for idx, (nid, bundle) in enumerate(zip(rec.bundle_nodes, rec.bundles)):
                node = self.state.nodes.get(nid) if nid is not None else None
                if node is None:
                    continue
                node.remove_total(_group_resources(rec.pg_id, idx, bundle))
                node.release(bundle)
        rec.state = PGState.REMOVED
        self._record(rec, "REMOVED")
        self._forget_group_ids(rec)

    def _forget_group_ids(self, rec):
        """Recycle the PG's interned resource ids in the native scheduling
        core — group names are unique per PG, so without this the dense
        id space grows by O(#PGs-ever)."""
        native = getattr(self.state, "native", None)
        if native is None:
            return
        names = set()
        for idx, bundle in enumerate(rec.bundles):
            for k, _ in _group_resources(rec.pg_id, idx, bundle).items_fp():
                names.add(k)
        for name in names:
            native.forget(name)

    # ------------------------------------------------------------------
    def on_node_removed(self, node_id: NodeID):
        """Bundles on a dead node → PG goes back to rescheduling
        (reference: gcs_placement_group_manager.cc OnNodeDead). Only the
        DEAD node's bundles are re-placed; surviving bundles keep their
        reservations so the actors inside them stay warm — the elastic
        gang-repair invariant (backend_executor.restart rejoin)."""
        for rec in self.groups.values():
            # RESCHEDULING too: a second node death while earlier dead
            # bundles are still unplaced must clear ITS slots as well, or
            # the group would later commit with a bundle pinned to the
            # second dead node.
            if (rec.state in (PGState.CREATED, PGState.RESCHEDULING)
                    and node_id in rec.bundle_nodes):
                for idx, nid in enumerate(rec.bundle_nodes):
                    if nid == node_id:
                        # The node record is already gone — its resource
                        # accounting died with it; just mark the slot.
                        rec.bundle_nodes[idx] = None
                if rec.state != PGState.RESCHEDULING:
                    rec.state = PGState.RESCHEDULING
                    self._record(rec, "RESCHEDULING")
                self.try_place(rec)

    def shrink(self, pg_id: PlacementGroupID, indices: List[int]) -> bool:
        """Retire bundles after an elastic re-mesh: release any held
        reservation and stop re-placing them — without this, a shrunken
        gang's dead bundle would sit RESCHEDULING forever and commit the
        moment capacity returns, reserving resources no worker will use."""
        rec = self.groups.get(pg_id)
        if rec is None or rec.state == PGState.REMOVED:
            return False
        for idx in indices:
            if not 0 <= idx < len(rec.bundles) or idx in rec.retired:
                continue
            nid = (
                rec.bundle_nodes[idx]
                if idx < len(rec.bundle_nodes) else None
            )
            if nid is not None:
                node = self.state.nodes.get(nid)
                if node is not None:
                    node.remove_total(
                        _group_resources(rec.pg_id, idx, rec.bundles[idx])
                    )
                    node.release(rec.bundles[idx])
                rec.bundle_nodes[idx] = None
            rec.retired.add(idx)
        if rec.state == PGState.RESCHEDULING and not any(
            n is None and i not in rec.retired
            for i, n in enumerate(rec.bundle_nodes)
        ):
            rec.state = PGState.CREATED
            self._record(rec, "CREATED")
        return True

    def retry_pending(self):
        for rec in self.groups.values():
            if rec.state in (PGState.PENDING, PGState.RESCHEDULING):
                self.try_place(rec)

    def pending_records(self) -> List[PlacementGroupRecord]:
        return [
            rec
            for rec in self.groups.values()
            if rec.state in (PGState.PENDING, PGState.RESCHEDULING)
        ]

    def is_ready(self, pg_id: PlacementGroupID) -> bool:
        rec = self.groups.get(pg_id)
        return rec is not None and rec.state == PGState.CREATED

    def table(self) -> dict:
        return {pid.hex(): rec.to_dict() for pid, rec in self.groups.items()}
