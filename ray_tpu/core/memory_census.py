"""Per-process object & memory census with creation call-site attribution.

Reference: ``ray memory`` / the dashboard memory view, built on the core
worker's reference counting (src/ray/core_worker/reference_count.cc keeps
per-ref ``call_site`` strings captured at creation;
python/ray/util/state/common.py ObjectState carries them to the user).
The question this layer answers is the one an OOM'd object store poses:
**who holds it** — which file:line created the refs that pin store memory.

Three pieces, all cheap enough for the put/submit hot path:

* **call-site capture** — :func:`capture_callsite` walks at most a handful
  of frames to the first frame outside the ray_tpu package and interns the
  ``file.py:line:func`` string in a bounded table (:class:`CallsiteTable`,
  ``memory_callsite_cap``): past the cap every new site collapses into
  ``(other)`` so the vocabulary — and any metric tag built from it — stays
  bounded. A per-code-object cache makes repeat captures a dict hit.
* **attribution** — the CoreWorker's RefTracker maps live ref keys to
  their creation site (client.py); puts/task submissions attribute at
  creation, deserialized borrows report as ``(borrowed)``.
* **process dump** — :func:`dump` snapshots THIS process's census: open
  local refs grouped by call-site, owner-local memory-store occupancy,
  and live pinned arena views (PR 5's zero-copy pins, registered by
  PlasmaClient). Every process answers ``rpc_dump_memory`` with it; the
  controller fans out and merges (controller.rpc_summarize_memory).

Disabled via the ``memory_census`` config (the envelope A/B knob):
capture returns ``""`` and the dump degrades to counts without sites.
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Dict, Optional

# Trailing separator: a sibling directory whose name merely starts with
# "ray_tpu" (ray_tpu_contrib/...) must not be classified as internal.
_PKG_PREFIX = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) + os.sep

OVERFLOW_SITE = "(other)"
BORROWED_SITE = "(borrowed)"

_enabled = True


def set_enabled(flag: bool):
    global _enabled
    _enabled = bool(flag)


def enabled() -> bool:
    return _enabled


class CallsiteTable:
    """Bounded intern table for creation call-sites.

    The table bounds the attribution vocabulary (and therefore anything
    keyed by it — census groups, leak-detector trend entries, metric
    tags): the first ``cap`` distinct sites intern; later ones all map to
    ``(other)``. Thread-safe; lookups after interning are lock-free dict
    hits.
    """

    def __init__(self, cap: int = 512):
        self.cap = max(8, int(cap))
        self._lock = threading.Lock()
        # (filename, lineno, funcname) -> interned site string
        self._by_frame: Dict[tuple, str] = {}
        self._sites: Dict[str, None] = {}

    def intern_frame(self, filename: str, lineno: int, func: str) -> str:
        key = (filename, lineno, func)
        site = self._by_frame.get(key)
        if site is not None:
            return site
        with self._lock:
            site = self._by_frame.get(key)
            if site is not None:
                return site
            if len(self._sites) >= self.cap:
                site = OVERFLOW_SITE
            else:
                # trim to the last two path components for readability
                # (full paths repeat the venv prefix on every row)
                parts = filename.replace("\\", "/").rsplit("/", 2)
                short = "/".join(parts[-2:]) if len(parts) > 1 else filename
                site = f"{short}:{lineno}:{func}"
                self._sites[site] = None
            self._by_frame[key] = site
            return site

    def intern(self, site: str) -> str:
        """Intern an already-formatted site label (task names etc.)."""
        if site in self._sites:
            return site
        with self._lock:
            if site in self._sites:
                return site
            if len(self._sites) >= self.cap:
                return OVERFLOW_SITE
            self._sites[site] = None
            return site

    def __len__(self):
        return len(self._sites)


_table: Optional[CallsiteTable] = None
_table_lock = threading.Lock()


def _get_table() -> CallsiteTable:
    global _table
    if _table is None:
        with _table_lock:
            if _table is None:
                from ray_tpu.util.profiling import _config_value

                _table = CallsiteTable(
                    int(_config_value("memory_callsite_cap", 512))
                )
    return _table


def _reset_for_tests(cap: int = 512):
    global _table, _enabled
    with _table_lock:
        _table = CallsiteTable(cap)
    _enabled = True


def capture_callsite(depth: int = 1) -> str:
    """The creating USER frame as an interned ``file.py:line:func``
    string, or ``""`` when the census is disabled. Walks outward from the
    caller until it leaves the ray_tpu package (bounded walk), so
    ``ray_tpu.put(...)`` in app code attributes to the app line, not to
    client.py."""
    if not _enabled:
        return ""
    try:
        f = sys._getframe(depth)  # 1 = capture_callsite's direct caller
    except ValueError:  # shallow stack (embedding oddities)
        return "(unknown)"
    hops = 0
    while f is not None and hops < 32:
        fname = f.f_code.co_filename
        if not fname.startswith(_PKG_PREFIX):
            return _get_table().intern_frame(
                fname, f.f_lineno, f.f_code.co_name
            )
        f = f.f_back
        hops += 1
    return "(internal)"


def task_site(name: str) -> str:
    """Interned label for task-return objects (``(task) <name>``) — task
    names are the natural call-site for values a task produced."""
    if not _enabled:
        return ""
    return _get_table().intern(f"(task) {name}")


# ---------------------------------------------------------------------------
# Process census dump (the rpc_dump_memory leg)
# ---------------------------------------------------------------------------
def dump(limit: int = 1000) -> dict:
    """Snapshot THIS process's object/memory census.

    Shape::

        {kind: "process", process, pid, worker_id, mode,
         refs: {site: {count, pinned}},          # open local refs by site
         objects: [{object_id, callsite, count, local_only, pinned}, ...],
         memory_store: {entries, ready_bytes, pending, shm},
         pins: {count, bytes, objects: [hex, ...]}}

    Touches only the ref tracker's lock (briefly) and the pin registry;
    safe to answer from any process at any time.
    """
    from ray_tpu.core import api
    from ray_tpu.core import object_store as _os_mod
    from ray_tpu.util.profiling import process_label

    out = {
        "kind": "process",
        "process": process_label(),
        "pid": os.getpid(),
        "worker_id": None,
        "mode": None,
        "refs": {},
        "objects": [],
        "memory_store": {},
        "pins": {},
    }
    pins = _os_mod.live_pin_stats()
    out["pins"] = pins
    pinned_keys = _os_mod.live_pin_keys()  # uncapped, unlike pins["objects"]
    core = api._global_worker
    if core is None:
        return out
    out["worker_id"] = core.worker_id.hex()
    out["mode"] = core.mode
    out["memory_store"] = core.memory_store.stats()
    counts, sites = core.refs.census_snapshot()
    by_site: Dict[str, dict] = {}
    rows = []
    for key, count in counts.items():
        site = sites.get(key) or BORROWED_SITE
        row = by_site.setdefault(site, {"count": 0, "pinned": 0})
        row["count"] += count
        hexid = key.hex()
        if hexid in pinned_keys:
            row["pinned"] += 1
        if len(rows) < limit:
            rows.append(
                {
                    "object_id": hexid,
                    "callsite": site,
                    "count": count,
                    "local_only": core.memory_store.is_local_only(key),
                    "pinned": hexid in pinned_keys,
                }
            )
    out["refs"] = by_site
    out["objects"] = rows
    out["truncated"] = len(counts) > len(rows)
    return out
