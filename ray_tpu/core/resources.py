"""Resource model.

Fixed-point resource arithmetic with fractional support, mirroring the
reference's scheduling substrate (reference:
src/ray/common/scheduling/fixed_point.h, cluster_resource_data.h,
resource_instance_set.cc). Quantities are stored as integer 1/10000 units so
fractional CPUs/TPUs never accumulate float error.

TPU-specific: ``TPU`` is a countable chip resource like GPU; pod-slice
topology resources (``TPU-v5p-8-head``-style, reference:
python/ray/_private/accelerators/tpu.py:334-397) are plain custom resources
layered on top by the node's accelerator detection.
"""
from __future__ import annotations

import logging
from typing import Dict, Iterable

logger = logging.getLogger(__name__)

PRECISION = 10000


def to_fp(v: float | int) -> int:
    return int(round(v * PRECISION))


def from_fp(v: int) -> float:
    f = v / PRECISION
    return int(f) if f.is_integer() else f


class ResourceSet:
    """An immutable-ish mapping resource-name -> fixed-point quantity."""

    __slots__ = ("_m",)

    def __init__(self, m: Dict[str, int] | None = None):
        self._m = {k: v for k, v in (m or {}).items() if v != 0}

    @classmethod
    def from_dict(cls, d: Dict[str, float] | None) -> "ResourceSet":
        return cls({k: to_fp(v) for k, v in (d or {}).items()})

    def to_dict(self) -> Dict[str, float]:
        return {k: from_fp(v) for k, v in self._m.items()}

    def get(self, name: str) -> int:
        return self._m.get(name, 0)

    def is_empty(self) -> bool:
        return not self._m

    def names(self) -> Iterable[str]:
        return self._m.keys()

    def fits(self, other: "ResourceSet") -> bool:
        """True if self (available) can satisfy other (demand)."""
        return all(self._m.get(k, 0) >= v for k, v in other._m.items())

    def __add__(self, other: "ResourceSet") -> "ResourceSet":
        m = dict(self._m)
        for k, v in other._m.items():
            m[k] = m.get(k, 0) + v
        return ResourceSet(m)

    def __sub__(self, other: "ResourceSet") -> "ResourceSet":
        m = dict(self._m)
        for k, v in other._m.items():
            m[k] = m.get(k, 0) - v
        return ResourceSet(m)

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._m == other._m

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __reduce__(self):
        return (ResourceSet, (self._m,))

    def items_fp(self):
        return self._m.items()


class NodeResources:
    """Total and available resources of one node, plus labels.

    Reference: src/ray/common/scheduling/cluster_resource_data.h
    ``NodeResources`` {total, available, labels}.
    """

    def __init__(self, total: ResourceSet, labels: Dict[str, str] | None = None):
        self.total = total
        self.available = ResourceSet(dict(total.items_fp()))
        self.labels = dict(labels or {})
        # Optional native mirror (ray_tpu/native/sched.py): every mutation
        # is written through so the C++ core can make scheduling decisions
        # over its own dense view. Python stays the source of truth.
        self._native = None
        self._native_id = None
        # Optional capacity watcher (ClusterState's shape index + dirty
        # tracking for the resource pubsub channel): notified after every
        # availability/capacity mutation, same back-binding pattern as
        # the native mirror.
        self._watcher = None
        self._watcher_id = None
        # Graceful drain: excluded from placement, accounting kept live.
        self.draining = False

    def bind_native(self, sched, node_id):
        self._native = sched
        self._native_id = node_id

    def bind_watcher(self, watcher, node_id):
        self._watcher = watcher
        self._watcher_id = node_id

    def _notify_watcher(self):
        if self._watcher is not None:
            self._watcher.note_capacity_changed(self._watcher_id)

    def fits(self, demand: ResourceSet) -> bool:
        return self.available.fits(demand)

    def is_feasible(self, demand: ResourceSet) -> bool:
        """Could this node EVER satisfy demand (ignores current usage)."""
        return self.total.fits(demand)

    def acquire(self, demand: ResourceSet) -> bool:
        if not self.available.fits(demand):
            return False
        self.available = self.available - demand
        if self._native is not None:
            ok = self._native.acquire(self._native_id, demand.items_fp())
            if not ok:
                # The mirror disagreed with the Python source of truth —
                # repair it in place rather than letting the C++ view
                # drive placement off stale numbers.
                logger.warning(
                    "native scheduler mirror desync on %s; resyncing", self._native_id
                )
                self._native.sync_node(
                    self._native_id, self.total.items_fp(), self.available.items_fp()
                )
        self._notify_watcher()
        return True

    def release(self, demand: ResourceSet):
        if self._native is not None:
            self._native.release(self._native_id, demand.items_fp())
        self.available = self.available + demand
        # Clamp: releasing more than total indicates a bug elsewhere, but
        # never let availability exceed capacity for dynamic resources.
        m = {}
        for k, v in self.available.items_fp():
            cap = self.total.get(k)
            m[k] = min(v, cap) if cap else v
        self.available = ResourceSet(m)
        self._notify_watcher()

    def utilization(self) -> float:
        """Max utilization across resource kinds — drives the hybrid policy's
        pack/spread decision (reference: hybrid_scheduling_policy.cc)."""
        best = 0.0
        for k, tot in self.total.items_fp():
            if tot <= 0:
                continue
            used = tot - self.available.get(k)
            best = max(best, used / tot)
        return best

    def add_total(self, extra: ResourceSet):
        self.total = self.total + extra
        self.available = self.available + extra
        if self._native is not None:
            self._native.add_total(self._native_id, extra.items_fp())
        self._notify_watcher()

    def remove_total(self, extra: ResourceSet):
        self.total = self.total - extra
        self.available = self.available - extra
        if self._native is not None:
            self._native.remove_total(self._native_id, extra.items_fp())
        self._notify_watcher()

    def to_dict(self):
        return {
            "total": self.total.to_dict(),
            "available": self.available.to_dict(),
            "labels": dict(self.labels),
        }
