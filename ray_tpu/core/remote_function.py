"""@remote function plumbing.

Reference: python/ray/remote_function.py (decorator, ``.options()``,
``_remote`` at :266). The serialized function is cached on the handle and
shipped inside the TaskSpec; workers cache it by digest.
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

from ray_tpu.core.resources import ResourceSet
from ray_tpu.core.task_spec import SchedulingStrategy, TaskSpec, TaskType
from ray_tpu.utils.ids import TaskID, WorkerID
from ray_tpu.utils.serialization import serialize_function

_DEFAULT_TASK_OPTIONS = dict(
    num_cpus=1,
    num_tpus=0,
    memory=0,
    resources=None,
    num_returns=1,
    max_retries=3,
    retry_exceptions=False,
    scheduling_strategy=None,
    name=None,
    runtime_env=None,
)


def build_resource_set(opts: Dict[str, Any]) -> ResourceSet:
    d: Dict[str, float] = {}
    if opts.get("num_cpus"):
        d["CPU"] = opts["num_cpus"]
    if opts.get("num_tpus"):
        d["TPU"] = opts["num_tpus"]
    if opts.get("memory"):
        d["memory"] = opts["memory"]
    for k, v in (opts.get("resources") or {}).items():
        d[k] = v
    return ResourceSet.from_dict(d)


def normalize_strategy(raw) -> SchedulingStrategy:
    if raw is None:
        return SchedulingStrategy()
    if isinstance(raw, SchedulingStrategy):
        return raw
    if isinstance(raw, str):
        if raw == "SPREAD":
            return SchedulingStrategy(kind="SPREAD")
        if raw == "DEFAULT":
            return SchedulingStrategy()
        raise ValueError(f"unknown scheduling strategy {raw!r}")
    # Duck-typed strategy objects from ray_tpu.util.scheduling_strategies.
    if hasattr(raw, "placement_group"):
        pg = raw.placement_group
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP",
            placement_group_id=pg.id if hasattr(pg, "id") else pg,
            bundle_index=getattr(raw, "placement_group_bundle_index", -1),
            capture_child_tasks=getattr(raw, "placement_group_capture_child_tasks", False),
        )
    if hasattr(raw, "node_id"):
        return SchedulingStrategy(
            kind="NODE_AFFINITY", node_id=raw.node_id, soft=getattr(raw, "soft", False)
        )
    if hasattr(raw, "to_wire") and (hasattr(raw, "hard") or hasattr(raw, "soft")):
        # NodeLabelSchedulingStrategy (reference:
        # util/scheduling_strategies.py:94-115 In/NotIn/Exists/DoesNotExist)
        return SchedulingStrategy(kind="NODE_LABEL", node_labels=raw.to_wire())
    raise ValueError(f"unsupported scheduling strategy: {raw!r}")


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._options = dict(_DEFAULT_TASK_OPTIONS)
        self._options.update(options or {})
        self._blob: Optional[bytes] = None
        self._digest: Optional[bytes] = None

    def _ensure_exported(self):
        if self._blob is None:
            self._blob = serialize_function(self._fn)
            self._digest = hashlib.blake2b(self._blob, digest_size=16).digest()

    def options(self, **opts) -> "RemoteFunction":
        new = RemoteFunction(self._fn, {**self._options, **opts})
        new._blob, new._digest = self._blob, self._digest
        return new

    def remote(self, *args, **kwargs):
        from ray_tpu.core.api import _require_worker

        core = _require_worker()
        self._ensure_exported()
        opts = self._options
        streaming = opts["num_returns"] == "streaming"
        args_blob, deps, captures = core.build_args(args, kwargs)
        # Trace-context propagation (reference: tracing_helper.py:88 —
        # context rides in task metadata when tracing is on).
        from ray_tpu.util import tracing as _tracing

        runtime_env = _tracing.inject_runtime_env(opts.get("runtime_env"))
        spec = TaskSpec(
            task_id=core.next_task_id(),
            task_type=TaskType.NORMAL_TASK,
            name=opts.get("name") or getattr(self._fn, "__name__", "anonymous"),
            func_digest=self._digest,
            func_blob=self._blob,
            args_blob=args_blob,
            dependencies=deps,
            num_returns=TaskSpec.STREAMING if streaming else opts["num_returns"],
            resources=build_resource_set(opts),
            owner_id=core.worker_id,
            scheduling_strategy=normalize_strategy(opts.get("scheduling_strategy")),
            max_retries=opts["max_retries"],
            retry_exceptions=bool(opts["retry_exceptions"]),
            runtime_env=runtime_env,
        )
        refs = core.submit_task(spec, captures)
        if streaming:
            from ray_tpu.core.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id)
        return refs[0] if opts["num_returns"] == 1 else refs

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference: dag API, remote_function bind)."""
        from ray_tpu.dag.node import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "Remote functions cannot be called directly. "
            f"Use {getattr(self._fn, '__name__', 'fn')}.remote() instead."
        )
