"""Controller state persistence — the GCS fault-tolerance store.

Reference: src/ray/gcs/store_client/ — the GCS persists its tables
through a ``StoreClient`` (in-memory by default, Redis for FT;
redis_store_client.h:88) and restores them on restart, after which
clients resubscribe. This image has no Redis, so the durable backend is
an append-only JSONL journal with periodic compaction — same recovery
contract, file-backed: every mutation to a persisted table is appended
synchronously, and a restarting controller replays the journal to
rebuild {KV store, detached-actor specs, placement-group specs}.

Binary values are hex-encoded; TaskSpecs travel as pickled blobs (they
carry their own function payloads, so a restored spec is
self-contained).
"""
from __future__ import annotations

import json
import logging
import os
import pickle
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

JOURNAL_NAME = "gcs_journal.jsonl"


class GcsJournal:
    """Append-only journal of controller table mutations."""

    def __init__(self, session_dir: str, sync: bool = True):
        self.path = os.path.join(session_dir, JOURNAL_NAME)
        self._sync = sync
        self._f = None

    # -- write path -------------------------------------------------------
    def _file(self):
        if self._f is None:
            self._f = open(self.path, "a", encoding="utf-8")
        return self._f

    def append(self, op: str, **fields: Any):
        rec = {"op": op, **fields}
        f = self._file()
        f.write(json.dumps(rec) + "\n")
        f.flush()
        if self._sync:
            os.fsync(f.fileno())

    # table-specific helpers (hex/pickle encoding in one place) ----------
    def kv_put(self, ns: str, key: bytes, value: bytes):
        self.append("kv_put", ns=ns, key=key.hex(), value=value.hex())

    def kv_del(self, ns: str, key: bytes):
        self.append("kv_del", ns=ns, key=key.hex())

    def actor_register(self, spec) -> None:
        self.append("actor_register", actor_id=spec.actor_id.hex(),
                    spec=pickle.dumps(spec).hex())

    def actor_dead(self, actor_id_hex: str):
        self.append("actor_dead", actor_id=actor_id_hex)

    def pg_create(self, pg_id_hex: str, bundles: List[Dict[str, float]],
                  strategy: str, name: str):
        self.append("pg_create", pg_id=pg_id_hex, bundles=bundles,
                    strategy=strategy, name=name)

    def pg_remove(self, pg_id_hex: str):
        self.append("pg_remove", pg_id=pg_id_hex)

    def pg_shrink(self, pg_id_hex: str, indices: List[int]):
        """Elastic re-mesh retired these bundle indices — replay must not
        resurrect them (they would re-reserve resources no worker uses)."""
        self.append("pg_shrink", pg_id=pg_id_hex, indices=list(indices))

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None

    # -- read path --------------------------------------------------------
    def exists(self) -> bool:
        return os.path.exists(self.path)

    def replay(self) -> "RestoredState":
        """Replay the journal into the latest table state.

        A torn tail (crash mid-append) is dropped AND physically truncated
        — otherwise the next append would merge into the partial line and
        poison every later record for the following replay."""
        state = RestoredState()
        if not self.exists():
            return state
        good_bytes = 0
        torn = False
        with open(self.path, "rb") as f:
            for line_no, raw in enumerate(f):
                line = raw.decode("utf-8", errors="replace").strip()
                if not line:
                    good_bytes += len(raw)
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    logger.warning("journal: torn record at line %d; truncating", line_no)
                    torn = True
                    break
                good_bytes += len(raw)
                op = rec.get("op")
                if op == "kv_put":
                    state.kv.setdefault(rec["ns"], {})[bytes.fromhex(rec["key"])] = (
                        bytes.fromhex(rec["value"])
                    )
                elif op == "kv_del":
                    state.kv.get(rec["ns"], {}).pop(bytes.fromhex(rec["key"]), None)
                elif op == "actor_register":
                    try:
                        spec = pickle.loads(bytes.fromhex(rec["spec"]))
                        state.actors[rec["actor_id"]] = spec
                    except Exception:
                        logger.warning("journal: undeserializable actor spec %s", rec["actor_id"])
                elif op == "actor_dead":
                    state.actors.pop(rec["actor_id"], None)
                elif op == "pg_create":
                    state.pgs[rec["pg_id"]] = {
                        "bundles": rec["bundles"],
                        "strategy": rec["strategy"],
                        "name": rec["name"],
                        "retired": rec.get("retired", []),
                    }
                elif op == "pg_remove":
                    state.pgs.pop(rec["pg_id"], None)
                elif op == "pg_shrink":
                    pg = state.pgs.get(rec["pg_id"])
                    if pg is not None:
                        pg["retired"] = sorted(
                            set(pg.get("retired", [])) | set(rec["indices"])
                        )
        if torn:
            with open(self.path, "rb+") as f:
                f.truncate(good_bytes)
        return state

    def compact(self, state: "RestoredState"):
        """Rewrite the journal as the current state (bounds replay cost)."""
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for ns, table in state.kv.items():
                for k, v in table.items():
                    f.write(json.dumps({"op": "kv_put", "ns": ns, "key": k.hex(),
                                        "value": v.hex()}) + "\n")
            for aid, spec in state.actors.items():
                f.write(json.dumps({"op": "actor_register", "actor_id": aid,
                                    "spec": pickle.dumps(spec).hex()}) + "\n")
            for pgid, pg in state.pgs.items():
                f.write(json.dumps({"op": "pg_create", "pg_id": pgid, **pg}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self.close()
        os.replace(tmp, self.path)


class RestoredState:
    def __init__(self):
        self.kv: Dict[str, Dict[bytes, bytes]] = {}
        self.actors: Dict[str, Any] = {}  # actor_id hex -> creation TaskSpec
        self.pgs: Dict[str, dict] = {}  # pg_id hex -> {bundles, strategy, name}

    @property
    def empty(self) -> bool:
        return not (self.kv or self.actors or self.pgs)
