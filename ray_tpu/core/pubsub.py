"""Topic bus: the generalized control-plane pub/sub plane.

Round 13 added one hardcoded channel (``lifecycle:deaths``) wired
directly through the controller's ``_pubsub_subs`` dict. This module
promotes that into a small topic bus (reference: src/ray/pubsub/ — the
reference's publisher/subscriber carries resource views, actor state,
AND worker failures over the same machinery) and adds the two channels
that move the resource hot path from per-sweep polling to
push-on-change:

  RESOURCES_CHANNEL  controller -> subscribers: per-node availability
                     deltas, coalesced at resource_broadcast_min_interval_ms,
                     plus periodic full-snapshot reconciliation
  AVOID_CHANNEL      controller -> agents: scheduler avoid/drain state
                     (quarantines, throttles, drains) pushed on change —
                     agents gate spawn decisions on a local mirror
                     instead of asking per spawn

Delivery is at-most-once per subscriber per publish (one ``pubsub_msg``
notify on the subscriber's existing control connection — no long-poll,
no redelivery), so every push channel pairs with reconciliation:
:class:`ResourceViewMirror` applies per-node sequence-numbered deltas,
drops stale/out-of-order ones, and converges on the periodic snapshot
no matter what the delta stream dropped or reordered.

Single-writer: the bus lives on the controller and is mutated only from
its asyncio loop — no locks (same discipline as every controller map).
"""
from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, Optional, Set

from ray_tpu.core.lifecycle import DEATH_CHANNEL  # noqa: F401  (re-export)
from ray_tpu.util.guards import OWNER_THREAD, GuardedDict
from ray_tpu.utils import rpc

logger = logging.getLogger(__name__)

# Per-node availability deltas + periodic snapshots (push-on-change
# replacement for polling rpc_cluster_resources per sweep).
RESOURCES_CHANNEL = "cluster:resources"
# Scheduler avoid/drain state (controller -> agents).
AVOID_CHANNEL = "cluster:avoid"


class TopicBus:
    """Channel -> subscriber-peer fan-out with closed-peer pruning.

    Publish is concurrent per subscriber (one wedged subscriber's
    backpressure must not stall the rest or the publisher) and
    fire-and-forget (``notify`` — no reply frames on the hot path).
    """

    def __init__(self):
        # single-writer (controller loop): ConcSan checks thread affinity
        self._subs: Dict[str, Set[rpc.Peer]] = GuardedDict(
            OWNER_THREAD, owner=self, name="subs"
        )

    def subscribe(self, channel: str, peer: rpc.Peer):
        self._subs.setdefault(channel, set()).add(peer)
        peer.meta.setdefault("subscriptions", set()).add(channel)

    def unsubscribe(self, channel: str, peer: rpc.Peer):
        subs = self._subs.get(channel)
        if subs is not None:
            subs.discard(peer)
            if not subs:
                del self._subs[channel]
        peer.meta.get("subscriptions", set()).discard(channel)

    def drop_peer(self, peer: rpc.Peer):
        for channel in list(peer.meta.get("subscriptions", ())):
            subs = self._subs.get(channel)
            if subs is not None:
                subs.discard(peer)
                if not subs:
                    del self._subs[channel]

    def has(self, channel: str) -> bool:
        """Any subscribers? Publishers check this first so building the
        message costs nothing on clusters that never subscribed."""
        return bool(self._subs.get(channel))

    def channels(self) -> Dict[str, int]:
        return {c: len(s) for c, s in self._subs.items()}

    async def publish(self, channel: str, msg: Any) -> int:
        """Fan ``msg`` out to the channel's subscribers concurrently;
        returns the number of live subscribers notified."""
        subs = self._subs.get(channel)
        if not subs:
            return 0
        live = []
        for p in list(subs):
            if p.closed:
                subs.discard(p)
            else:
                live.append(p)
        if not subs:
            self._subs.pop(channel, None)
        if live:
            await asyncio.gather(
                *(p.notify("pubsub_msg", channel, msg) for p in live),
                return_exceptions=True,
            )
        return len(live)


class ResourceViewMirror:
    """Subscriber-side materialization of RESOURCES_CHANNEL.

    Deltas carry a per-node monotonic ``seq``; a delta at or below the
    last applied seq for that node is stale (reordered or duplicated in
    flight) and is dropped. ``reconcile`` replaces the whole view from a
    full snapshot — nodes absent from the snapshot are removed, and the
    snapshot's seqs become the new floors — so the mirror converges on
    the poll-equivalent state within one reconcile period regardless of
    what the delta stream lost.
    """

    def __init__(self):
        # node hex -> {"available": {...}, "total": {...},
        #              "draining": bool, "avoid": str|None}
        # single-writer (the subscriber's ingest loop); GuardedDict
        # pickles down to a plain dict when the view crosses RPC
        self.nodes: Dict[str, dict] = GuardedDict(
            OWNER_THREAD, owner=self, name="nodes"
        )
        self._seq: Dict[str, int] = GuardedDict(
            OWNER_THREAD, owner=self, name="seq"
        )
        self.applied = 0
        self.stale = 0
        self.reconciles = 0

    def ingest(self, msg: dict) -> bool:
        """Dispatch one RESOURCES_CHANNEL message: full snapshots (marked
        ``{"snapshot": True}``) reconcile, everything else is a delta."""
        if not isinstance(msg, dict):
            return False
        if msg.get("snapshot"):
            self.reconcile(msg)
            return True
        return self.apply(msg)

    def apply(self, delta: dict) -> bool:
        """Apply one per-node delta; returns False if it was stale."""
        node = delta.get("node")
        seq = delta.get("seq")
        if not node or not isinstance(seq, int):
            return False
        if delta.get("removed"):
            # Removal tombstone: drop the node but KEEP its seq floor so
            # a reordered pre-removal delta can't resurrect it.
            if seq <= self._seq.get(node, -1):
                self.stale += 1
                return False
            self._seq[node] = seq
            self.nodes.pop(node, None)
            self.applied += 1
            return True
        if seq <= self._seq.get(node, -1):
            self.stale += 1
            return False
        self._seq[node] = seq
        view = self.nodes.setdefault(node, {})
        for k in ("available", "total", "draining", "avoid"):
            if k in delta:
                view[k] = delta[k]
        self.applied += 1
        return True

    def reconcile(self, snapshot: dict):
        """Replace the view from a full snapshot
        (``{"nodes": {hex: {seq, available, total, draining, avoid}}}``)."""
        rows = snapshot.get("nodes")
        if not isinstance(rows, dict):
            return
        fresh: Dict[str, dict] = {}
        for node, row in rows.items():
            fresh[node] = {
                "available": row.get("available", {}),
                "total": row.get("total", {}),
                "draining": bool(row.get("draining")),
                "avoid": row.get("avoid"),
            }
            seq = row.get("seq")
            if isinstance(seq, int):
                self._seq[node] = max(self._seq.get(node, -1), seq)
        # Forget seq floors for nodes the authority no longer knows:
        # a reused hex (never in practice) starts a fresh seq space.
        for node in list(self._seq):
            if node not in fresh:
                self._seq.pop(node, None)
        # in place, not `self.nodes = fresh`: a rebind would replace the
        # guard-annotated dict with a plain one (RTL010 flags that)
        self.nodes.clear()
        self.nodes.update(fresh)
        self.reconciles += 1

    def available(self, node: str) -> Optional[dict]:
        view = self.nodes.get(node)
        return None if view is None else view.get("available")
