"""CoreWorker: the in-process runtime embedded by drivers and workers.

Reference: src/ray/core_worker/core_worker.h:295 (SubmitTask / CreateActor /
SubmitActorTask / Get / Put / Wait) and its Cython surface
python/ray/_raylet.pyx:3282. Blocking public methods bridge onto the
process's asyncio loop; object payloads are read zero-copy out of the node's
shared-memory store.
"""
from __future__ import annotations

import itertools
import os
import threading
from concurrent.futures import Future
from typing import Any, List, Optional, Sequence

from concurrent.futures import TimeoutError as _CfTimeout

from ray_tpu.core.object_ref import ObjectRef, _RefMarker, _capture, set_ref_tracker
from ray_tpu.core.object_store import PlasmaClient
from ray_tpu.core.task_spec import SchedulingStrategy, TaskSpec, TaskType
from ray_tpu.exceptions import GetTimeoutError, ObjectLostError
from ray_tpu.utils import rpc
from ray_tpu.utils.ids import NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.utils.serialization import deserialize, serialize

INLINE_LIMIT_FALLBACK = 100 * 1024

# Control-plane methods that block by DESIGN (waiting for objects,
# streams, placement, drains — their duration is the workload's, not the
# control plane's). Everything else gets the bounded default timeout
# (``control_call_timeout_s``) when the caller passes none, so a wedged
# or partitioned controller surfaces as an error instead of a hang.
_UNBOUNDED_METHODS = frozenset(
    {
        "object_get",
        "object_wait",
        "object_pull",
        "object_ensure_local",
        "object_broadcast",
        "stream_next",
        "pg_wait_ready",
        "wait_actor_ready",
        "drain_node",
        "task_done",  # carries result upload; sized by payload, not control
    }
)


class RefTracker:
    """Per-process local ref table (reference: ReferenceCounter's local
    refs, src/ray/core_worker/reference_count.h:142). Zero-crossings are
    collected and batch-flushed; ids touched-and-dropped within one flush
    window still flush as drops so the controller learns the object was
    once held (transient refs must not leak).

    Also carries the memory census's creation-site attribution: puts and
    task submissions :meth:`attribute` their refs with the interned user
    call-site (reference: reference_count.cc keeps a per-ref call_site
    string for ``ray memory``); sites drop with their last ref."""

    def __init__(self):
        import collections

        self._lock = threading.Lock()
        self._counts: dict[bytes, int] = {}
        self._touched: set[bytes] = set()
        # oid key -> interned creation call-site (memory_census); absent
        # for borrowed/deserialized refs.
        self._sites: dict[bytes, str] = {}
        # dec() is called from ObjectRef.__del__, which the cyclic GC may
        # run on ANY thread — including one currently inside inc()/drain()
        # holding the (non-reentrant) lock. dec therefore never locks: it
        # appends to a thread-safe deque that drain/inc fold in later.
        self._pending_decs = collections.deque()

    def inc(self, oid):
        key = oid.binary()
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            self._touched.add(key)

    def dec(self, oid):
        self._pending_decs.append(oid.binary())  # lock-free (see __init__)

    def attribute(self, key: bytes, site: str):
        """Record the creation call-site for a ref this process created
        (no-op for empty sites — census disabled)."""
        if not site:
            return
        with self._lock:
            if key not in self._sites:
                self._sites[key] = site

    def site_of(self, key: bytes) -> str:
        return self._sites.get(key, "")

    def census_snapshot(self) -> "tuple[dict[bytes, int], dict[bytes, str]]":
        """(open counts, sites) copies for the memory census dump —
        pending decs folded first so the snapshot reflects GC'd refs."""
        with self._lock:
            self._fold_decs_locked()
            return dict(self._counts), dict(self._sites)

    def _fold_decs_locked(self):
        while True:
            try:
                key = self._pending_decs.popleft()
            except IndexError:
                return
            n = self._counts.get(key, 0) - 1
            if n <= 0:
                self._counts.pop(key, None)
                self._sites.pop(key, None)
            else:
                self._counts[key] = n
            self._touched.add(key)

    def pending_drops(self) -> int:
        """Decs queued by ObjectRef.__del__ but not yet folded into the
        flush — the health plane's gc_nudge reports this as evidence a
        forced collection actually freed refs."""
        return len(self._pending_decs)

    def drain(self) -> tuple[list[bytes], list[bytes]]:
        """(held, dropped) among ids touched since the last drain."""
        with self._lock:
            self._fold_decs_locked()
            touched, self._touched = self._touched, set()
            held = [k for k in touched if self._counts.get(k, 0) > 0]
            dropped = [k for k in touched if self._counts.get(k, 0) <= 0]
        return held, dropped

def _serialize_parts_capturing(value: Any):
    """serialize_parts() + captured nested refs — the zero-extra-copy path
    for large puts/returns (nested refs → containment pins)."""
    from ray_tpu.utils.serialization import serialize_parts

    token = _capture.set([])
    try:
        meta, raws, total = serialize_parts(value)
        contained = _capture.get()  # ray-tpu: lint-ignore[RTL008] — ContextVar.get(), not a queue: returns immediately
    finally:
        _capture.reset(token)
    if contained:
        # serialize_parts may pickle twice (fast-path fallback) — dedupe
        # the captured refs so pins aren't double-counted
        seen, out = set(), []
        for c in contained:
            k = c.binary() if hasattr(c, "binary") else bytes(c)
            if k not in seen:
                seen.add(k)
                out.append(c)
        contained = out
    return meta, raws, total, contained


def _serialize_capturing(value: Any) -> tuple[bytes, list]:
    """Contiguous-blob variant of :func:`_serialize_parts_capturing`."""
    from ray_tpu.utils.serialization import assemble_parts

    meta, raws, _, contained = _serialize_parts_capturing(value)
    return assemble_parts(meta, raws), contained


class CoreWorker:
    """One per process. ``mode`` is "driver" or "worker"."""

    def __init__(
        self,
        address: str,
        mode: str,
        loop_runner: rpc.EventLoopThread,
        handler: Any = None,
        worker_id: Optional[WorkerID] = None,
        node_id: Optional[NodeID] = None,
        local_shm_dir: Optional[str] = None,
        listen_addr: str = "",
    ):
        from ray_tpu.core.memory_store import LocalMemoryStore

        self.mode = mode
        self.address = address
        self.loop_runner = loop_runner
        self.worker_id = worker_id or WorkerID.from_random()
        self.node_id = node_id
        self._put_counter = itertools.count()
        self._task_counter = itertools.count()
        self._lock = threading.Lock()
        self._handler = handler or _NullHandler()
        self._listen_addr = listen_addr
        self._reconnect_lock = threading.Lock()
        self._reconnect_cbs: list = []  # called with the fresh peer
        # Once a full reconnect window fails (controller truly gone) or
        # this process initiated the disconnect, later ConnectionLost
        # errors fail fast instead of burning another window each.
        self._reconnect_dead = False
        self._control_timeout: Optional[float] = 300.0  # pre-config fallback
        host, port = address.rsplit(":", 1)
        self.peer: rpc.Peer = loop_runner.run(rpc.connect(host, int(port), self._handler))
        self.peer.label = "controller"
        if mode == "driver":
            info = self._call("register_driver")
            self.node_id = NodeID.from_hex(info["head_node_id"])
            self.local_shm_dir = info["shm_dir"]
        else:
            info = self._call(
                "register_worker", self.worker_id, node_id, os.getpid(),
                listen_addr=listen_addr,
                pool=os.environ.get("RAY_TPU_WORKER_POOL", ""),
                # Spawn-time env identity (container images): the worker
                # was born into this env hash (runtime_env/container.py).
                env_hash=os.environ.get("RAY_TPU_PRESET_ENV_HASH", ""),
            )
            self.local_shm_dir = local_shm_dir
        self.session_dir = info["session_dir"]
        self.config = info["config"]
        self.inline_limit = self.config.get("max_inline_object_size", INLINE_LIMIT_FALLBACK)
        self._control_timeout = (
            float(self.config.get("control_call_timeout_s", 300.0)) or None
        )
        self.plasma = PlasmaClient(self.local_shm_dir)
        self._plasma_clients: dict[str, PlasmaClient] = {}
        # Owner-local memory store + direct actor transport (reference:
        # memory_store.cc; actor_task_submitter.h caller→actor push).
        self.memory_store = LocalMemoryStore()
        self.direct_enabled = bool(self.config.get("direct_actor_calls", True))
        self.direct_normal_enabled = bool(self.config.get("direct_normal_tasks", True))
        self._submitters: dict = {}  # ActorID -> ActorSubmitter
        self._direct_tasks: dict = {}  # TaskID -> ActorSubmitter (cancel routing)
        self._direct_returns: dict = {}  # return ObjectID -> TaskID
        self._normal_sub = None  # lazily-created NormalSubmitter
        # Batched caller-thread → loop handoff for direct submissions.
        self._direct_handoff = rpc.BatchedHandoff(
            self.loop_runner.loop, lambda item: item[0]._enqueue(item[1])
        )
        # Distributed ref counting: local ref table + periodic flush of
        # held/dropped transitions to the controller.
        self.refs = RefTracker()
        self._refs_closed = threading.Event()
        self._ref_flush_task = None
        self._async_errors: list = []
        set_ref_tracker(self.refs)
        # Memory census: call-site attribution at put/submit (the
        # ``memory_census`` config is the envelope A/B knob).
        from ray_tpu.core import memory_census

        memory_census.set_enabled(
            bool(self.config.get("memory_census", True))
        )
        if self.config.get("object_auto_gc", True):
            self._ref_flush_task = self.loop_runner.submit(self._ref_flush_loop())

    async def _ref_flush_loop(self):
        import asyncio

        interval = self.config.get("ref_flush_interval_ms", 200) / 1000.0
        me = self.worker_id.hex()
        while not self._refs_closed.is_set():
            await asyncio.sleep(interval)
            held, dropped = self.refs.drain()
            # Owner-local (never-promoted) objects don't exist in the
            # controller's directory: their GC is a local eviction, and
            # mentioning them to the controller would create leaked empty
            # records (reference: memory-store objects are owner-private).
            ms = self.memory_store
            g_held = [k for k in held if not ms.is_local_only(k)]
            g_dropped = []
            for k in dropped:
                local_only = ms.is_local_only(k)
                ms.evict(k)
                if not local_only:
                    g_dropped.append(k)
            if g_held or g_dropped:
                try:
                    await self.peer.notify("ref_update", me, g_held, g_dropped)
                except Exception:
                    return  # connection gone; controller reaps us on disconnect

    # ------------------------------------------------------------------
    def _call(self, method: str, *args, timeout: Optional[float] = None, **kwargs):
        """Sync controller RPC. Callers that pass no timeout get the
        bounded ``control_call_timeout_s`` default unless the method
        blocks by design (:data:`_UNBOUNDED_METHODS`). A connection loss
        triggers ONE bounded reconnect + re-register attempt (rides
        through a controller restart) before the error surfaces.

        The post-reconnect retry makes control calls AT-LEAST-ONCE: a
        request the controller executed whose response died with the
        connection is re-issued. Controller-restart rides are safe (the
        journal replay is the state), but a transient drop to a LIVE
        controller can duplicate a non-idempotent call — exactly-once
        needs per-request ids + controller-side dedup (roadmap)."""
        if timeout is None and method not in _UNBOUNDED_METHODS:
            timeout = self._control_timeout
        try:
            return self.loop_runner.run(self.peer.call(method, *args, **kwargs), timeout)
        except rpc.ConnectionLost:
            if not self.try_reconnect():
                raise
            return self.loop_runner.run(self.peer.call(method, *args, **kwargs), timeout)

    def on_reconnect(self, cb):
        """Register a callback invoked (from the reconnecting thread)
        with the fresh controller peer after a successful re-register."""
        self._reconnect_cbs.append(cb)

    def try_reconnect(self) -> bool:
        """Bounded reconnect + re-register after controller connection
        loss (jittered backoff within ``controller_reconnect_window_s``).
        Safe from any thread; concurrent callers coalesce on the lock.
        Returns True when ``self.peer`` is live again."""
        import random as _random
        import time as _time

        window = 0.0
        if isinstance(getattr(self, "config", None), dict):
            window = float(self.config.get("controller_reconnect_window_s", 0.0))
        if window <= 0 or self._reconnect_dead:
            return False
        resumed_peer = None
        with self._reconnect_lock:
            if not self.peer.closed:
                return True  # someone else already reconnected
            host, port = self.address.rsplit(":", 1)
            deadline = _time.monotonic() + window
            wait = 0.1
            last: Optional[BaseException] = None
            # Holding _reconnect_lock across the bounded dial/register
            # is the design: concurrent callers MUST coalesce on one
            # reconnect attempt  # ray-tpu: lint-ignore-file[RTL001]
            while _time.monotonic() < deadline:
                try:
                    peer = self.loop_runner.run(
                        rpc.connect(host, int(port), self._handler, retries=1),
                        timeout=10,
                    )
                    peer.label = "controller"
                    if self.mode == "driver":
                        self.loop_runner.run(peer.call("register_driver"), 10)
                    else:
                        self.loop_runner.run(
                            peer.call(
                                "register_worker", self.worker_id, self.node_id,
                                os.getpid(), listen_addr=self._listen_addr,
                                # Never re-advertise into a worker pool and
                                # mark busy: the restarted controller must
                                # not dispatch onto a possibly-mid-actor
                                # process it knows nothing about.
                                pool="",
                                env_hash=os.environ.get("RAY_TPU_PRESET_ENV_HASH", ""),
                                rejoining=True,
                            ),
                            10,
                        )
                    self.peer = peer
                    resumed_peer = peer
                    break
                except Exception as e:  # noqa: BLE001 — retry within window
                    if "re-registration refused" in str(e):
                        # Permanent: the live controller declared this
                        # process dead while it was away — further
                        # attempts get the identical refusal.
                        last = e
                        break
                    _time.sleep(min(wait * (0.5 + _random.random()),
                                    max(0.0, deadline - _time.monotonic())))
                    wait = min(wait * 1.7, 2.0)
                    last = e
            if resumed_peer is None:
                import logging

                logging.getLogger("ray_tpu.client").warning(
                    "controller reconnect failed after %.0fs: %s", window, last
                )
                self._reconnect_dead = True
                return False
        # Resume work (pubsub resubscribe, callbacks) issues RPCs of its
        # own — run it OUTSIDE the lock: a second connection loss here
        # re-enters try_reconnect on this same thread, which would
        # self-deadlock on the non-reentrant lock.
        self._resume_after_reconnect(resumed_peer)
        return True

    def _resume_after_reconnect(self, peer):
        import logging

        logging.getLogger("ray_tpu.client").warning(
            "reconnected to controller at %s (%s)", self.address, self.mode
        )
        # Ref-flush loop exits on connection loss — restart it.
        if self._ref_flush_task is not None and self._ref_flush_task.done():
            self._ref_flush_task = self.loop_runner.submit(self._ref_flush_loop())
        # Re-establish pubsub subscriptions (death watchers, etc.).
        try:
            from ray_tpu.experimental import pubsub

            pubsub._resubscribe(self)
        except Exception as e:  # noqa: BLE001 — subscriptions are best-effort
            logging.getLogger("ray_tpu.client").warning(
                "pubsub resubscribe failed: %s", e
            )
        for cb in list(self._reconnect_cbs):
            try:
                cb(peer)
            except Exception:  # noqa: BLE001 — one bad callback must not block others
                logging.getLogger("ray_tpu.client").exception(
                    "reconnect callback failed"
                )

    def _submit(self, method: str, *args, **kwargs) -> Future:
        return self.loop_runner.submit(self.peer.call(method, *args, **kwargs))

    # ------------------------------------------------------------------
    # Objects
    # ------------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        from ray_tpu.core import memory_census
        from ray_tpu.utils.serialization import assemble_parts

        # Creation-site attribution (reference: reference counting records
        # a call_site per ref for `ray memory`): captured before the
        # serialize so deep value graphs can't push the user frame out of
        # the bounded walk.
        site = memory_census.capture_callsite()
        oid = ObjectID.for_put(self.worker_id, next(self._put_counter))
        meta, raws, total, contained = _serialize_parts_capturing(value)
        if contained:
            self.promote_refs(contained)  # nested refs escape via the put
        if total <= self.inline_limit:
            self._call(
                "object_put_inline", oid, assemble_parts(meta, raws), False,
                contained or [], callsite=site,
            )
        else:
            # Single copy: parts go straight into the shm mapping.
            self.plasma.put_parts(oid, meta, raws, total)
            self._call(
                "object_put_shm", oid, total, self.node_id, False,
                contained or [], callsite=site,
            )
        ref = ObjectRef(oid)
        self.refs.attribute(oid.binary(), site)
        return ref

    def put_serialized(
        self, oid: ObjectID, data: bytes, is_error: bool = False,
        contained: Optional[list] = None, callsite: str = "",
    ):
        if contained:
            self.promote_refs(contained)
        if len(data) <= self.inline_limit:
            self._call(
                "object_put_inline", oid, data, is_error, contained or [],
                callsite=callsite,
            )
        else:
            self.plasma.put_bytes(oid, data)
            self._call(
                "object_put_shm", oid, len(data), self.node_id, is_error,
                contained or [], callsite=callsite,
            )

    def get(self, refs: Sequence[ObjectRef] | ObjectRef, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list: List[ObjectRef] = [refs] if single else list(refs)
        values = self._get_values([r.id for r in ref_list], timeout)
        return values[0] if single else values

    def get_async(self, refs: Sequence[ObjectRef]) -> Future:
        """Future-returning get (used by ObjectRef.future())."""
        fut: Future = Future()

        def _run():
            try:
                fut.set_result(self._get_values([r.id for r in refs]))
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_run, daemon=True).start()
        return fut

    def _get_values(self, oids: List[ObjectID], timeout: Optional[float] = None) -> List[Any]:
        self._check_async_errors()
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        # Partition: owner-local entries resolve in-process with ZERO
        # controller round-trips (reference: memory_store.cc Get); the
        # rest go through the controller directory.
        local: dict[bytes, Any] = {}
        remote: List[ObjectID] = []
        for oid in oids:
            e = self.memory_store.lookup(oid.binary())
            if e is not None and e.kind == "inline":
                local[oid.binary()] = e
            else:
                remote.append(oid)
        resp_fut = self._submit("object_get", remote, timeout) if remote else None
        local_values: dict[bytes, tuple] = {}
        shm_fallback: List[ObjectID] = []
        for oid in oids:
            e = local.get(oid.binary())
            if e is None:
                continue
            remain = None if deadline is None else max(0.0, deadline - _time.monotonic())
            try:
                payload, is_err = e.value(remain)
            except (TimeoutError, _CfTimeout):  # _CfTimeout: pre-3.11 alias
                if resp_fut is not None:
                    resp_fut.cancel()
                raise GetTimeoutError(f"get() timed out after {timeout}s")
            if e.kind == "shm":
                # resolved to a large result living in the global store
                shm_fallback.append(oid)
            else:
                local_values[oid.binary()] = (payload, is_err)
        metas = {}
        if resp_fut is not None:
            # bounded by the caller's get() deadline: the controller leg
            # resolves this future within the requested timeout (resp
            # carries the timed-out flag); unbounded only when the USER
            # asked get(timeout=None)  # ray-tpu: lint-ignore[RTL008]
            resp = resp_fut.result()
            if resp["timeout"]:
                raise GetTimeoutError(f"get() timed out after {timeout}s")
            metas = resp["metas"]
        if shm_fallback:
            remain = None if deadline is None else max(0.0, deadline - _time.monotonic())
            resp = self._call("object_get", shm_fallback, remain)
            if resp["timeout"]:
                raise GetTimeoutError(f"get() timed out after {timeout}s")
            metas.update(resp["metas"])
        out = []
        for oid in oids:
            entry = local_values.get(oid.binary())
            if entry is not None:
                payload, is_error = entry
                if isinstance(payload, Exception):
                    raise payload
                value = deserialize(payload)
            else:
                meta = metas[oid.hex()]
                kind = meta[0]
                if kind == "lost":
                    raise ObjectLostError(oid.hex(), "object lost and could not be reconstructed")
                if kind == "inline":
                    _, data, is_error = meta
                    # Objects are immutable: cache the fetched value so
                    # repeated gets are process-local (reference:
                    # memory_store.cc caches gotten small objects).
                    # promoted=True keeps ref flushes going to the
                    # controller; the entry evicts when local refs drop.
                    key = oid.binary()
                    self.memory_store.put(key, data, is_error)
                    self.memory_store.mark_promoted(key)
                    value = deserialize(data)
                else:
                    _, size, node_hex, shm_dir, is_error = meta
                    if deadline is None:
                        remain = None
                    else:
                        remain = deadline - _time.monotonic()
                        if remain <= 0:
                            raise GetTimeoutError(f"get() timed out after {timeout}s")
                    value = deserialize(
                        self._read_object(oid, size, node_hex, shm_dir, timeout=remain)
                    )
            if is_error:
                raise value
            out.append(value)
        return out

    def _plasma_for(self, shm_dir: str) -> PlasmaClient:
        if shm_dir == self.local_shm_dir:
            return self.plasma
        with self._lock:
            client = self._plasma_clients.get(shm_dir)
            if client is None:
                client = self._plasma_clients[shm_dir] = PlasmaClient(shm_dir)
            return client

    def _resolve_mapping(self, local: bool, shm_dir: str) -> "tuple[PlasmaClient, bool]":
        """(plasma client whose mapping serves this object on THIS node,
        whether a missing mapping means a cross-node pull is needed first).
        The one locality rule shared by the copying (`_read_object`) and
        pinned (`get_pinned_view`) read paths: remote objects map through
        the owner's shm_dir only when cross_node_shm says path-opens work
        (nodes sharing one host's filesystem, the co-located-cluster
        shortcut); otherwise they are pulled into this node's store."""
        if local:
            return self.plasma, False
        if not self.config.get("cross_node_shm", False):
            return self.plasma, True
        return self._plasma_for(shm_dir), False

    def _read_object(self, oid: ObjectID, size: int, node_hex: str, shm_dir: str,
                     timeout: Optional[float] = None) -> memoryview:
        local = self.node_id is not None and node_hex == self.node_id.hex()
        plasma, needs_pull = self._resolve_mapping(local, shm_dir)
        view = plasma.try_view(oid, size)
        if view is not None:
            return view
        if needs_pull:
            # Network data plane (reference: object_manager.cc Push/Pull):
            # the object lives on another node — pull it into THIS node's
            # store over the network, then map it locally.
            try:
                ok = self._call("object_pull", oid, self.node_id, timeout=timeout)
            except (TimeoutError, _CfTimeout):
                raise GetTimeoutError(
                    f"get() timed out pulling {oid.hex()[:8]} cross-node"
                )
            if not ok:
                raise ObjectLostError(oid.hex(), "cross-node object pull failed")
            missing = "object missing after pull"
        else:
            # Possibly spilled to disk — ask the owning node to restore it.
            if not self._call("object_ensure_local", oid, node_hex):
                raise ObjectLostError(oid.hex(), "object missing from store")
            missing = "object missing from store"
        view = plasma.try_view(oid, size)
        if view is None:
            raise ObjectLostError(oid.hex(), missing)
        return view

    def get_pinned_view(self, oid: ObjectID, timeout: Optional[float] = None):
        """Zero-copy read: resolve ``oid`` to a ``(memoryview, release)``
        pair over the node's shared-memory mapping, pinned against arena
        eviction until ``release()`` is called (the data layer's zero-copy
        block decode; reference: plasma client Get returning store buffers
        that the raylet pins while mapped). Returns None when the object is
        inline-tier, an error marker, or not mappable — callers fall back
        to a copying ``get``. Blocks until the object is ready."""
        e = self.memory_store.lookup(oid.binary())
        if e is not None:
            # Owner-local entry: wait for resolution (kind may flip from
            # inline to shm when a large result lands in the store).
            try:
                _, is_err = e.value(timeout)
            except (TimeoutError, _CfTimeout):
                raise GetTimeoutError(f"get() timed out after {timeout}s")
            if is_err or e.kind != "shm":
                return None
        resp = self._call("object_get", [oid], timeout)
        if resp["timeout"]:
            raise GetTimeoutError(f"get() timed out after {timeout}s")
        meta = resp["metas"][oid.hex()]
        if meta[0] != "shm":
            return None
        _, size, node_hex, shm_dir, is_error = meta
        if is_error:
            return None
        local = self.node_id is not None and node_hex == self.node_id.hex()
        plasma, _ = self._resolve_mapping(local, shm_dir)
        pv = plasma.view_pinned(oid, size)
        if pv is None:
            # Spilled, or living on another node: materialize locally
            # (pull / restore), then map again.
            try:
                self._read_object(oid, size, node_hex, shm_dir, timeout=timeout)
            except ObjectLostError:
                return None
            pv = plasma.view_pinned(oid, size)
        return pv

    def get_raw(self, oid: ObjectID) -> tuple[Any, bool]:
        """(value, is_error) without raising — used by arg resolution."""
        e = self.memory_store.lookup(oid.binary())
        if e is not None and e.kind == "inline":
            payload, is_err = e.value()
            if e.kind == "inline":  # may flip to shm while pending
                if isinstance(payload, Exception):
                    return payload, True
                return deserialize(payload), is_err
        resp = self._call("object_get", [oid], None)
        meta = resp["metas"][oid.hex()]
        if meta[0] == "lost":
            return ObjectLostError(oid.hex(), "lost"), True
        if meta[0] == "inline":
            return deserialize(meta[1]), meta[2]
        _, size, node_hex, shm_dir, is_error = meta
        return deserialize(self._read_object(oid, size, node_hex, shm_dir)), is_error

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1, timeout: Optional[float] = None):
        self._check_async_errors()
        import time as _time

        local_futs = {}  # ref -> Entry future (resolution == readiness)
        remote = []
        for r in refs:
            e = self.memory_store.lookup(r.id.binary())
            if e is not None:
                local_futs[r] = e.ensure_future()
            else:
                remote.append(r)
        if not local_futs:
            ready_hex = set(self._call("object_wait", [r.id for r in refs], num_returns, timeout))
            return self._split_wait(refs, ready_hex, num_returns)
        deadline = None if timeout is None else _time.monotonic() + timeout
        if not remote:
            import concurrent.futures as _cf

            pending = {f for f in local_futs.values() if not f.done()}
            while True:
                ready_hex = {r.id.hex() for r, f in local_futs.items() if f.done()}
                if len(ready_hex) >= num_returns or not pending:
                    return self._split_wait(refs, ready_hex, num_returns)
                remain = None if deadline is None else deadline - _time.monotonic()
                if remain is not None and remain <= 0:
                    return self._split_wait(refs, ready_hex, num_returns)
                done, pending = _cf.wait(
                    pending, timeout=remain, return_when=_cf.FIRST_COMPLETED
                )
                if not done and remain is not None:
                    return self._split_wait(
                        refs,
                        {r.id.hex() for r, f in local_futs.items() if f.done()},
                        num_returns,
                    )
        # Mixed local/remote: poll the controller in short slices while
        # local futures resolve independently (rare path — a wait over
        # both direct-call results and globally-owned objects).
        remote_ready: set = set()
        while True:
            ready_hex = {r.id.hex() for r, f in local_futs.items() if f.done()} | remote_ready
            remain = None if deadline is None else deadline - _time.monotonic()
            need = num_returns - len(ready_hex)
            if need <= 0 or (remain is not None and remain <= 0):
                return self._split_wait(refs, ready_hex, num_returns)
            slice_t = 0.05 if remain is None else max(0.0, min(0.05, remain))
            remote_ready |= set(
                self._call("object_wait", [r.id for r in remote], max(need, 1), slice_t)
            )

    @staticmethod
    def _split_wait(refs, ready_hex, num_returns):
        ready, not_ready = [], []
        for r in refs:
            (ready if r.id.hex() in ready_hex and len(ready) < num_returns else not_ready).append(r)
        return ready, not_ready

    def free(self, refs: Sequence[ObjectRef]):
        remote = []
        for r in refs:
            key = r.id.binary()
            local_only = self.memory_store.is_local_only(key)
            self.memory_store.evict(key)  # drop local copy either way
            if not local_only:
                remote.append(r.id)
        if remote:
            self._call("object_free", remote)

    # ------------------------------------------------------------------
    # Tasks
    # ------------------------------------------------------------------
    def build_args(self, args: tuple, kwargs: dict) -> "tuple[bytes, List[ObjectID], list]":
        """Returns (blob, deps). Top-level refs become _RefMarker deps
        (resolved before dispatch); refs *nested inside* arg values are
        captured during serialization and pinned for the task's lifetime
        via ``last_captures`` (the reference's submitted-task references,
        reference_count.h UpdateSubmittedTaskReferences)."""
        deps: List[ObjectID] = []

        def mark(v):
            if isinstance(v, ObjectRef):
                deps.append(v.id)
                return _RefMarker(v.id)
            return v

        margs = tuple(mark(a) for a in args)
        mkwargs = {k: mark(v) for k, v in kwargs.items()}
        blob, contained = _serialize_capturing((margs, mkwargs))
        return blob, deps, contained

    # Submission is pipelined: fire-and-forget notify, return refs
    # immediately (reference: NormalTaskSubmitter queues without blocking
    # the caller; return ids are deterministic). Submission-side failures
    # surface on the next sync point via _check_async_errors; task-side
    # failures surface through the returned refs as usual.
    def _note_async_error(self, fut):
        exc = fut.exception() if not fut.cancelled() else None
        if exc is not None:
            self._async_errors.append(exc)

    def _check_async_errors(self):
        if self._async_errors:
            raise self._async_errors.pop(0)

    def _attribute_returns(self, refs: List[ObjectRef]):
        """Attribute a submission's return refs to the user call-site
        (the ``.remote()`` line). One bounded stack walk per submit; the
        per-code-object intern cache makes steady-state cost a dict hit."""
        from ray_tpu.core import memory_census

        site = memory_census.capture_callsite()
        if site:
            for r in refs:
                self.refs.attribute(r.id.binary(), site)

    def _submit_pipelined(self, spec: TaskSpec, captures: Optional[list]) -> List[ObjectRef]:
        self._check_async_errors()
        fut = self.loop_runner.submit(
            self.peer.notify("submit_task", spec, captures or [])
        )
        fut.add_done_callback(self._note_async_error)
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        self._attribute_returns(refs)
        return refs

    def submit_task(self, spec: TaskSpec, captures: Optional[list] = None) -> List[ObjectRef]:
        if (
            self.direct_normal_enabled
            and spec.task_type == TaskType.NORMAL_TASK
            and not spec.is_streaming
            # Container envs need spawn-time (image-wrapped) workers,
            # which only the controller's dispatch path provisions; the
            # direct-lease pool hands out host workers.
            and not (spec.runtime_env or {}).get("image_uri")
        ):
            return self._submit_normal_direct(spec, captures)
        self.promote_refs(list(spec.dependencies) + list(captures or []))
        return self._submit_pipelined(spec, captures)

    def _submit_normal_direct(self, spec: TaskSpec, captures: Optional[list]) -> List[ObjectRef]:
        """Lease-based direct submission (reference:
        normal_task_submitter.cc). Top-level owner-local deps travel
        inline with the push — no promotion; captured (nested) refs must
        be globally resolvable by the executing worker → promote."""
        self._check_async_errors()
        if captures:
            self.promote_refs(captures)
        rids = spec.return_ids()
        self.memory_store.register_pending([oid.binary() for oid in rids])
        refs = [ObjectRef(oid) for oid in rids]
        self._attribute_returns(refs)
        if spec.dependencies or captures:
            pins = [ObjectRef(d) for d in spec.dependencies]
            pins += [
                ObjectRef(c if isinstance(c, ObjectID) else ObjectID(c))
                for c in (captures or [])
            ]
        else:
            pins = None
        self._normal_submitter().submit(spec, pins)
        return refs

    def _normal_submitter(self):
        sub = self._normal_sub
        if sub is None:
            with self._lock:
                if self._normal_sub is None:
                    from ray_tpu.core.normal_direct import NormalSubmitter

                    self._normal_sub = NormalSubmitter(self)
                sub = self._normal_sub
        return sub

    def create_actor(self, spec: TaskSpec, captures: Optional[list] = None):
        self.promote_refs(list(spec.dependencies) + list(captures or []))
        self._call("create_actor", spec, captures or [])

    def submit_actor_task(self, spec: TaskSpec, captures: Optional[list] = None) -> List[ObjectRef]:
        if not self.direct_enabled or spec.is_streaming:
            self.promote_refs(list(spec.dependencies) + list(captures or []))
            return self._submit_pipelined(spec, captures)
        # Direct caller→actor push (reference: actor_task_submitter.h).
        # Top-level ref deps the caller owns locally travel inline with
        # the push; nested (captured) refs must be globally resolvable by
        # the executing worker → promote.
        self._check_async_errors()
        if captures:
            self.promote_refs(captures)
        rids = spec.return_ids()
        self.memory_store.register_pending([oid.binary() for oid in rids])
        refs = [ObjectRef(oid) for oid in rids]
        self._attribute_returns(refs)
        # Pin args (deps + captures) until the reply lands — the owner-side
        # equivalent of the reference's submitted-task references.
        if spec.dependencies or captures:
            pins = [ObjectRef(d) for d in spec.dependencies]
            pins += [ObjectRef(c if isinstance(c, ObjectID) else ObjectID(c)) for c in (captures or [])]
        else:
            pins = None
        sub = self._submitter_for(spec.actor_id)
        self._direct_tasks[spec.task_id] = sub
        for oid in rids:
            self._direct_returns[oid] = spec.task_id
        sub.submit(spec, pins)
        return refs

    def _queue_direct(self, submitter, call):
        self._direct_handoff.push((submitter, call))

    def _submitter_for(self, actor_id):
        with self._lock:
            sub = self._submitters.get(actor_id)
            if sub is None:
                from ray_tpu.core.direct import ActorSubmitter

                sub = self._submitters[actor_id] = ActorSubmitter(self, actor_id)
            return sub

    def _direct_task_done(self, spec: TaskSpec):
        self._direct_tasks.pop(spec.task_id, None)
        for oid in spec.return_ids():
            self._direct_returns.pop(oid, None)

    def promote_refs(self, oids: Sequence, timeout: Optional[float] = None):
        """Publish owner-local objects whose refs are escaping this
        process to the controller directory (promotion-on-escape — the
        reference instead resolves owners from the ref; see
        memory_store.py module docstring). NON-BLOCKING: ready values are
        published via a notify on the controller connection (ordered
        before any subsequent submit on the same connection); pending
        entries are flagged and publish when their reply resolves them —
        the controller's dependency wait covers the gap."""
        from ray_tpu.utils.serialization import serialize

        for oid in oids:
            oid = oid if isinstance(oid, ObjectID) else ObjectID(oid)
            key = oid.binary()
            status = self.memory_store.request_promotion(key)
            if status != "ready":
                continue  # done / gone / deferred-to-resolve
            e = self.memory_store.lookup(key)
            if e is None:
                continue
            payload, is_err = e.value(0)
            if e.kind == "shm":
                continue  # resolved to a global shm object
            if isinstance(payload, Exception):
                payload, is_err = serialize(payload), True
            self.loop_runner.submit(
                self.peer.notify("object_put_inline", oid, bytes(payload), is_err, [])
            )
            self.memory_store.mark_promoted(key)

    def next_task_id(self) -> TaskID:
        return TaskID.for_index(self.worker_id, next(self._task_counter))

    # ------------------------------------------------------------------
    # Control
    # ------------------------------------------------------------------
    def kill_actor(self, actor_id, no_restart: bool):
        self._call("kill_actor", actor_id, no_restart)

    def wait_actor_ready(self, actor_id, timeout: Optional[float] = None):
        return self._call("wait_actor_ready", actor_id, timeout=timeout)

    def get_actor_by_name(self, name: str):
        return self._call("get_actor_by_name", name)

    def cancel_task(self, task_id: TaskID, force: bool):
        sub = self._direct_tasks.get(task_id)
        if sub is not None:
            sub.cancel_threadsafe(task_id)
            return
        if self._normal_sub is not None and self._normal_sub.owns_task(task_id):
            self._normal_sub.cancel_threadsafe(task_id)
            return
        self._call("cancel_task", task_id, force)

    def cancel_by_object(self, oid: ObjectID, force: bool):
        tid = self._direct_returns.get(oid)
        if tid is None and self._normal_sub is not None:
            tid = self._normal_sub.task_for_return(oid)
        if tid is not None:
            self.cancel_task(tid, force)
            return
        self._call("cancel_by_object", oid, force)

    # KV
    def kv_put(self, ns: str, key: bytes, value: bytes, overwrite: bool = True) -> bool:
        return self._call("kv_put", ns, key, value, overwrite)

    def kv_get(self, ns: str, key: bytes) -> Optional[bytes]:
        return self._call("kv_get", ns, key)

    def kv_del(self, ns: str, key: bytes) -> bool:
        return self._call("kv_del", ns, key)

    def kv_keys(self, ns: str, prefix: bytes) -> List[bytes]:
        return self._call("kv_keys", ns, prefix)

    def drain_node(self, node_id: NodeID, timeout_s: float = 300.0) -> bool:
        return self._call("drain_node", node_id, timeout_s)

    # PGs
    def pg_create(self, bundles, strategy: str, name: str):
        return self._call("pg_create", bundles, strategy, name)

    def pg_wait_ready(self, pg_id, timeout):
        return self._call("pg_wait_ready", pg_id, timeout)

    def pg_remove(self, pg_id):
        return self._call("pg_remove", pg_id)

    def pg_shrink(self, pg_id, bundle_indices):
        return self._call("pg_shrink", pg_id, list(bundle_indices))

    def pg_table(self):
        return self._call("pg_table")

    def pg_bundle_nodes(self, pg_id):
        return self._call("pg_bundle_nodes", pg_id)

    # Introspection
    def cluster_resources(self):
        return self._call("cluster_resources")

    def available_resources(self):
        return self._call("available_resources")

    def list_state(self, what: str, **kwargs):
        return self._call(f"list_{what}", **kwargs)

    def disconnect(self):
        self._reconnect_dead = True  # deliberate: never dial back out
        self._refs_closed.set()
        if self._ref_flush_task is not None:
            self._ref_flush_task.cancel()
        try:
            self.loop_runner.run(self.peer.close(), timeout=2)
        except Exception:
            pass


class _NullHandler:
    def on_disconnect(self, peer):
        pass

    # Every CoreWorker-embedded process answers the profiling fan-out —
    # drivers AND handler-less admin connections (cluster_utils,
    # autoscaler monitor): a wedged driver (deadlocked ray_tpu.get,
    # stuck user loop) is exactly what `ray-tpu profile stacks` exists
    # to see.
    def rpc_stack_dump(self, peer):
        from ray_tpu.utils.stack_dump import dump_all_threads

        return dump_all_threads()

    def rpc_dump_stacks(self, peer):
        from ray_tpu.util import profiling

        return profiling.dump_stacks()

    def rpc_profile_cpu(self, peer, duration_s: float = 10.0, hz: float = 100.0):
        from ray_tpu.util import profiling

        return profiling.sample_async(duration_s, hz)

    def rpc_dump_memory(self, peer, limit: int = 1000):
        """This process's object/memory census (`ray-tpu memory` fan-out
        leg): open local refs by creation call-site, owner-local memory
        store occupancy, live zero-copy pins. Drivers hold refs too — a
        leak is as often the driver's list as an actor's."""
        from ray_tpu.core import memory_census

        return memory_census.dump(limit)

    # The controller broadcasts worker log lines / follow-mode records to
    # every driver connection; admin connections (cluster_utils, monitor)
    # have no console to print them to. Drop the pushes silently — a
    # missing handler would log an ERROR per batch, which the log plane
    # then ships back as a head-attributed error signature (self-inflicted
    # spike noise).
    def rpc_log_batch(self, peer, batch):
        pass

    def rpc_log_records(self, peer, batch):
        pass


class DriverHandler(_NullHandler):
    """Driver-side handlers for controller pushes (reference: the driver
    prints worker log lines — worker.py print_to_stdstream)."""

    def rpc_log_batch(self, peer, batch):
        from ray_tpu.core.log_monitor import print_to_driver

        print_to_driver(batch)

    def rpc_log_records(self, peer, batch):
        """Structured follow-mode records (``ray-tpu logs --follow``):
        the controller pushes filtered sidecar records; the registered
        sink (or a default stderr renderer) consumes them."""
        from ray_tpu.core.log_monitor import deliver_records

        deliver_records(batch)

    def rpc_pubsub_msg(self, peer, channel: str, message):
        from ray_tpu.experimental.pubsub import _deliver

        _deliver(channel, message)
