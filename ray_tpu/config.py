"""Global config table.

The reference defines 217 ``RAY_CONFIG(type, name, default)`` entries
overridable via ``RAY_<name>`` env vars (reference:
src/ray/common/ray_config_def.h). Same pattern here: a declarative table,
env-var override ``RAY_TPU_<NAME>``, plus per-``init`` ``_system_config``
dict overrides.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any


def _env(name: str, default):
    raw = os.environ.get(f"RAY_TPU_{name.upper()}")
    if raw is None:
        return default
    t = type(default)
    if t is bool:
        return raw.lower() in ("1", "true", "yes")
    if t in (int, float, str):
        return t(raw)
    return json.loads(raw)


@dataclass
class Config:
    # --- object store ---
    # Objects at or below this size are carried inline through the control
    # plane instead of the shared-memory store (reference default 100KB:
    # ray_config_def.h ``max_direct_call_object_size``).
    max_inline_object_size: int = 100 * 1024
    # Per-node shared-memory store capacity (bytes). 0 = auto (30% of RAM).
    object_store_memory: int = 0
    # Chunk size for node-to-node object transfer (reference 64MB chunks:
    # object_manager.cc).
    object_transfer_chunk_bytes: int = 8 * 1024 * 1024
    # Allow readers to mmap ANOTHER node's shared-memory store directly —
    # only valid when all "nodes" share one host's filesystem (the
    # single-host simulation shortcut). Off (default) = cross-node reads
    # go through the chunked network data plane like the reference.
    cross_node_shm: bool = False
    # Spill to disk when store is above this fraction.
    object_spilling_threshold: float = 0.8
    spill_directory: str = ""

    # --- scheduler ---
    # Hybrid policy: pack onto lower-index nodes until utilization crosses
    # this threshold, then spread (reference:
    # raylet/scheduling/policy/hybrid_scheduling_policy.h:50).
    scheduler_spread_threshold: float = 0.5
    # Max tasks a single lease dispatch round hands to one worker.
    max_tasks_in_flight_per_worker: int = 10
    worker_lease_timeout_s: float = 30.0
    # Kill switch for the native C++ scheduling core (falls back to the
    # pure-Python policy path). Env override: RAY_TPU_DISABLE_NATIVE_SCHED.
    disable_native_sched: bool = False

    # --- workers ---
    # Prestarted workers per node (reference prestarts 1/CPU:
    # raylet/worker_pool.h:365).
    prestart_workers: bool = True
    worker_register_timeout_s: float = 60.0
    idle_worker_killing_time_s: float = 300.0
    maximum_startup_concurrency: int = 8

    # --- memory / OOM (reference: memory_monitor.h, ray_config_def.h
    # memory_usage_threshold / memory_monitor_refresh_ms) ---
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_ms: int = 250  # 0 disables the monitor
    worker_killing_policy: str = "retriable_fifo"  # or "group_by_owner"

    # --- fault tolerance ---
    health_check_period_s: float = 1.0
    health_check_failure_threshold: int = 5
    task_retry_delay_s: float = 0.05
    actor_restart_delay_s: float = 0.1
    # Default bound for control-plane RPCs issued without an explicit
    # timeout (register/kv/pg-admin/lease bookkeeping/...). Methods that
    # block by DESIGN (object get/wait, streams, pg readiness, drains)
    # are exempt — see client._UNBOUNDED_METHODS. A wedged controller
    # then surfaces as a timeout error instead of a process hung forever.
    control_call_timeout_s: float = 300.0
    # Controller-connection loss: workers, agents, and drivers attempt to
    # reconnect + re-register with jittered backoff for this long before
    # treating the controller as gone (worker/agent exit; driver raises).
    # Rides through a controller restart on the same address when the
    # persistence journal is intact. 0 = legacy exit-on-first-disconnect.
    controller_reconnect_window_s: float = 10.0
    # fsync the GCS journal on every append (reference analogue: Redis
    # persistence guarantees for GCS FT). Off by default: a torn tail is
    # detected and dropped on replay, and the journal is for whole-process
    # crashes, not host power loss.
    gcs_journal_fsync: bool = False

    # --- direct transport ---
    # Push actor tasks straight from the caller to the actor's worker
    # (reference: actor_task_submitter.h caller→actor gRPC); results land
    # in the caller's owner-local memory store. Off → every call routes
    # through the controller (the pre-round-2 path).
    direct_actor_calls: bool = True
    # Lease-based direct submission for NORMAL tasks (reference:
    # normal_task_submitter.cc worker leasing + PushNormalTask): the
    # caller leases a worker (controller does placement only; the node
    # agent owns the local free-worker view) and pushes tasks straight to
    # it, reusing the lease across a scheduling key's queue. Off → tasks
    # dispatch through the controller loop (the round-2 path).
    direct_normal_tasks: bool = True
    # Pushes in flight per leased worker (reference:
    # max_tasks_in_flight_per_worker pipelining) — 2 keeps the worker's
    # execution thread fed while the previous reply is on the wire.
    max_tasks_in_flight_per_lease: int = 2
    # Outstanding lease requests + held leases per scheduling key
    # (reference: max_pending_lease_requests_per_scheduling_category).
    max_leases_per_scheduling_key: int = 10
    # Batched control plane (round 17): one rpc_lease_batch round-trip
    # grants up to N leases per scheduling key, and pushes to an
    # already-leased worker coalesce into one framed push_task_batch RPC
    # with ONE gathered reply. Dynamic windows (grow on full grants /
    # clean batch completion, shrink on spillback / failure) replace the
    # static per-lease and per-key caps above, which then only serve the
    # legacy path. Off = the round-13 per-task path (the bench A/B knob).
    lease_batching: bool = True
    # Cap on leases granted per batch request — also the ceiling of the
    # per-key dynamic lease window.
    lease_batch_max: int = 16
    # Cap on tasks per push_task_batch frame — also the ceiling of the
    # per-lease dynamic in-flight window.
    task_push_batch_max: int = 64

    # --- control plane ---
    raylet_heartbeat_period_s: float = 0.5
    pubsub_batch_size: int = 1000
    # Topic-bus resource sync (round 17): capacity changes publish
    # coalesced per-node availability deltas on RESOURCES_CHANNEL no
    # more often than this; subscribers mirror push-on-change instead of
    # polling per sweep. 0 = publish every change uncoalesced.
    resource_broadcast_min_interval_ms: int = 100
    # Periodic full-snapshot reconciliation for topic-bus mirrors
    # (out-of-order / dropped deltas self-heal within one period).
    resource_reconcile_interval_s: float = 10.0
    task_event_buffer_size: int = 100000
    # Worker-side task-event flush cadence. The state API is eventually
    # consistent for direct-push tasks (reference: GCS task events are
    # buffered the same way); short period = snappy `list_tasks`.
    event_flush_period_s: float = 0.25

    # --- distributed ref counting / object GC ---
    # Free objects no process references (reference: reference_count.cc
    # ownership GC). Off → objects live for the session (freed only by
    # ray_tpu.internal.free or store eviction).
    object_auto_gc: bool = True
    # Worker-side batch flush cadence for local-ref zero crossings.
    # COUPLING: two-phase GC safety requires gc_sweep_interval_ms >=
    # 2 * ref_flush_interval_ms — a GC-marked object must survive one full
    # sweep so a borrower's in-flight "held" flush can land before the
    # free. _validate() clamps the sweep interval to keep the invariant.
    ref_flush_interval_ms: int = 200
    # Controller GC sweep debounce after a ref update arrives (see the
    # coupling note on ref_flush_interval_ms).
    gc_sweep_interval_ms: int = 1000

    # --- observability ---
    # App-metric flush cadence (reference: metrics_report_interval_ms).
    metrics_report_interval_ms: int = 2000
    # 0 = pick a free port for the controller's HTTP observability endpoint
    # (/metrics Prometheus text + /api/v0/* state JSON); -1 disables it.
    dashboard_port: int = 0
    # Node/device telemetry poll cadence (host CPU/mem + object store in
    # the agents' controller heartbeat; per-device HBM + compile stats in
    # workers' device_telemetry reports). 0 disables both loops.
    node_telemetry_interval_ms: int = 2000
    # Recompilation-storm detector: >= threshold compiles of the SAME
    # function name inside the window flags a storm (warning log + state
    # API + jax_recompile_storms_total).
    compile_storm_threshold: int = 5
    compile_storm_window_s: float = 60.0
    # Per-metric cap on distinct label sets: series past the cap are
    # dropped (counted in metrics_series_dropped_total) so per-request or
    # per-task tags can't blow up the registry/controller/Prometheus.
    metrics_max_series_per_metric: int = 200
    # Control-plane flight recorder (core/lifecycle.py): task/actor/PG/
    # lease/worker state-transition events with per-state dwell times and
    # why-pending attribution, aggregated controller-side and exposed via
    # state.summarize_lifecycle() / `ray-tpu timeline`. Off = near-zero
    # overhead (the envelope A/B knob).
    lifecycle_events: bool = True
    # Controller-side event ring bound (newest N transitions kept).
    lifecycle_ring_size: int = 20000
    # Per-(kind, state) dwell sample ring bound (percentile source).
    lifecycle_dwell_samples: int = 4096

    # --- object & memory observability (core/memory_census.py) ---
    # Master switch for creation call-site attribution + the per-process
    # ref census + the controller's leak/pressure detectors (the
    # envelope A/B knob: benchmarks/envelope.py --no-memory-census).
    memory_census: bool = True
    # Bounded call-site intern table: past the cap every new site
    # collapses into "(other)" so census groups / leak-trend entries /
    # metric tags built from call-sites stay bounded.
    memory_callsite_cap: int = 512
    # Leak detector: flag a call-site whose open-object count rises
    # monotonically across this many consecutive census sweeps (one
    # sweep per node_telemetry_interval_ms) ...
    memory_leak_sweeps: int = 5
    # ... and sits at or above this floor (small transients don't flag).
    memory_leak_min_refs: int = 32
    # Store-pressure incident trigger: object-store occupancy at/above
    # this fraction fires PR 9's incident machinery with a memory
    # autopsy bundle (0 disables the occupancy trigger).
    memory_incident_occupancy_pct: float = 0.95
    # ... or this many spill operations within one census sweep
    # (eviction-loop churn; 0 disables the churn trigger).
    memory_incident_spill_churn: int = 200

    # --- log plane (core/log_plane.py) ---
    # Master switch for structured log capture: every worker (and driver)
    # stamps logging records + stdout/stderr lines + task tracebacks with
    # {node, worker, task, severity, ts} into a bounded JSONL sidecar
    # next to the raw log, ships ERROR records to the controller's error
    # index, and answers the cluster-wide log search fan-out. The
    # envelope A/B knob (benchmarks/envelope.py log-churn arm).
    log_structured: bool = True
    # Size cap for worker log files — BOTH the raw worker-*.log (rotated
    # copy-truncate, the redirected-stdout fd keeps appending) and the
    # structured .jsonl sidecar (rotated by rename). One rotated ``.1``
    # half is kept, like the PR 6 span sinks — disk is bounded at ~2x
    # the cap per file.
    log_rotate_bytes: int = 64 * 1024 * 1024
    # Worker→controller shipping cadence for ERROR/exception records
    # (only those ship; the full firehose stays in node-local sidecars
    # reached by the search fan-out).
    log_ship_interval_ms: int = 1000
    # Bounded error-signature index on the controller (same bounded-
    # intern pattern as the memory census CallsiteTable): past the cap
    # new signatures collapse into "(other)".
    log_error_index_size: int = 256
    # Error-rate-spike incident trigger: this many ERROR records ingested
    # within one telemetry sweep fires the PR 9 incident machinery with
    # the offending log tail attached (0 disables).
    log_error_spike_threshold: int = 50

    # --- self-healing health plane (core/health.py + util/actuators.py) ---
    # Master switch for the observe→act loop: detector signals (leak /
    # pressure / storm / error-spike) drive bounded, audited actuators.
    # Off = detectors keep writing autopsies only (the pre-PR-16 world;
    # also the envelope A/B knob).
    health_actuators: bool = True
    # Comma-separated actuator names forced into dry-run (decision made
    # + audited + lifecycle event, side effect suppressed). "*" = all.
    health_dry_run: str = ""
    # Per-(actuator, target) cooldown: the same remedy never re-fires at
    # the same target inside this window.
    health_action_cooldown_s: float = 30.0
    # Global budget across all actuators (a detector storm must not turn
    # the health plane into its own denial of service).
    health_max_actions_per_min: int = 6
    # error-spike quarantine: hard scheduler avoid of the offending node
    # (drain semantics) for this long.
    health_quarantine_s: float = 60.0
    # store-pressure admission throttle: soft scheduler avoid (node moves
    # to the back of placement order) for this long.
    health_throttle_s: float = 30.0
    # store-pressure proactive spill target: spill LRU entries until the
    # store's file-tier occupancy is at or below this fraction.
    health_spill_target_pct: float = 0.6
    # memory-leak nudge: at most this many holder processes get the
    # gc/ref-reclamation RPC per action.
    health_nudge_max_procs: int = 8
    # Bounded action audit ring in the controller.
    health_audit_ring: int = 256

    # --- profiling (util/profiling.py) ---
    # Default sample rate for on-demand `ray-tpu profile cpu` runs.
    profiling_sample_hz: int = 100
    # Continuous low-rate background sampler feeding the incident ring
    # (0 = off, the default; ~5-20 Hz keeps overhead well under the 3%
    # budget measured by bench.py profiling_overhead_pct).
    profiling_continuous_hz: float = 0.0
    # How many seconds of recent samples the incident ring retains.
    profiling_ring_s: float = 60.0
    # Incident auto-capture master switch: detector hooks (lockwatch
    # long-hold/cycle, recompile storms, SLO breaches) flush capture
    # bundles under <session>/incidents/.
    profiling_incidents: bool = True
    # Newest N incident bundles kept on disk (oldest pruned at write).
    profiling_incident_keep: int = 20
    # Per-trigger rate limit between captures in one process.
    profiling_incident_min_interval_s: float = 30.0
    # Serve TTFT SLO-breach capture threshold in ms (0 = disabled).
    profiling_slo_ttft_ms: float = 0.0

    # --- fault injection (tests only; reference:
    # python/ray/tests/chaos/chaos_network_delay.yaml injects network
    # latency with k8s traffic shaping — here the agents' chunk server
    # sleeps per chunk, stretching transfers so chaos can land mid-pull) ---
    chaos_fetch_delay_ms: int = 0

    # --- misc ---
    temp_dir: str = field(default_factory=lambda: os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu"))
    log_to_driver: bool = True

    def __post_init__(self):
        self._validate()

    def _validate(self):
        # Two-phase GC safety (see ref_flush_interval_ms): clamp rather
        # than raise so a user tuning one knob can't silently break
        # borrowed-object liveness.
        floor = 2 * self.ref_flush_interval_ms
        if self.gc_sweep_interval_ms < floor:
            import logging

            logging.getLogger("ray_tpu.config").warning(
                "gc_sweep_interval_ms=%d raised to %d (must be >= 2x "
                "ref_flush_interval_ms for two-phase GC safety)",
                self.gc_sweep_interval_ms, floor,
            )
            self.gc_sweep_interval_ms = floor
        return self

    def apply_overrides(self, overrides: dict[str, Any] | None):
        if not overrides:
            return self
        for k, v in overrides.items():
            if not hasattr(self, k):
                raise ValueError(f"Unknown config key: {k}")
            setattr(self, k, v)
        return self._validate()

    @classmethod
    def from_env(cls) -> "Config":
        cfg = cls()
        for f in fields(cls):
            setattr(cfg, f.name, _env(f.name, getattr(cfg, f.name)))
        return cfg._validate()

    def to_dict(self) -> dict:
        return {f.name: getattr(self, f.name) for f in fields(self)}


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config.from_env()
    return _global_config


def set_config(cfg: Config):
    global _global_config
    _global_config = cfg
