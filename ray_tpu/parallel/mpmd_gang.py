"""Cross-process MPMD pipeline: per-stage gangs in one jax.distributed
runtime, activations crossing stage (and host) boundaries on the
collective fabric.

Reference: the reference's compiled DAGs run pipeline stages as actors
on different NODES with NCCL device channels between them
(python/ray/experimental/channel/torch_tensor_nccl_channel.py:190,
nccl_group.py:23, dag/dag_node_operation.py op-graph schedules). The
TPU-native shape replaces NCCL p2p with hop_bridge.HopBridge — a tiny
SPMD program over the two stages' device rows that both gangs dispatch
at the same schedule point, so XLA routes the activation over ICI/DCN
(gloo on the CPU simulation).

Topology: the global device list (sorted process-major) splits into
``num_stages`` contiguous equal groups. A process "participates" in a
stage when it owns any of that stage's devices — one process may own
several stages (the single-process degenerate case runs the exact same
code), and one stage may span several processes (its stage programs then
run SPMD across that gang).

Every participating process executes the SAME Python schedule; per-op
guards keep each process to its own stages plus the bridges adjacent to
them. Loss math is the ``full_head`` mode of parallel/mpmd (one head
over the re-assembled batch) built from the SAME stage_fn/head builders,
so the loss matches the in-graph GPipe loss bit-for-bit.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import transformer as tf
from ray_tpu.parallel.hop_bridge import HopBridge
from ray_tpu.parallel.mpmd import (
    make_embed_bwd,
    make_head_loss,
    make_stage_bwd,
    make_stage_fn,
)


@dataclass
class _GangStage:
    index: int
    devices: List[Any]
    mesh: Mesh
    sharding: NamedSharding
    local: bool  # this process owns devices in the stage
    fwd: Optional[Callable]
    bwd: Optional[Callable]
    layer_shardings: Any = None  # per-leaf shardings of the stage's layers


def _local_copy(value) -> np.ndarray:
    """Host copy of a group-replicated global array via its first
    addressable shard (float()/np.asarray need full addressability)."""
    return np.asarray(value.addressable_shards[0].data)


class MpmdGangPipeline:
    """MPMD transformer pipeline across a jax.distributed gang."""

    def __init__(self, cfg: tf.TransformerConfig, num_stages: int, attn_fn=None,
                 stage_tp: int = 1):
        from ray_tpu.parallel import mesh as mesh_lib

        self.cfg = cfg
        self.num_stages = num_stages
        self.stage_tp = stage_tp
        devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
        assert len(devices) % num_stages == 0, (len(devices), num_stages)
        assert cfg.n_layers % num_stages == 0, (cfg.n_layers, num_stages)
        per = len(devices) // num_stages
        assert per % stage_tp == 0, (per, stage_tp)
        rep = per // stage_tp
        my_pid = jax.process_index()

        # tp inside a stage keeps activations replicated at the stage
        # boundary (Megatron contract), so the hop bridge is unchanged;
        # params are tp-sharded which needs single-owner commits.
        self._stage_plan = mesh_lib.MeshPlan(tp=stage_tp)
        all_specs = mesh_lib.param_specs(cfg, self._stage_plan)
        layer_specs = all_specs["layers"]

        stage_fn = make_stage_fn(cfg, attn_fn)
        bwd_fn = make_stage_bwd(stage_fn)
        self.stages: List[_GangStage] = []
        for s in range(num_stages):
            devs = devices[s * per : (s + 1) * per]
            owners = {d.process_index for d in devs}
            if stage_tp > 1 and len(owners) > 1:
                raise NotImplementedError(
                    "stage_tp > 1 needs each stage owned by one process "
                    "(stage-per-host MPMD); multi-process tp stages would "
                    f"need sharded cross-process commits (stage {s} spans "
                    f"processes {sorted(owners)})"
                )
            mesh = Mesh(
                np.array(devs).reshape(rep, 1, stage_tp), ("rep", "fsdp", "tp")
            )
            shard = NamedSharding(mesh, P())
            lshard = jax.tree.map(
                lambda sp: NamedSharding(mesh, sp), layer_specs,
                is_leaf=lambda x: isinstance(x, P),
            )
            local = any(d.process_index == my_pid for d in devs)
            self.stages.append(
                _GangStage(
                    index=s,
                    devices=devs,
                    mesh=mesh,
                    sharding=shard,
                    local=local,
                    fwd=jax.jit(stage_fn, out_shardings=shard) if local else None,
                    bwd=jax.jit(bwd_fn, out_shardings=(shard, lshard)) if local else None,
                    layer_shardings=lshard,
                )
            )
        # hop bridges between consecutive stages (collective programs;
        # construction is metadata-only, transfer() guards participation)
        self.bridges: List[HopBridge] = [
            HopBridge(self.stages[s].devices, self.stages[s + 1].devices)
            for s in range(num_stages - 1)
        ]
        first, last = self.stages[0], self.stages[-1]
        self._embed_shardings = {
            "embed": NamedSharding(first.mesh, all_specs["embed"])
        }
        self._head_shardings = {
            "final_norm": NamedSharding(last.mesh, all_specs["final_norm"]),
            "lm_head": NamedSharding(last.mesh, all_specs["lm_head"]),
        }
        self._embed = (
            jax.jit(
                lambda emb_params, tokens: tf.embed(emb_params, tokens, cfg),
                out_shardings=first.sharding,
            )
            if first.local else None
        )
        self._head_grad = (
            jax.jit(jax.value_and_grad(make_head_loss(cfg), argnums=(0, 1)))
            if last.local else None
        )
        self._embed_bwd = (
            jax.jit(make_embed_bwd(cfg), out_shardings=self._embed_shardings)
            if first.local else None
        )

    # ------------------------------------------------------------------
    def _commit(self, arr, stage: _GangStage, sharding=None):
        """Place host data onto a stage's (possibly multi-process) mesh —
        replicated by default, or per ``sharding`` (tp-sharded params).
        Participating processes only."""
        if not stage.local:
            return None
        from ray_tpu.parallel.hop_bridge import commit_replicated

        return commit_replicated(arr, stage.devices, sharding or stage.sharding)

    def split_params(self, params: Dict[str, Any]):
        """Full host param tree (identical on every process) → this
        process's stage partitions: embed with stage 0, layer slices per
        stage, head with the last stage. Non-participating partitions
        are None."""
        L, S = self.cfg.n_layers, self.num_stages
        per = L // S
        stage_layers = []
        for s in range(S):
            st = self.stages[s]
            if st.local:
                sl = jax.tree.map(
                    lambda x: np.asarray(x)[s * per : (s + 1) * per],
                    params["layers"],
                )
                stage_layers.append(
                    jax.tree.map(
                        lambda a, sh: self._commit(a, st, sh),
                        sl, st.layer_shardings,
                    )
                )
            else:
                stage_layers.append(None)
        embed_params = (
            {"embed": self._commit(params["embed"], self.stages[0],
                                   self._embed_shardings["embed"])}
            if self.stages[0].local else None
        )
        head_params = (
            {k: self._commit(params[k], self.stages[-1], self._head_shardings[k])
             for k in ("final_norm", "lm_head")}
            if self.stages[-1].local else None
        )
        return embed_params, stage_layers, head_params

    # ------------------------------------------------------------------
    def loss_and_grads(self, params, batch: Dict[str, np.ndarray],
                       num_microbatches: int):
        """Full fwd+bwd. ``batch`` is HOST data, identical on every
        participating process (the pipeline is dp=1; data parallelism is
        an outer axis). Returns (loss, (g_embed, g_stage, g_head)) where
        loss is a host float on every process and each grad partition is
        present only on its stage's processes."""
        cfg = self.cfg
        S, M = self.num_stages, num_microbatches
        tokens = np.asarray(batch["tokens"])
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        b, seq = inputs.shape
        assert b % M == 0, (b, M)
        mb = b // M
        act_shape = (mb, seq, cfg.d_model)
        act_dtype = cfg.dtype
        positions = np.broadcast_to(
            np.arange(seq, dtype=np.int32)[None, :], (mb, seq)
        )
        mask = batch.get("mask")
        embed_params, stage_layers, head_params = params
        first, last = self.stages[0], self.stages[-1]

        pos_by_stage = [
            self._commit(positions, st) if st.local else None
            for st in self.stages
        ]

        # ---- forward wavefront -------------------------------------
        h_mb: List[Any] = [None] * M
        if first.local:
            tokens0 = self._commit(inputs, first)
            h = self._embed(embed_params, tokens0)
            h_mb = [h[m * mb : (m + 1) * mb] for m in range(M)]
        saved_inputs = [[None] * M for _ in range(S)]
        outs: List[Any] = [None] * M
        for m in range(M):
            x = h_mb[m]
            for s in range(S):
                st = self.stages[s]
                if st.local:
                    saved_inputs[s][m] = x
                    x = st.fwd(stage_layers[s], x, pos_by_stage[s])
                if s + 1 < S:
                    x = self.bridges[s].transfer(
                        x if st.local else None, act_shape, act_dtype
                    )
            if last.local:
                outs[m] = x

        # ---- head over the re-assembled batch (full_head mode) ------
        loss_arr = None
        g_out_mb: List[Any] = [None] * M
        g_head = None
        if last.local:
            h_full = jnp.concatenate(outs, axis=0)
            targets_l = self._commit(targets, last)
            mask_l = self._commit(mask[:, 1:], last) if mask is not None else None
            loss_arr, (g_head, g_h) = self._head_grad(
                head_params, h_full, targets_l, mask_l
            )
            g_out_mb = [g_h[m * mb : (m + 1) * mb] for m in range(M)]

        # ---- backward drain (microbatch order, deterministic sums) --
        g_stage: List[Any] = [None] * S
        g_first_inputs: List[Any] = []
        for m in range(M):
            gy = g_out_mb[m]
            for s in range(S - 1, -1, -1):
                st = self.stages[s]
                if st.local:
                    gx, gp = st.bwd(
                        stage_layers[s], saved_inputs[s][m], pos_by_stage[s], gy
                    )
                    g_stage[s] = gp if g_stage[s] is None else jax.tree.map(
                        jnp.add, g_stage[s], gp
                    )
                    gy = gx
                if s > 0:
                    gy = self.bridges[s - 1].transfer(
                        gy if st.local else None, act_shape, act_dtype,
                        reverse=True,
                    )
            if first.local:
                g_first_inputs.append(gy)

        g_embed = None
        if first.local:
            gh_embed = jnp.concatenate(g_first_inputs, axis=0)
            g_embed = self._embed_bwd(embed_params, tokens0, gh_embed)

        # ---- loss rides the reverse bridges to every stage ----------
        # Take every received copy unconditionally: after hop s the loss
        # must be resident on stage s-1's devices for the NEXT hop (a
        # process owning several consecutive stages re-sends the copy it
        # just received, never a stale earlier-stage-resident one).
        for s in range(S - 1, 0, -1):
            got = self.bridges[s - 1].transfer(
                loss_arr if self.stages[s].local else None, (), jnp.float32,
                reverse=True,
            )
            if got is not None:
                loss_arr = got
        loss = float(_local_copy(loss_arr)) if loss_arr is not None else None
        return loss, (g_embed, g_stage, g_head)


def mpmd_gang_train_step_fns(cfg: tf.TransformerConfig, num_stages: int,
                             optimizer=None, num_microbatches: int = 2,
                             attn_fn=None, stage_tp: int = 1):
    """Training-step closure over MpmdGangPipeline, mirroring
    mpmd.mpmd_train_step_fns: init_fn(params) -> (split, opt_states);
    step_fn(split, opt_states, batch) -> (split', opt_states', loss)."""
    import optax

    optimizer = optimizer or optax.adamw(1e-3)
    pipe = MpmdGangPipeline(cfg, num_stages, attn_fn=attn_fn, stage_tp=stage_tp)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def _apply_update(p, st, g):
        updates, st2 = optimizer.update(g, st, p)
        return optax.apply_updates(p, updates), st2

    def _opt_init(p):
        return jax.jit(optimizer.init)(p) if p is not None else None

    def init_fn(params):
        split = pipe.split_params(params)
        embed_params, stage_layers, head_params = split
        opt_states = (
            _opt_init(embed_params),
            [_opt_init(sl) for sl in stage_layers],
            _opt_init(head_params),
        )
        return split, opt_states

    def step_fn(split, opt_states, batch):
        embed_params, stage_layers, head_params = split
        st_embed, st_stages, st_head = opt_states
        loss, (g_embed, g_stage, g_head) = pipe.loss_and_grads(
            split, batch, num_microbatches
        )
        if g_embed is not None:
            embed_params, st_embed = _apply_update(embed_params, st_embed, g_embed)
        new_layers, new_states = [], []
        for s in range(num_stages):
            if g_stage[s] is not None:
                p2, s2 = _apply_update(stage_layers[s], st_stages[s], g_stage[s])
            else:
                p2, s2 = stage_layers[s], st_stages[s]
            new_layers.append(p2)
            new_states.append(s2)
        if g_head is not None:
            head_params, st_head = _apply_update(head_params, st_head, g_head)
        return (
            (embed_params, new_layers, head_params),
            (st_embed, new_states, st_head),
            loss,
        )

    return pipe, init_fn, step_fn
