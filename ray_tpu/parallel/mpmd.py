"""MPMD pipeline parallelism: one jit program per stage, per device set.

Reference: the compiled-DAG op-graph (python/ray/dag/dag_node_operation.py
:9-120 — per-actor READ/COMPUTE/WRITE op schedules with comm overlap) and
its NCCL device channels (experimental/channel/torch_tensor_nccl_channel
.py:190); SURVEY.md §7 names JaxPP-style MPMD as the hard part the
in-graph GPipe (parallel/pipeline.py) cannot cover: heterogeneous stages,
per-stage compilation, and pipelines spanning more devices than one XLA
program wants to address.

Shape here, TPU-first:
- each stage owns a disjoint device subset with its own ``Mesh`` and its
  own jit-compiled forward/backward programs (separate XLA programs — the
  "MPMD" in the name);
- activations hand off between stage meshes with ``jax.device_put`` —
  HBM→HBM over ICI when the meshes sit in one slice. Cross-PROCESS /
  cross-host handoff (DCN) is the collective-bridge program in
  hop_bridge.HopBridge, driven by the gang pipeline in mpmd_gang;
- the host issues the microbatch schedule; XLA's async dispatch runs
  stage programs concurrently, so issue order ≈ the reference's op-graph
  schedule. Backward for microbatch m is issued 1F1B-style (oldest
  first, interleaved with remaining forwards when the loss mode allows).

Two loss modes:
- ``full_head`` (default): the head (final-norm + unembed + NLL) runs
  once over the reassembled full batch — EXACTLY the math of the
  in-graph GPipe loss (train_step.build_loss_fn), so losses match
  bit-for-bit. Backward drains 1F1B-ordered after the head barrier.
- ``per_microbatch``: the head runs per microbatch (loss = mean over
  microbatches) — true 1F1B interleaving with bounded live activations,
  at the cost of a different (but mathematically equivalent) FP
  accumulation order.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import transformer as tf


# The cross-process/cross-host leg of the handoff lives in
# hop_bridge.HopBridge (a collective-bridge program per hop, jointly
# dispatched by both stage gangs); the gang-driven pipeline that uses it
# is parallel/mpmd_gang.MpmdGangPipeline. This module keeps the
# single-process form, whose handoffs are plain jax.device_put.


def make_stage_fn(cfg: "tf.TransformerConfig", attn_fn=None) -> Callable:
    """The per-stage layer-stack program. IDENTICAL structure to
    train_step.build_loss_fn's stage_fn — the bit-for-bit loss equality
    between MPMD (single- AND multi-process) and in-graph GPipe depends
    on every pipeline flavor using this one definition."""

    def stage_fn(stage_params, x, positions):
        def layer_fn(carry, lp):
            return tf.decoder_layer(carry, lp, cfg, positions, attn_fn), None

        if cfg.remat:
            layer_fn = jax.checkpoint(layer_fn, prevent_cse=False)
        x, _ = jax.lax.scan(layer_fn, x, stage_params)
        return x

    return stage_fn


def make_stage_bwd(stage_fn: Callable) -> Callable:
    """Recompute-in-backward VJP of a stage: only stage INPUTS are saved
    across the schedule, not intermediate activations."""

    def bwd(stage_params, x, positions, gy):
        y, vjp = jax.vjp(lambda p, xx: stage_fn(p, xx, positions), stage_params, x)
        gparams, gx = vjp(gy)
        del y
        return gx, gparams

    return bwd


def make_head_loss(cfg: "tf.TransformerConfig") -> Callable:
    def head_loss(head_params, h, targets, mask):
        logits = tf.unembed(head_params, h, cfg)
        return tf.token_nll(logits, targets, mask)

    return head_loss


def make_embed_bwd(cfg: "tf.TransformerConfig") -> Callable:
    def embed_bwd(emb_params, tokens, gh):
        _, vjp = jax.vjp(lambda p: tf.embed(p, tokens, cfg), emb_params)
        (gp,) = vjp(gh)
        return gp

    return embed_bwd


@dataclass
class _Stage:
    index: int
    mesh: Mesh
    sharding: NamedSharding  # activation placement within the stage
    fwd: Callable  # (stage_params, x, positions) -> y
    bwd: Callable  # (stage_params, x, positions, gy) -> (gx, gparams)
    layer_shardings: Any = None  # per-leaf shardings of the stage's layers


class MpmdPipeline:
    """A transformer layer-stack pipeline where stage ``s`` is its own
    XLA program on its own devices.

    Stage interiors compose with tensor/FSDP parallelism: with
    ``stage_tp``/``stage_fsdp`` > 1 each stage's devices form a
    ``(fsdp, tp)`` mesh and the stage program is GSPMD-partitioned with
    the same Megatron/ZeRO specs the in-graph path uses
    (mesh.param_specs) — XLA inserts the per-block tp psums inside the
    stage while the pipeline schedule stays host-driven. Activations at
    stage boundaries are batch-sharded over fsdp and replicated over tp
    (the Megatron contract), so handoffs remain a single device_put."""

    def __init__(
        self,
        cfg: tf.TransformerConfig,
        num_stages: int,
        devices: Optional[List[Any]] = None,
        attn_fn=None,
        stage_tp: int = 1,
        stage_fsdp: int = 1,
    ):
        from ray_tpu.parallel import mesh as mesh_lib

        self.cfg = cfg
        self.num_stages = num_stages
        self.stage_tp = stage_tp
        self.stage_fsdp = stage_fsdp
        devices = list(devices if devices is not None else jax.devices())
        assert len(devices) % num_stages == 0, (len(devices), num_stages)
        assert cfg.n_layers % num_stages == 0, (cfg.n_layers, num_stages)
        per = len(devices) // num_stages
        inner = stage_tp * stage_fsdp
        assert per % inner == 0, (per, inner)
        # extra stage devices replicate over a leading "rep" axis
        rep = per // inner
        self._stage_plan = mesh_lib.MeshPlan(fsdp=stage_fsdp, tp=stage_tp)
        self._act_spec = P(("fsdp",) if stage_fsdp > 1 else None)
        self.stages: List[_Stage] = []

        stage_fn = make_stage_fn(cfg, attn_fn)
        self._stage_fn = stage_fn
        bwd = make_stage_bwd(stage_fn)
        all_specs = mesh_lib.param_specs(cfg, self._stage_plan)
        self._layer_specs = all_specs["layers"]
        for s in range(num_stages):
            devs = np.array(devices[s * per : (s + 1) * per]).reshape(
                rep, stage_fsdp, stage_tp
            )
            mesh = Mesh(devs, ("rep", "fsdp", "tp"))
            shard = NamedSharding(mesh, self._act_spec)
            lshard = jax.tree.map(
                lambda sp: NamedSharding(mesh, sp), self._layer_specs,
                is_leaf=lambda x: isinstance(x, P),
            )
            self.stages.append(
                _Stage(
                    index=s,
                    mesh=mesh,
                    sharding=shard,
                    fwd=jax.jit(stage_fn, out_shardings=shard),
                    bwd=jax.jit(bwd, out_shardings=(shard, lshard)),
                    layer_shardings=lshard,
                )
            )
        first, last = self.stages[0], self.stages[-1]
        self._embed_shardings = {
            "embed": NamedSharding(first.mesh, all_specs["embed"])
        }
        self._head_shardings = {
            "final_norm": NamedSharding(last.mesh, all_specs["final_norm"]),
            "lm_head": NamedSharding(last.mesh, all_specs["lm_head"]),
        }
        # stage-resident programs for the model's ends
        self._embed = jax.jit(
            lambda emb_params, tokens: tf.embed(emb_params, tokens, cfg),
            out_shardings=first.sharding,
        )

        self._head_grad = jax.jit(
            jax.value_and_grad(make_head_loss(cfg), argnums=(0, 1)),
        )
        self._embed_bwd = jax.jit(
            make_embed_bwd(cfg), out_shardings=self._embed_shardings
        )

    # ------------------------------------------------------------------
    def split_params(self, params: Dict[str, Any]):
        """The flagship param tree → per-stage partitions, device_put onto
        each stage's mesh: embed params with stage 0, layer slices per
        stage, head (final_norm + lm_head) with the last stage."""
        L, S = self.cfg.n_layers, self.num_stages
        per = L // S
        stage_layers = []
        for s in range(S):
            sl = jax.tree.map(lambda x: x[s * per : (s + 1) * per], params["layers"])
            stage_layers.append(jax.device_put(sl, self.stages[s].layer_shardings))
        embed_params = jax.device_put(
            {k: v for k, v in params.items() if k == "embed"},
            self._embed_shardings,
        )
        head_params = jax.device_put(
            {k: params[k] for k in ("final_norm", "lm_head")},
            self._head_shardings,
        )
        return embed_params, stage_layers, head_params

    def _handoff(self, value, stage: _Stage):
        """Activation transfer onto ``stage``'s devices (ICI/HBM path).
        All stage meshes here are single-process; the cross-process form
        rides hop_bridge.HopBridge (see mpmd_gang)."""
        return jax.device_put(value, stage.sharding)

    # ------------------------------------------------------------------
    def forward(self, stage_layers, h_mb: List[jax.Array], positions):
        """Microbatch wavefront through the stage programs. Returns the
        per-microbatch outputs ON THE LAST STAGE's devices."""
        S = self.num_stages
        inflight: List[Any] = list(h_mb)
        saved_inputs = [[None] * len(h_mb) for _ in range(S)]
        pos_by_stage = [self._handoff(positions, st) for st in self.stages]
        outs: List[Any] = [None] * len(h_mb)
        # wavefront issue order == the op-graph's fwd schedule: stage s
        # runs microbatch m while stage s-1 runs m+1 (async dispatch)
        for m in range(len(h_mb)):
            x = self._handoff(inflight[m], self.stages[0])
            for s, st in enumerate(self.stages):
                saved_inputs[s][m] = x
                x = st.fwd(stage_layers[s], x, pos_by_stage[s])
                if s + 1 < S:
                    x = self._handoff(x, self.stages[s + 1])
            outs[m] = x
        return outs, saved_inputs, pos_by_stage

    def backward(self, stage_layers, saved_inputs, pos_by_stage, g_out_mb: List[jax.Array]):
        """1F1B-ordered backward drain: microbatch m's backward walks
        stages last→first; grads accumulate per stage in microbatch
        order (deterministic summation)."""
        S = self.num_stages
        g_stage: List[Any] = [None] * S
        g_first_inputs = []
        for m in range(len(g_out_mb)):
            gy = g_out_mb[m]
            for s in range(S - 1, -1, -1):
                st = self.stages[s]
                gy = self._handoff(gy, st)
                gx, gp = st.bwd(stage_layers[s], saved_inputs[s][m], pos_by_stage[s], gy)
                g_stage[s] = gp if g_stage[s] is None else jax.tree.map(
                    jnp.add, g_stage[s], gp
                )
                gy = gx
            g_first_inputs.append(gy)
        return g_stage, g_first_inputs

    # ------------------------------------------------------------------
    def loss_and_grads(self, params, batch, num_microbatches: int,
                       loss_mode: str = "full_head"):
        """Full fwd+bwd over the MPMD pipeline. Returns
        (loss, grads_by_partition) where grads_by_partition =
        (g_embed, [g_stage_layers...], g_head)."""
        cfg = self.cfg
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        b, s = inputs.shape
        assert b % num_microbatches == 0, (b, num_microbatches)
        mb = b // num_microbatches
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (mb, s))
        mask = batch.get("mask")
        embed_params, stage_layers, head_params = params

        tokens0 = self._handoff(inputs, self.stages[0])
        h = self._embed(embed_params, tokens0)
        h_mb = [h[m * mb : (m + 1) * mb] for m in range(num_microbatches)]
        outs, saved_inputs, pos_by_stage = self.forward(stage_layers, h_mb, positions)

        last = self.stages[-1]
        if loss_mode == "full_head":
            # EXACT in-graph GPipe math: one head over the full batch
            h_full = jnp.concatenate(outs, axis=0)
            targets_l = self._handoff(targets, last)
            mask_l = self._handoff(mask[:, 1:], last) if mask is not None else None
            loss, (g_head, g_h) = self._head_grad(head_params, h_full, targets_l, mask_l)
            g_out_mb = [g_h[m * mb : (m + 1) * mb] for m in range(num_microbatches)]
        elif loss_mode == "per_microbatch":
            # true 1F1B: per-microbatch head. Each microbatch's masked
            # mean must be re-weighted by ITS token count so the combined
            # objective equals the global masked mean (uniform 1/M would
            # over-weight sparse microbatches); unmasked microbatches are
            # equal-sized, so 1/M is exact there.
            if mask is not None:
                m_counts = [
                    jnp.maximum(mask[m * mb : (m + 1) * mb, 1:].sum(), 1)
                    for m in range(num_microbatches)
                ]
                total = sum(m_counts[1:], m_counts[0])
                weights = [c / total for c in m_counts]
            else:
                weights = [1.0 / num_microbatches] * num_microbatches
            losses, g_out_mb, g_head = [], [], None
            for m in range(num_microbatches):
                t_m = self._handoff(targets[m * mb : (m + 1) * mb], last)
                m_m = (
                    self._handoff(mask[m * mb : (m + 1) * mb, 1:], last)
                    if mask is not None else None
                )
                l_m, (gh_m, g_h_m) = self._head_grad(head_params, outs[m], t_m, m_m)
                w = weights[m]
                losses.append(l_m * w)
                g_out_mb.append(jax.tree.map(lambda x: x * w, g_h_m))
                gh_m = jax.tree.map(lambda x: x * w, gh_m)
                g_head = gh_m if g_head is None else jax.tree.map(jnp.add, g_head, gh_m)
            loss = sum(losses[1:], losses[0])
        else:
            raise ValueError(f"unknown loss_mode {loss_mode!r}")

        g_stage, g_first = self.backward(stage_layers, saved_inputs, pos_by_stage, g_out_mb)
        gh_embed = jnp.concatenate(
            [self._handoff(g, self.stages[0]) for g in g_first], axis=0
        )
        g_embed = self._embed_bwd(embed_params, tokens0, gh_embed)
        return loss, (g_embed, g_stage, g_head)


def mpmd_train_step_fns(cfg: tf.TransformerConfig, num_stages: int,
                        devices=None, optimizer=None, num_microbatches: int = 2,
                        stage_tp: int = 1, stage_fsdp: int = 1):
    """A full MPMD training step (loss + grads + per-partition optimizer
    update) as host-driven per-stage programs. Returns
    (pipeline, init_fn, step_fn):
      init_fn(params)   -> (split_params, opt_states)
      step_fn(split_params, opt_states, batch) -> (params', states', loss)
    """
    import optax

    optimizer = optimizer or optax.adamw(1e-3)
    pipe = MpmdPipeline(
        cfg, num_stages, devices, stage_tp=stage_tp, stage_fsdp=stage_fsdp
    )

    # One jitted apply serves every partition: output placement follows
    # the donated inputs, and the jit cache keys on shapes/shardings.
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def _apply_update(p, st, g):
        updates, st2 = optimizer.update(g, st, p)
        return optax.apply_updates(p, updates), st2

    def init_fn(params):
        split = pipe.split_params(params)
        embed_params, stage_layers, head_params = split
        opt_states = (
            jax.jit(optimizer.init)(embed_params),
            [jax.jit(optimizer.init)(sl) for sl in stage_layers],
            jax.jit(optimizer.init)(head_params),
        )
        return split, opt_states

    def step_fn(split, opt_states, batch, loss_mode: str = "full_head"):
        embed_params, stage_layers, head_params = split
        st_embed, st_stages, st_head = opt_states
        loss, (g_embed, g_stage, g_head) = pipe.loss_and_grads(
            split, batch, num_microbatches, loss_mode=loss_mode
        )
        embed_params, st_embed = _apply_update(embed_params, st_embed, g_embed)
        new_layers, new_states = [], []
        for s in range(num_stages):
            p2, s2 = _apply_update(stage_layers[s], st_stages[s], g_stage[s])
            new_layers.append(p2)
            new_states.append(s2)
        head_params, st_head = _apply_update(head_params, st_head, g_head)
        return (
            (embed_params, new_layers, head_params),
            (st_embed, new_states, st_head),
            loss,
        )

    return pipe, init_fn, step_fn
