"""Pipeline parallelism: microbatched GPipe over the ``pp`` mesh axis.

The reference provides pipeline *transport/scheduling* only (SURVEY.md §2.9
— compiled-DAG NCCL channels + op-graph overlap, dag/dag_node_operation.py);
the TPU-native version is in-graph: the layer stack is reshaped to
[n_stages, layers_per_stage, ...] with the stage axis sharded over ``pp``,
and a shard_map (manual only over ``pp``; dp/fsdp/tp/sp stay automatic so
GSPMD keeps inserting their collectives inside the stage body) runs the
classic GPipe schedule — microbatches march through stages via
``lax.ppermute`` activation hand-offs over ICI neighbor links (cf. the MPMD
pipeline paper in PAPERS.md; this is its SPMD collective-permute variant).

Cost model: bubble fraction = (S-1)/(M+S-1); every stage computes every
step (idle steps compute on zeros) which XLA overlaps with the permute.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_body(stage_params, stage_ids, h_mb, positions, *, stage_fn, num_stages, num_microbatches, axis_name):
    """shard_map body. stage_params: [1, L/S, ...] (local stage shard);
    stage_ids: [1] this stage's index (an arange sharded over pp —
    lax.axis_index lowers to a PartitionId op that the SPMD partitioner
    rejects inside a partially-manual shard_map on older jax);
    h_mb: [M, mb, s, d] microbatched activations (auto-sharded on batch)."""
    p = stage_ids[0]
    M, S = num_microbatches, num_stages
    params_local = jax.tree.map(lambda x: x[0], stage_params)
    is_first = p == 0
    is_last = p == S - 1
    zero = jnp.zeros_like(h_mb[0])

    def step(carry, t):
        pipe_reg, outputs = carry
        # Stage 0 feeds microbatch t (clamped); other stages use the register.
        mb_idx = jnp.clip(t, 0, M - 1)
        first_in = jax.lax.dynamic_index_in_dim(h_mb, mb_idx, axis=0, keepdims=False)
        x_in = jnp.where(is_first, first_in, pipe_reg)
        active = jnp.logical_and(t >= p, t - p < M)
        out = stage_fn(params_local, x_in, positions)
        out = jnp.where(active, out, zero)
        # Forward hand-off: stage i → i+1 (no wraparound; stage 0 receives 0s).
        perm = [(i, i + 1) for i in range(S - 1)]
        nxt = jax.lax.ppermute(out, axis_name, perm)
        # Last stage banks its finished microbatch at slot t-(S-1).
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        bank = jnp.logical_and(is_last, active)
        onehot = (jnp.arange(M) == out_idx).astype(out.dtype) * jnp.where(bank, 1.0, 0.0).astype(out.dtype)
        outputs = outputs + onehot[:, None, None, None] * out[None]
        return (nxt, outputs), None

    init = (zero, jnp.zeros_like(h_mb))
    (_, outputs), _ = jax.lax.scan(step, init, jnp.arange(M + S - 1))
    # Everyone needs the result (loss/unembed run data-parallel afterwards):
    # only the last stage holds non-zeros, so a psum over pp broadcasts it.
    return jax.lax.psum(outputs, axis_name)


def pipeline_apply(
    stage_fn: Callable,
    stacked_stage_params,
    h,
    positions,
    mesh: Mesh,
    num_stages: int,
    num_microbatches: int,
    axis_name: str = "pp",
    seq_axis: str = None,
):
    """Run h [b, s, d] through the pipelined decoder stack.

    stage_fn(params_one_stage, x, positions) -> x, where params_one_stage
    has leading dim layers_per_stage. ``stacked_stage_params`` has leading
    dims [num_stages, layers_per_stage] with the stage axis sharded over pp.

    ``seq_axis``: when sequence parallelism composes with pp, the pipeline
    shard_map goes manual over BOTH axes (nested partial-manual shard_maps
    don't lower) — the sequence dim arrives pre-sharded and the stage_fn's
    attention must be the RAW per-shard collective body
    (ring_attention_local / ulysses_attention_local), whose ppermute/
    all_to_all run directly in this manual context.
    """
    b = h.shape[0]
    assert b % num_microbatches == 0, (b, num_microbatches)
    mb = b // num_microbatches
    h_mb = h.reshape(num_microbatches, mb, *h.shape[1:])
    pos_mb = positions[:mb]

    # Manual only over pp (+ seq_axis when composing with sp); remaining
    # axes stay automatic so GSPMD keeps inserting fsdp/tp collectives
    # inside the stage body.
    h_spec = P(None, None, seq_axis, None) if seq_axis else P()
    pos_spec = P(None, seq_axis) if seq_axis else P()
    manual = {axis_name} | ({seq_axis} if seq_axis else set())
    from ray_tpu.utils import jax_compat

    body = jax_compat.shard_map(
        functools.partial(
            _pipeline_body,
            stage_fn=stage_fn,
            num_stages=num_stages,
            num_microbatches=num_microbatches,
            axis_name=axis_name,
        ),
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(axis_name), stacked_stage_params),
            P(axis_name),
            h_spec,
            pos_spec,
        ),
        out_specs=h_spec,
        axis_names=manual,
        check_vma=False,
    )
    stage_ids = jnp.arange(num_stages, dtype=jnp.int32)
    out = body(stacked_stage_params, stage_ids, h_mb, pos_mb)
    return out.reshape(b, *h.shape[1:])


def split_stages(layer_params, num_stages: int):
    """[L, ...] stacked layer params → [S, L/S, ...]."""

    def rs(x):
        L = x.shape[0]
        assert L % num_stages == 0, (L, num_stages)
        return x.reshape(num_stages, L // num_stages, *x.shape[1:])

    return jax.tree.map(rs, layer_params)
