"""Ulysses sequence parallelism: all-to-all head/sequence swaps.

The reference has NO sequence/context parallelism (SURVEY.md §5.7 —
repo-wide grep finds none; its closest primitives are NCCL p2p channels,
python/ray/util/collective/collective.py:531). Here it is native, as the
second SP strategy next to ring attention (ray_tpu/parallel/ring.py):

Each device holds a ``[b, h, s/sp, d]`` shard. One ``lax.all_to_all``
over the ``sp`` axis re-shards from sequence-split to head-split
(``[b, h/sp, s, d]``), every device then runs *full-sequence* attention
over its head subset — so the single-chip flash-attention pallas kernel
(ray_tpu/ops/attention.py) applies unchanged — and a second all-to-all
swaps back. Two all-to-alls per attention call vs ring's sp-1 ppermute
rounds: Ulysses wins when sp divides the local head count and the
per-hop latency dominates (short sequences, large sp); ring wins at very
long sequence where overlap of compute with neighbor-hop transfers
matters.

Both ride ICI when ``sp`` maps to a physical torus axis.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import flash_attention


def ulysses_attention_local(q, k, v, axis_name: str, scale: Optional[float] = None,
                            causal: bool = True):
    """Per-shard body — call inside shard_map with q,k,v local shards
    ``[b, h, s_local, d]``. Requires ``h % sp == 0`` (heads per device
    after any tp split must still divide sp)."""
    from ray_tpu.utils import jax_compat

    sp = jax_compat.axis_size(axis_name)
    h = q.shape[1]
    if h % sp != 0:
        raise ValueError(
            f"Ulysses SP needs local heads ({h}) divisible by sp ({sp}); "
            "use ring attention for head counts that don't split"
        )

    # One collective for all three tensors: stack on a leading axis so the
    # latency-dominated regime this mode targets pays a single all-to-all
    # launch instead of three.
    qkv = jnp.stack([q, k, v])  # [3, b, h, s/sp, d]
    qkv = jax.lax.all_to_all(qkv, axis_name, split_axis=2, concat_axis=3, tiled=True)
    qh, kh, vh = qkv  # each [b, h/sp, s, d]
    out = flash_attention(qh, kh, vh, causal=causal, scale=scale)
    # [b, h/sp, s, d] -> [b, h, s/sp, d]
    return jax.lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1, tiled=True)


def make_ulysses_attn_fn(mesh: Mesh, axis_name: str = "sp"):
    """An attn_fn for models.transformer: [b,h,s,d] global → Ulysses
    attention over the ``axis_name`` shards. Must run inside a jit whose
    inputs are sharded over this mesh. Same signature/specs as
    ring.make_ring_attn_fn so the two are drop-in alternatives."""
    spec = P(("dp", "fsdp"), "tp", axis_name, None)
    body = functools.partial(ulysses_attention_local, axis_name=axis_name)

    def attn(q, k, v):
        # nestable under a pp shard_map — see ring.make_ring_attn_fn
        from ray_tpu.utils import jax_compat

        cur = jax_compat.get_abstract_mesh()
        use = cur if (cur is not None and cur.shape) else mesh
        fn = jax_compat.shard_map(
            body,
            mesh=use,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            axis_names={"dp", "fsdp", "tp", axis_name},
            check_vma=False,
        )
        return fn(q, k, v)

    return attn
