"""Sharded train-state + train-step builders.

This is where a MeshPlan becomes a compiled program: params/optimizer state
initialized directly into their NamedShardings (no host round-trip), and a
single donated-argument jit whose gradient collectives are chosen by GSPMD
from the shardings (reference contrast: Ray Train wraps torch DDP,
train/torch/config.py:66 — here the "backend" is the compiler).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import transformer as tf
from ray_tpu.parallel import mesh as mesh_lib
from ray_tpu.parallel.mesh import MeshPlan
from ray_tpu.parallel.pipeline import pipeline_apply, split_stages
from ray_tpu.parallel.ring import make_ring_attn_fn
from ray_tpu.parallel.ulysses import make_ulysses_attn_fn


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.1, warmup: int = 100, grad_clip: float = 1.0):
    sched = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, max(warmup * 10, 1000))
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def build_loss_fn(cfg: tf.TransformerConfig, plan: MeshPlan, mesh: Mesh, num_microbatches: int = 4):
    """Loss with the plan's parallelism baked in (ring or Ulysses
    attention for sp>1 per ``plan.sp_mode``, GPipe for pp>1)."""
    # sp dispatch: (shard_map wrapper for GSPMD-auto contexts, raw local
    # collective body for manual contexts like the pp pipeline)
    from ray_tpu.parallel.ring import ring_attention_local
    from ray_tpu.parallel.ulysses import ulysses_attention_local

    SP_MODES = {
        "ring": (make_ring_attn_fn, ring_attention_local),
        "ulysses": (make_ulysses_attn_fn, ulysses_attention_local),
    }
    attn_fn = None
    if plan.sp > 1:
        attn_fn = SP_MODES[plan.sp_mode][0](mesh)
    elif mesh.size > 1:
        # Pallas kernels can't be auto-partitioned by GSPMD — on any
        # multi-device mesh the flash attention must run inside its own
        # shard_map over the batch/head axes (ops/attention.py).
        from ray_tpu.ops.attention import make_flash_attn_fn

        attn_fn = make_flash_attn_fn(mesh)

    if plan.pp == 1:
        def loss(params, batch):
            return tf.loss_fn(params, batch, cfg, attn_fn)

        return loss

    S = plan.pp
    assert cfg.n_layers % S == 0, (cfg.n_layers, S)

    # pp × sp composition: the pipeline shard_map is manual over BOTH
    # axes, so the attention must be the raw per-shard collective body
    # (nested partial-manual shard_maps don't lower — see pipeline_apply).
    seq_axis = None
    stage_attn_fn = attn_fn
    if plan.sp > 1:
        stage_attn_fn = functools.partial(SP_MODES[plan.sp_mode][1], axis_name="sp")
        seq_axis = "sp"

    def stage_fn(stage_params, x, positions):
        def layer_fn(carry, lp):
            out = tf.decoder_layer(carry, lp, cfg, positions, stage_attn_fn)
            return out, None

        if cfg.remat:
            # honor the same remat_policy knob as tf.decoder_stack
            if cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            elif cfg.remat_policy == "attn":
                policy = jax.checkpoint_policies.save_only_these_names("attn_out")
            else:
                policy = None
            layer_fn = jax.checkpoint(layer_fn, prevent_cse=False, policy=policy)
        x, _ = jax.lax.scan(layer_fn, x, stage_params, unroll=cfg.scan_unroll)
        return x

    def loss(params, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        b, s = inputs.shape
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
        h = tf.embed(params, inputs, cfg)
        staged = split_stages(params["layers"], S)
        h = pipeline_apply(
            stage_fn, staged, h, positions, mesh, S, num_microbatches,
            seq_axis=seq_axis,
        )
        logits = tf.unembed(params, h, cfg)
        mask = batch.get("mask")
        return tf.token_nll(logits, targets, mask[:, 1:] if mask is not None else None)

    return loss


def make_train_state(
    cfg: tf.TransformerConfig,
    plan: MeshPlan,
    mesh: Mesh,
    optimizer=None,
    seed: int = 0,
):
    """Returns (params, opt_state, shardings dict) — initialized sharded."""
    optimizer = optimizer or make_optimizer()
    p_shard = mesh_lib.param_shardings(mesh, cfg, plan)

    @functools.partial(jax.jit, out_shardings=p_shard)
    def _init(key):
        return tf.init_params(key, cfg)

    params = _init(jax.random.PRNGKey(seed))

    opt_shard = _opt_state_shardings(optimizer, params, p_shard, mesh)

    @functools.partial(jax.jit, out_shardings=opt_shard)
    def _init_opt(p):
        return optimizer.init(p)

    opt_state = _init_opt(params)
    return params, opt_state, {"params": p_shard, "opt": opt_shard}


def _opt_state_shardings(optimizer, params, p_shard, mesh):
    """Optimizer-state subtrees that mirror the param tree (Adam moments)
    get the params' shardings — sharded optimizer state is the PAPERS.md
    cross-replica weight-update-sharding recipe; scalar leaves replicate."""
    shapes = jax.eval_shape(optimizer.init, params)
    rep = NamedSharding(mesh, P())
    params_treedef = jax.tree.structure(params)

    def is_param_like(subtree) -> bool:
        try:
            return jax.tree.structure(subtree) == params_treedef
        except Exception:
            return False

    leaves, treedef = jax.tree.flatten(shapes, is_leaf=is_param_like)
    out = [p_shard if is_param_like(leaf) else rep for leaf in leaves]
    return jax.tree.unflatten(treedef, out)


def make_train_step(
    cfg: tf.TransformerConfig,
    plan: MeshPlan,
    mesh: Mesh,
    optimizer=None,
    num_microbatches: int = 4,
) -> Callable:
    """jitted (params, opt_state, batch) → (params, opt_state, metrics)."""
    optimizer = optimizer or make_optimizer()
    loss_fn = build_loss_fn(cfg, plan, mesh, num_microbatches)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    # Shardings ride on the committed arrays (params/opt_state come out of
    # make_train_state sharded; callers device_put batches with
    # ``mesh_lib.batch_sharding``) — jit propagates them.
    return jax.jit(step, donate_argnums=(0, 1))
