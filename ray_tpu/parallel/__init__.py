from ray_tpu.parallel.mesh import MeshPlan, build_mesh
from ray_tpu.parallel.train_step import make_train_state, make_train_step

__all__ = ["MeshPlan", "build_mesh", "make_train_state", "make_train_step"]
