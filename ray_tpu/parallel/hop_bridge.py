"""Cross-process device-to-device activation transfer (the DCN leg).

Reference: python/ray/experimental/channel/torch_tensor_nccl_channel.py
:190 and nccl_group.py:23 — the reference moves device tensors between
nodes with NCCL p2p send/recv. The TPU-native equivalent is NOT a
point-to-point kernel API (XLA owns the fabric): it is a tiny SPMD
program over the union of the two device groups that both sides dispatch
jointly, letting XLA route the bytes over ICI/DCN (gloo on the CPU
simulation). This is the "collective-bridge program per hop" design.

Mechanics: a 2-row mesh ``[[src...], [dst...]]`` with axes
("hop", "within"); the payload is a global array of shape
``(2, *shape)`` sharded ``P("hop")`` — row 0 holds the sender's value
(resident on src devices), row 1 a dummy. One ``ppermute`` along "hop"
moves row 0 onto the dst row; the receiver reads its addressable shard.
Every process owning src or dst devices MUST call :meth:`transfer` at
the same point in its schedule (it is a collective). A single process
owning both rows degenerates to a local copy — the same code path runs
single- and multi-process.
"""
from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def commit_replicated(arr, devices, sharding: Optional[Any] = None):
    """Place host data replicated onto a device row that may span
    processes: a sole-owner row takes the direct ``device_put``; a
    multi-process row assembles the global array from each process's
    identical local copy."""
    arr = np.asarray(arr)
    devices = list(devices)
    if sharding is None:
        sharding = NamedSharding(Mesh(np.array(devices), ("r",)), P())
    pid = jax.process_index()
    if all(d.process_index == pid for d in devices):
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, arr)


class HopBridge:
    """Device-group → device-group transfer inside one jax runtime
    (single- or multi-process via ``jax.distributed``).

    ``src_devices`` / ``dst_devices``: equal-length device lists. Values
    transferred must be replicated across their group (the MPMD stage
    contract: stage-internal sharding is handled by the stage program,
    the handoff carries the stage's replicated activations; a
    within-sharded variant threads the "within" mesh axis through
    ``within_spec``).
    """

    def __init__(self, src_devices: Sequence[Any], dst_devices: Sequence[Any],
                 within_spec: Optional[P] = None):
        assert len(src_devices) == len(dst_devices), (
            "hop bridge rows must be equal-length; pad the narrower stage "
            f"(got {len(src_devices)} src vs {len(dst_devices)} dst)"
        )
        self.src_devices = list(src_devices)
        self.dst_devices = list(dst_devices)
        self.mesh = Mesh(
            np.array([self.src_devices, self.dst_devices]), ("hop", "within")
        )
        # P("hop") on the leading payload axis; remaining dims replicated
        # (or within-sharded when within_spec names the "within" axis).
        if within_spec is None:
            spec = P("hop")
        else:
            spec = P("hop", *within_spec)
        self._spec = spec
        self.sharding = NamedSharding(self.mesh, spec)

        from ray_tpu.utils import jax_compat

        @functools.partial(
            jax_compat.shard_map, mesh=self.mesh, in_specs=spec, out_specs=spec
        )
        def _fwd(x):
            return jax.lax.ppermute(x, "hop", [(0, 1)])

        @functools.partial(
            jax_compat.shard_map, mesh=self.mesh, in_specs=spec, out_specs=spec
        )
        def _rev(x):
            return jax.lax.ppermute(x, "hop", [(1, 0)])

        self._bridge = {False: jax.jit(_fwd), True: jax.jit(_rev)}
        my_pid = jax.process_index()
        self._my_src = [d for d in self.src_devices if d.process_index == my_pid]
        self._my_dst = [d for d in self.dst_devices if d.process_index == my_pid]
        self._zeros_cache = {}

    # ------------------------------------------------------------------
    def _blocks_for(self, devices, value, shape, dtype):
        """Per-device [1, *shape] blocks. ``value`` replicated over its
        group → every local device holds a full copy we can reshape in
        place; dummy rows come from a cached zeros block."""
        blocks = []
        if value is None:
            for d in devices:
                key = (d.id, shape, dtype)
                z = self._zeros_cache.get(key)
                if z is None:
                    z = jax.device_put(
                        jnp.zeros((1,) + tuple(shape), dtype=dtype), d
                    )
                    self._zeros_cache[key] = z
                blocks.append(z)
            return blocks
        per_dev = {s.device.id: s.data for s in value.addressable_shards}
        for d in devices:
            blk = per_dev.get(d.id)
            if blk is None:
                raise ValueError(
                    f"value for hop transfer has no shard on device {d}: "
                    "stage activations must be replicated over the stage "
                    "mesh before the handoff"
                )
            blocks.append(blk.reshape((1,) + tuple(shape)))
        return blocks

    def transfer(self, value: Optional[Any], shape, dtype, *,
                 reverse: bool = False):
        """One hop. Collective: every process owning bridge devices calls
        this at the same schedule point. ``value``: the group-replicated
        array on the SENDING side's processes (None elsewhere). Returns
        the received value (replicated over this process's receiving
        devices) on receiver-side processes, else None.
        ``reverse=True`` sends dst→src (the backward-grad direction)."""
        shape = tuple(shape)
        send_local = self._my_dst if reverse else self._my_src
        recv_local = self._my_src if reverse else self._my_dst
        if not send_local and not recv_local:
            return None  # not a participant in this hop
        blocks = []
        src_row = self._my_src
        dst_row = self._my_dst
        # row order must follow the mesh: row 0 = src devices, row 1 = dst
        blocks += self._blocks_for(
            src_row, value if (src_row and not reverse) else None, shape, dtype
        )
        blocks += self._blocks_for(
            dst_row, value if (dst_row and reverse) else None, shape, dtype
        )
        g = jax.make_array_from_single_device_arrays(
            (2,) + shape, self.sharding, blocks
        )
        out = self._bridge[reverse](g)
        if not recv_local:
            return None
        recv_set = set(recv_local)
        out_blocks = []
        for s in out.addressable_shards:
            if s.device in recv_set:
                out_blocks.append(s.data.reshape(shape))
        # reassemble as a replicated GLOBAL array over the receiving
        # group (each process contributes its addressable blocks) so a
        # multi-process stage sees its usual replicated placement
        recv_group = self.src_devices if reverse else self.dst_devices
        recv_mesh = Mesh(np.array(recv_group), ("r",))
        return jax.make_array_from_single_device_arrays(
            shape, NamedSharding(recv_mesh, P()), out_blocks
        )
