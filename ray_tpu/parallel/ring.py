"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

The reference has NO sequence/context parallelism (SURVEY.md §5.7 — absent;
its closest primitives are NCCL p2p channels). Here it is native: each
device holds a [b, h, s/sp, d] shard of Q, K, V; K/V shards rotate around
the ``sp`` ring via ``lax.ppermute`` while every device accumulates its
queries' attention with a running (max, sum) online-softmax merge — the
blockwise/ring attention construction (cf. PAPERS.md ring-topology entries),
riding ICI neighbor links on a real pod.

Causality across shards is handled at shard granularity: with q-shard index
i attending k-shard index j, j>i contributes nothing, j==i is causally
masked, j<i is full attention.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import DEFAULT_MASK_VALUE


def _block_attend(q, k, v, scale, mode):
    """Partial attention of one (q-shard, k-shard) pair.

    Returns (numerator [b,h,sq,d], row_max [b,h,sq], row_sum [b,h,sq]).
    mode: 0 = masked-out entirely, 1 = causal within block, 2 = full.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    sq, sk = q.shape[-2], k.shape[-2]

    def causal(s):
        ids_q = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        ids_k = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        return jnp.where(ids_q >= ids_k, s, DEFAULT_MASK_VALUE)

    s = jax.lax.switch(
        mode,
        [lambda s: jnp.full_like(s, DEFAULT_MASK_VALUE), causal, lambda s: s],
        s,
    )
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    num = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    return num, m, l


def ring_attention_local(q, k, v, axis_name: str, scale: Optional[float] = None):
    """Per-shard body — call inside shard_map with q,k,v local shards
    [b, h, s_local, d]."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    from ray_tpu.utils import jax_compat

    sp = jax_compat.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, sq, d = q.shape

    def merge(carry, k_cur, v_cur, r):
        acc, m_run, l_run = carry
        src_idx = (my_idx - r) % sp  # whose K/V shard we currently hold
        mode = jnp.where(src_idx == my_idx, 1, jnp.where(src_idx < my_idx, 2, 0))
        num, m_blk, l_blk = _block_attend(q, k_cur, v_cur, scale, mode)
        m_new = jnp.maximum(m_run, m_blk)
        c_run = jnp.exp(m_run - m_new)
        c_blk = jnp.exp(m_blk - m_new)
        acc = acc * c_run[..., None] + num * c_blk[..., None]
        l_run = l_run * c_run + l_blk * c_blk
        return acc, m_new, l_run

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(carry, r):
        k_cur, v_cur, inner = carry
        inner = merge(inner, k_cur, v_cur, r)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, inner), None

    inner0 = (
        jnp.zeros((b, h, sq, d), jnp.float32),
        jnp.full((b, h, sq), -jnp.inf, jnp.float32),
        jnp.zeros((b, h, sq), jnp.float32),
    )
    # sp-1 attend+rotate steps, then a final attend with no rotation —
    # exactly sp-1 ppermute pairs instead of sp.
    (k, v, inner), _ = jax.lax.scan(step, (k, v, inner0), jnp.arange(sp - 1))
    acc, m_run, l_run = merge(inner, k, v, sp - 1)
    out = acc / jnp.maximum(l_run, 1e-30)[..., None]
    return out.astype(q.dtype)


def make_ring_attn_fn(mesh: Mesh, axis_name: str = "sp"):
    """An attn_fn for models.transformer: [b,h,s,d] global → ring attention
    over the ``axis_name`` shards. Must run inside a jit whose inputs are
    sharded over this mesh.

    Nestable under another shard_map (the pp pipeline body): at trace
    time the AMBIENT abstract mesh — whose already-manual axes (pp) are
    marked as such — is used instead of the concrete construction-time
    mesh, and only the axes this collective touches are manualized."""
    spec = P(("dp", "fsdp"), "tp", axis_name, None)
    body = functools.partial(ring_attention_local, axis_name=axis_name)

    def attn(q, k, v):
        from ray_tpu.utils import jax_compat

        cur = jax_compat.get_abstract_mesh()
        use = cur if (cur is not None and cur.shape) else mesh
        fn = jax_compat.shard_map(
            body,
            mesh=use,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            axis_names={"dp", "fsdp", "tp", axis_name},
            check_vma=False,
        )
        return fn(q, k, v)

    return attn
