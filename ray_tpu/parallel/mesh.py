"""MeshPlan: one declarative object lowering to jax.sharding.

This is the native replacement for the reference's delegated parallelism
(SURVEY.md §2.9: the reference provides DP via torch DDP and leaves
TP/PP/SP/EP to external libraries; here they are mesh axes):

- dp    data parallel (pure replication of params)
- fsdp  fully-sharded data parallel (params sharded over this data axis —
        ZeRO-3 via GSPMD all-gather, cf. the weight-update sharding paper in
        PAPERS.md)
- ep    expert parallel (MoE experts sharded; XLA inserts all-to-alls)
- pp    pipeline parallel (layer stack split into stages;
        ray_tpu/parallel/pipeline.py runs microbatched GPipe with ppermute)
- sp    sequence/context parallel (ring attention over the seq axis;
        ray_tpu/parallel/ring.py)
- tp    tensor parallel (heads / ffn sharded; Megatron-style pairs of
        column+row splits so XLA inserts one psum per block)

Axis order puts dp outermost and tp innermost so tp collectives ride the
fastest ICI links on a real pod (mesh axes map to the physical torus
major→minor).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "ep", "pp", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    # Sequence-parallel strategy when sp > 1: "ring" (ppermute K/V rotation,
    # ray_tpu/parallel/ring.py) or "ulysses" (all-to-all head/seq swap,
    # ray_tpu/parallel/ulysses.py). Ulysses needs heads % (sp*tp) == 0.
    sp_mode: str = "ring"

    def __post_init__(self):
        if self.sp_mode not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_mode must be 'ring' or 'ulysses', got {self.sp_mode!r}"
            )

    @property
    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.ep * self.pp * self.sp * self.tp

    def sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXES}

    @classmethod
    def data_parallel(cls, n: int) -> "MeshPlan":
        return cls(dp=n)

    @classmethod
    def fsdp_plan(cls, n: int) -> "MeshPlan":
        return cls(fsdp=n)

    def validate(self, n_devices: int):
        if self.num_devices != n_devices:
            raise ValueError(
                f"MeshPlan {self.sizes()} needs {self.num_devices} devices, "
                f"got {n_devices}"
            )


def build_mesh(plan: MeshPlan, devices: Optional[Sequence[Any]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    plan.validate(len(devices))
    arr = np.array(devices).reshape([plan.dp, plan.fsdp, plan.ep, plan.pp, plan.sp, plan.tp])
    return Mesh(arr, AXES)


# ---------------------------------------------------------------------------
# Sharding rules for the flagship transformer
# ---------------------------------------------------------------------------

BATCH_AXES = ("dp", "fsdp")  # batch is split over both data axes


def param_specs(cfg, plan: MeshPlan, stacked_stage_axis: bool = False) -> Dict[str, Any]:
    """PartitionSpecs for ray_tpu.models.transformer params.

    2D weights: rows over fsdp (ZeRO-3 shard), cols over tp (Megatron
    split) — with the row/col roles flipped on the output projections so
    each attention/MLP block is one column-split matmul followed by one
    row-split matmul (single psum at the block end).

    Layer stacks carry a leading [n_layers] axis; when ``stacked_stage_axis``
    the leading axis is the pipeline-stage axis sharded over pp.
    """
    L = "pp" if (plan.pp > 1 or stacked_stage_axis) else None

    def lay(*rest):
        return P(L, *rest)

    layers = {
        "attn_norm": lay(None),
        "wq": lay("fsdp", "tp"),
        "wk": lay("fsdp", "tp"),
        "wv": lay("fsdp", "tp"),
        "wo": lay("tp", "fsdp"),
        "mlp_norm": lay(None),
    }
    if getattr(cfg, "num_experts", 0):
        layers.update(
            router=lay("fsdp", None),
            w_gate=lay("ep", "fsdp", "tp"),
            w_up=lay("ep", "fsdp", "tp"),
            w_down=lay("ep", "tp", "fsdp"),
        )
    else:
        layers.update(
            w_gate=lay("fsdp", "tp"),
            w_up=lay("fsdp", "tp"),
            w_down=lay("tp", "fsdp"),
        )
    return {
        "embed": P("tp", "fsdp"),
        "layers": layers,
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


def param_shardings(mesh: Mesh, cfg, plan: MeshPlan, params_tree=None):
    specs = param_specs(cfg, plan)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def batch_spec(plan: MeshPlan) -> P:
    """tokens [batch, seq]: batch over data axes. The raw token array keeps
    its seq dim unsharded (length s+1 rarely divides sp); under sequence
    parallelism GSPMD reshards the hidden states at the ring-attention
    shard_map boundary."""
    return P(BATCH_AXES, None)


def batch_sharding(mesh: Mesh, plan: MeshPlan) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(plan))


def activation_spec(plan: MeshPlan) -> P:
    """hidden states [batch, seq, d_model]."""
    return P(BATCH_AXES, "sp" if plan.sp > 1 else None, None)
