"""Workflow execution engine.

Reference: python/ray/workflow/workflow_executor.py + workflow_storage.py
(step checkpoints, deterministic step keys, status records).
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.utils import cloudfs
from ray_tpu.utils.serialization import deserialize, serialize
from ray_tpu.dag.node import (
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

_storage_dir: Optional[str] = None


def init(storage: Optional[str] = None):
    """Set the workflow storage root — a local path or a cloud URI
    (``gs://bucket/workflows``); all step/meta/event I/O goes through
    cloudfs (reference: workflow storage is pluggable the same way)."""
    global _storage_dir
    _storage_dir = storage or os.environ.get(
        "RAY_TPU_WORKFLOW_STORAGE", "/tmp/ray_tpu/workflows"
    )
    cloudfs.makedirs(_storage_dir)
    return _storage_dir


def _storage() -> str:
    if _storage_dir is None:
        init()
    return _storage_dir


def _wf_dir(workflow_id: str) -> str:
    return cloudfs.join(_storage(), workflow_id)


def _meta_path(workflow_id: str) -> str:
    return cloudfs.join(_wf_dir(workflow_id), "meta.json")


def _write_meta(wf_id: str, /, **updates):
    path = _meta_path(wf_id)
    meta = {}
    if cloudfs.exists(path):
        meta = json.loads(cloudfs.read_text(path))
    meta.update(updates)
    if cloudfs.is_uri(path):
        # object-store PUT is atomic per object — no tmp+rename needed
        cloudfs.write_text(path, json.dumps(meta))
    else:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, path)
    return meta


def _read_meta(workflow_id: str) -> dict:
    return json.loads(cloudfs.read_text(_meta_path(workflow_id)))


# ---------------------------------------------------------------------------
# Step checkpointing shim (runs on workers)
# ---------------------------------------------------------------------------
def _ckpt_path(wf_dir: str, key: str) -> str:
    return cloudfs.join(wf_dir, "steps", key)


def _run_step_with_checkpoint(fn, wf_dir: str, key: str, *args, **kwargs):
    """Wrapper executed as the task body: compute, checkpoint, return."""
    result = fn(*args, **kwargs)
    path = _ckpt_path(wf_dir, key)
    if cloudfs.is_uri(path):
        cloudfs.write_bytes(path, serialize(result))  # atomic PUT
        return result
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{uuid.uuid4().hex[:6]}"
    with open(tmp, "wb") as f:
        f.write(serialize(result))
    os.replace(tmp, path)  # atomic: readers never see partial checkpoints
    return result


def _run_step_no_checkpoint(fn, wf_dir: str, key: str, *args, **kwargs):
    """checkpoint=False steps: cheap/non-deterministic steps the user
    prefers to re-run on resume (reference: workflow.options(checkpoint=
    False))."""
    return fn(*args, **kwargs)


# ---------------------------------------------------------------------------
# DAG walk
# ---------------------------------------------------------------------------
def _step_key(idx: int, node: DAGNode) -> str:
    name = getattr(getattr(node, "_remote_fn", None), "_fn", None)
    name = getattr(name, "__name__", type(node).__name__)
    return f"{idx:04d}_{name}"


def _execute_workflow(dag: DAGNode, workflow_id: str, args: tuple, kwargs: dict,
                      max_concurrent_steps=None):
    import ray_tpu

    wf_dir = _wf_dir(workflow_id)
    pending_refs: list = []

    def _throttle():
        # workflow-level step-concurrency cap (reference: workflow's
        # max_running_workflows/queueing knobs): hold submission until a
        # slot frees — topo order is preserved
        if not max_concurrent_steps:
            return
        while len(pending_refs) >= max_concurrent_steps:
            ready, _ = ray_tpu.wait(pending_refs, num_returns=1, timeout=None)
            for r in ready:
                pending_refs.remove(r)
    order = dag.topo_sort()
    results: Dict[int, Any] = {}

    def resolve(v):
        if isinstance(v, DAGNode):
            return results[id(v)]
        return v

    for idx, node in enumerate(order):
        if isinstance(node, InputNode):
            if kwargs or len(args) != 1:
                results[id(node)] = args  # accessed via inp[i]
            else:
                results[id(node)] = args[0]
        elif isinstance(node, InputAttributeNode):
            key = node._key
            results[id(node)] = args[key] if isinstance(key, int) else kwargs[key]
        elif isinstance(node, MultiOutputNode):
            results[id(node)] = [resolve(a) for a in node._bound_args]
        elif isinstance(node, FunctionNode):
            key = _step_key(idx, node)
            ckpt = _ckpt_path(wf_dir, key)
            rf = node._remote_fn
            # Per-step workflow options (reference:
            # python/ray/workflow/api.py ``workflow.options`` splatted
            # into .options()): max_retries / retry_exceptions /
            # checkpoint=False.
            wopts = dict(rf._options.get("workflow_options") or {})
            if cloudfs.exists(ckpt):
                results[id(node)] = deserialize(cloudfs.read_bytes(ckpt))
                continue
            rargs = tuple(resolve(a) for a in node._bound_args)
            rkwargs = {k: resolve(v) for k, v in node._bound_kwargs.items()}
            rf._ensure_exported()
            if getattr(rf, "_fn", None) is _wait_for_event_step:
                shim_fn = _run_event_step  # needs wf_dir for claiming
            elif wopts.get("checkpoint") is False:
                shim_fn = _run_step_no_checkpoint
            else:
                shim_fn = _run_step_with_checkpoint
            # workflow max_retries covers APPLICATION failures (reference:
            # workflow step max_retries retries user exceptions) — so an
            # explicit workflow max_retries implies retry_exceptions
            # unless the user said otherwise.
            w_retries = wopts.get("max_retries")
            retry_exc = wopts.get(
                "retry_exceptions",
                True if w_retries else rf._options.get("retry_exceptions", False),
            )
            shim = ray_tpu.remote(shim_fn).options(
                num_cpus=rf._options.get("num_cpus", 1),
                max_retries=(
                    w_retries if w_retries is not None
                    else rf._options.get("max_retries", 3)
                ),
                retry_exceptions=retry_exc,
            )
            if shim_fn is _run_event_step:
                # event WAITERS don't occupy compute slots — counting
                # them could deadlock a capped DAG whose trigger step
                # hasn't been submitted yet
                ref = shim.remote(rf._fn, wf_dir, key, *rargs, **rkwargs)
            else:
                _throttle()
                ref = shim.remote(rf._fn, wf_dir, key, *rargs, **rkwargs)
                pending_refs.append(ref)
            results[id(node)] = ref
        else:
            raise ValueError(
                f"workflows support function DAGs; got {type(node).__name__} "
                "(actors hold process state, which durable re-execution "
                "cannot replay — reference drops virtual actors too)"
            )
        # Submitted steps return ObjectRefs; downstream tasks take refs as
        # args (dependency resolution fetches them worker-side). But
        # checkpoint-skip needs VALUES for args of re-run steps, so refs are
        # fine either way.
    out = results[id(order[-1])]
    return out


def run_async(dag: DAGNode, *args, workflow_id: Optional[str] = None,
              max_concurrent_steps: Optional[int] = None, **kwargs):
    """Start (or restart) a workflow; returns the output ObjectRef(s).
    ``max_concurrent_steps`` caps how many of this workflow's steps run
    at once (submission throttles; topo order preserved); None/omitted =
    uncapped."""
    import ray_tpu

    if max_concurrent_steps is not None and max_concurrent_steps < 1:
        raise ValueError(
            f"max_concurrent_steps must be >= 1 or None, got {max_concurrent_steps}"
        )
    workflow_id = workflow_id or f"wf_{uuid.uuid4().hex[:12]}"
    cloudfs.makedirs(cloudfs.join(_wf_dir(workflow_id), "steps"))
    _write_meta(
        workflow_id,
        **{
            "workflow_id": workflow_id,
            "status": "RUNNING",
            "start_time": time.time(),
            # persisted so resume() re-applies the same cap
            "max_concurrent_steps": max_concurrent_steps,
        },
    )
    cloudfs.write_bytes(
        cloudfs.join(_wf_dir(workflow_id), "dag.pkl"), serialize((dag, args, kwargs))
    )
    try:
        out = _execute_workflow(
            dag, workflow_id, args, kwargs,
            max_concurrent_steps=max_concurrent_steps,
        )
    except Exception:
        _write_meta(workflow_id, status="FAILED", end_time=time.time())
        raise
    return workflow_id, out


class Continuation:
    """A step's return value saying "the workflow continues with THIS
    sub-DAG" (reference: workflow.continuation — dynamic workflows whose
    shape depends on runtime values)."""

    def __init__(self, dag: DAGNode, *args, **kwargs):
        self.dag = dag
        self.args = args
        self.kwargs = kwargs


def continuation(dag: DAGNode, *args, **kwargs) -> Continuation:
    return Continuation(dag, *args, **kwargs)


def run(dag: DAGNode, *args, workflow_id: Optional[str] = None,
        catch_exceptions: bool = False,
        max_concurrent_steps: Optional[int] = None, **kwargs):
    """Run to completion; returns the final value(s). With
    ``catch_exceptions`` the result is ``(value, None)`` on success or
    ``(None, exception)`` on failure (reference:
    workflow.options(catch_exceptions=True) surfaced at run)."""
    try:
        value = _run_inner(
            dag, *args, workflow_id=workflow_id,
            max_concurrent_steps=max_concurrent_steps, **kwargs,
        )
    except Exception as e:  # noqa: BLE001 — surfaced per catch_exceptions
        if catch_exceptions:
            return None, e
        raise
    return (value, None) if catch_exceptions else value


def _run_inner(dag: DAGNode, *args, workflow_id: Optional[str] = None,
               max_concurrent_steps: Optional[int] = None, **kwargs):
    import ray_tpu

    workflow_id, out = run_async(
        dag, *args, workflow_id=workflow_id,
        max_concurrent_steps=max_concurrent_steps, **kwargs,
    )
    try:
        from ray_tpu.core.object_ref import ObjectRef

        if isinstance(out, list):
            value = [ray_tpu.get(o) if isinstance(o, ObjectRef) else o for o in out]
        elif isinstance(out, ObjectRef):
            value = ray_tpu.get(out)
        else:
            value = out
    except Exception:
        _write_meta(workflow_id, status="RESUMABLE", end_time=time.time())
        raise
    # Dynamic workflows: a Continuation return chains another DAG under a
    # derived id — resume replays the outer (checkpoint-skipped) and
    # re-enters the same continuation ids (deterministic keys). A failure
    # inside a continuation marks the OUTER workflow RESUMABLE too, so
    # status tooling sees one resumable unit, not a phantom RUNNING.
    depth = 0
    try:
        while isinstance(value, Continuation):
            depth += 1
            value = _run_inner(
                value.dag, *value.args,
                workflow_id=f"{workflow_id}.c{depth}",
                max_concurrent_steps=max_concurrent_steps,  # cap carries
                **value.kwargs,
            )
    except Exception:
        _write_meta(workflow_id, status="RESUMABLE", end_time=time.time())
        raise
    _write_meta(workflow_id, status="SUCCEEDED", end_time=time.time())
    # The final value doubles as the workflow output checkpoint.
    cloudfs.write_bytes(
        cloudfs.join(_wf_dir(workflow_id), "output.pkl"), serialize(value)
    )
    return value


# ---------------------------------------------------------------------------
# Durable external events (reference: python/ray/workflow/event_listener.py
# + workflow.wait_for_event) — an event is a named payload persisted in the
# workflow storage; a wait step polls for it and checkpoints like any step,
# so resumes do not re-wait for already-delivered events.
# ---------------------------------------------------------------------------
def _event_path(name: str) -> str:
    return cloudfs.join(_storage(), "events", name + ".pkl")


def trigger_event(name: str, payload: Any = None):
    path = _event_path(name)
    if cloudfs.is_uri(path):
        cloudfs.write_bytes(path, serialize(payload))  # atomic PUT
        return
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{uuid.uuid4().hex[:6]}"
    with open(tmp, "wb") as f:
        f.write(serialize(payload))
    os.replace(tmp, path)


def _wait_for_event_step(name: str, storage_root: str, timeout_s, poll_s: float):
    """Marker fn: the executor swaps in _run_event_step (which needs the
    workflow dir for crash-safe claiming)."""
    raise RuntimeError("event steps must run through workflow.run")


def _run_event_step(_fn, wf_dir: str, key: str, name: str, storage_root: str,
                    timeout_s, poll_s: float):
    """Wait for + CONSUME an event, crash-safe: the trigger file is
    atomically renamed into the workflow's own dir ("claimed"), so a
    later workflow (or a second wait step) never sees a stale payload,
    and a crash after the claim but before the checkpoint still resumes
    with the payload (the claim persists). Then checkpoints like any
    step."""
    claimed = cloudfs.join(wf_dir, "claimed_events", f"{key}.pkl")
    if not cloudfs.exists(claimed):
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        path = cloudfs.join(storage_root, "events", name + ".pkl")
        if cloudfs.is_uri(path):
            # No atomic rename on object stores: copy-then-delete. Within
            # one workflow, waiters share a deterministic key, so the
            # claim is idempotent; across DIFFERENT workflows racing for
            # one event, delivery is at-least-once (a loser that read
            # before the winner's delete also claims) — object stores
            # lack the rename primitive that makes the local path
            # exactly-once. A loser that observes the file vanish
            # mid-claim keeps waiting for the next trigger.
            while True:
                try:
                    data = cloudfs.read_bytes(path)
                except FileNotFoundError:
                    data = None
                if data is not None:
                    cloudfs.write_bytes(claimed, data)
                    cloudfs.delete(path)
                    break
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"workflow event {name!r} not delivered in {timeout_s}s"
                    )
                time.sleep(poll_s)
        else:
            os.makedirs(os.path.dirname(claimed), exist_ok=True)
            while True:
                try:
                    os.replace(path, claimed)  # atomic claim-and-consume
                    break
                except FileNotFoundError:
                    pass
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"workflow event {name!r} not delivered in {timeout_s}s"
                    )
                time.sleep(poll_s)
    payload = deserialize(cloudfs.read_bytes(claimed))
    return _run_step_with_checkpoint(lambda: payload, wf_dir, key)


def wait_for_event(name: str, timeout_s: Optional[float] = None,
                   poll_s: float = 0.2) -> DAGNode:
    """A bindable step that blocks until ``trigger_event(name, ...)``
    delivers, returning the payload."""
    import ray_tpu

    step = ray_tpu.remote(_wait_for_event_step)
    return step.bind(name, _storage(), timeout_s, poll_s)


def resume(workflow_id: str):
    """Re-run a failed/interrupted workflow; completed steps are skipped
    via their checkpoints."""
    dag_path = cloudfs.join(_wf_dir(workflow_id), "dag.pkl")
    if not cloudfs.exists(dag_path):
        raise ValueError(f"no stored workflow {workflow_id!r}")
    dag, args, kwargs = deserialize(cloudfs.read_bytes(dag_path))
    cap = _read_meta(workflow_id).get("max_concurrent_steps")
    return run(
        dag, *args, workflow_id=workflow_id, max_concurrent_steps=cap, **kwargs
    )


def get_status(workflow_id: str) -> str:
    return _read_meta(workflow_id)["status"]


def get_output(workflow_id: str):
    path = cloudfs.join(_wf_dir(workflow_id), "output.pkl")
    if not cloudfs.exists(path):
        raise ValueError(f"workflow {workflow_id!r} has no output (status: "
                         f"{get_status(workflow_id)})")
    return deserialize(cloudfs.read_bytes(path))


def list_all() -> List[dict]:
    root = _storage()
    out = []
    for wid in sorted(cloudfs.listdir(root)):
        meta = _meta_path(wid)
        if cloudfs.exists(meta):
            out.append(json.loads(cloudfs.read_text(meta)))
    return out


def delete(workflow_id: str):
    cloudfs.delete(_wf_dir(workflow_id))
