"""Workflow execution engine.

Reference: python/ray/workflow/workflow_executor.py + workflow_storage.py
(step checkpoints, deterministic step keys, status records).
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.utils.serialization import deserialize, serialize
from ray_tpu.dag.node import (
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

_storage_dir: Optional[str] = None


def init(storage: Optional[str] = None):
    """Set the workflow storage root (shared filesystem path)."""
    global _storage_dir
    _storage_dir = storage or os.environ.get(
        "RAY_TPU_WORKFLOW_STORAGE", "/tmp/ray_tpu/workflows"
    )
    os.makedirs(_storage_dir, exist_ok=True)
    return _storage_dir


def _storage() -> str:
    if _storage_dir is None:
        init()
    return _storage_dir


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage(), workflow_id)


def _meta_path(workflow_id: str) -> str:
    return os.path.join(_wf_dir(workflow_id), "meta.json")


def _write_meta(wf_id: str, /, **updates):
    path = _meta_path(wf_id)
    meta = {}
    if os.path.exists(path):
        with open(path) as f:
            meta = json.load(f)
    meta.update(updates)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, path)
    return meta


def _read_meta(workflow_id: str) -> dict:
    with open(_meta_path(workflow_id)) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# Step checkpointing shim (runs on workers)
# ---------------------------------------------------------------------------
def _ckpt_path(wf_dir: str, key: str) -> str:
    return os.path.join(wf_dir, "steps", key)


def _run_step_with_checkpoint(fn, wf_dir: str, key: str, *args, **kwargs):
    """Wrapper executed as the task body: compute, checkpoint, return."""
    result = fn(*args, **kwargs)
    path = _ckpt_path(wf_dir, key)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{uuid.uuid4().hex[:6]}"
    with open(tmp, "wb") as f:
        f.write(serialize(result))
    os.replace(tmp, path)  # atomic: readers never see partial checkpoints
    return result


# ---------------------------------------------------------------------------
# DAG walk
# ---------------------------------------------------------------------------
def _step_key(idx: int, node: DAGNode) -> str:
    name = getattr(getattr(node, "_remote_fn", None), "_fn", None)
    name = getattr(name, "__name__", type(node).__name__)
    return f"{idx:04d}_{name}"


def _execute_workflow(dag: DAGNode, workflow_id: str, args: tuple, kwargs: dict):
    import ray_tpu

    wf_dir = _wf_dir(workflow_id)
    order = dag.topo_sort()
    results: Dict[int, Any] = {}

    def resolve(v):
        if isinstance(v, DAGNode):
            return results[id(v)]
        return v

    for idx, node in enumerate(order):
        if isinstance(node, InputNode):
            if kwargs or len(args) != 1:
                results[id(node)] = args  # accessed via inp[i]
            else:
                results[id(node)] = args[0]
        elif isinstance(node, InputAttributeNode):
            key = node._key
            results[id(node)] = args[key] if isinstance(key, int) else kwargs[key]
        elif isinstance(node, MultiOutputNode):
            results[id(node)] = [resolve(a) for a in node._bound_args]
        elif isinstance(node, FunctionNode):
            key = _step_key(idx, node)
            ckpt = _ckpt_path(wf_dir, key)
            if os.path.exists(ckpt):
                with open(ckpt, "rb") as f:
                    results[id(node)] = deserialize(f.read())
                continue
            rargs = tuple(resolve(a) for a in node._bound_args)
            rkwargs = {k: resolve(v) for k, v in node._bound_kwargs.items()}
            rf = node._remote_fn
            rf._ensure_exported()
            shim = ray_tpu.remote(_run_step_with_checkpoint).options(
                num_cpus=rf._options.get("num_cpus", 1),
                max_retries=rf._options.get("max_retries", 3),
            )
            results[id(node)] = shim.remote(rf._fn, wf_dir, key, *rargs, **rkwargs)
        else:
            raise ValueError(
                f"workflows support function DAGs; got {type(node).__name__} "
                "(actors hold process state, which durable re-execution "
                "cannot replay — reference drops virtual actors too)"
            )
        # Submitted steps return ObjectRefs; downstream tasks take refs as
        # args (dependency resolution fetches them worker-side). But
        # checkpoint-skip needs VALUES for args of re-run steps, so refs are
        # fine either way.
    out = results[id(order[-1])]
    return out


def run_async(dag: DAGNode, *args, workflow_id: Optional[str] = None, **kwargs):
    """Start (or restart) a workflow; returns the output ObjectRef(s)."""
    import ray_tpu

    workflow_id = workflow_id or f"wf_{uuid.uuid4().hex[:12]}"
    os.makedirs(os.path.join(_wf_dir(workflow_id), "steps"), exist_ok=True)
    _write_meta(
        workflow_id,
        **{"workflow_id": workflow_id, "status": "RUNNING", "start_time": time.time()},
    )
    blob = serialize((dag, args, kwargs))
    with open(os.path.join(_wf_dir(workflow_id), "dag.pkl"), "wb") as f:
        f.write(blob)
    try:
        out = _execute_workflow(dag, workflow_id, args, kwargs)
    except Exception:
        _write_meta(workflow_id, status="FAILED", end_time=time.time())
        raise
    return workflow_id, out


def run(dag: DAGNode, *args, workflow_id: Optional[str] = None, **kwargs):
    """Run to completion; returns the final value(s)."""
    import ray_tpu

    workflow_id, out = run_async(dag, *args, workflow_id=workflow_id, **kwargs)
    try:
        from ray_tpu.core.object_ref import ObjectRef

        if isinstance(out, list):
            value = [ray_tpu.get(o) if isinstance(o, ObjectRef) else o for o in out]
        elif isinstance(out, ObjectRef):
            value = ray_tpu.get(out)
        else:
            value = out
    except Exception:
        _write_meta(workflow_id, status="RESUMABLE", end_time=time.time())
        raise
    _write_meta(workflow_id, status="SUCCEEDED", end_time=time.time())
    # The final value doubles as the workflow output checkpoint.
    with open(os.path.join(_wf_dir(workflow_id), "output.pkl"), "wb") as f:
        f.write(serialize(value))
    return value


def resume(workflow_id: str):
    """Re-run a failed/interrupted workflow; completed steps are skipped
    via their checkpoints."""
    dag_path = os.path.join(_wf_dir(workflow_id), "dag.pkl")
    if not os.path.exists(dag_path):
        raise ValueError(f"no stored workflow {workflow_id!r}")
    with open(dag_path, "rb") as f:
        dag, args, kwargs = deserialize(f.read())
    return run(dag, *args, workflow_id=workflow_id, **kwargs)


def get_status(workflow_id: str) -> str:
    return _read_meta(workflow_id)["status"]


def get_output(workflow_id: str):
    path = os.path.join(_wf_dir(workflow_id), "output.pkl")
    if not os.path.exists(path):
        raise ValueError(f"workflow {workflow_id!r} has no output (status: "
                         f"{get_status(workflow_id)})")
    with open(path, "rb") as f:
        return deserialize(f.read())


def list_all() -> List[dict]:
    root = _storage()
    out = []
    for wid in sorted(os.listdir(root)):
        meta = _meta_path(wid)
        if os.path.exists(meta):
            with open(meta) as f:
                out.append(json.load(f))
    return out


def delete(workflow_id: str):
    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
