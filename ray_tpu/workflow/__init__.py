"""Workflows: durable DAG execution with per-step checkpointing.

Reference: python/ray/workflow/ (api.py — ``workflow.run(dag_node,
workflow_id=...)`` over the Ray DAG API, per-step checkpoints to workflow
storage, ``resume``/``resume_all``, status tracking). Virtual actors are
deliberately omitted (deprecated upstream).

Rebuild: workflows execute ray_tpu DAGs (``fn.bind(...)``) where every
step runs as a normal task wrapped in a checkpointing shim — the worker
writes the step's result to ``<storage>/<workflow_id>/steps/<key>`` before
returning, so a crashed/resumed workflow skips completed steps and only
re-executes the frontier. Storage is a shared filesystem directory (on TPU
pods: NFS/GCS-fuse), set via :func:`init` or ``RAY_TPU_WORKFLOW_STORAGE``.
"""
from ray_tpu.workflow.execution import (
    Continuation,
    continuation,
    delete,
    get_output,
    get_status,
    init,
    list_all,
    resume,
    run,
    run_async,
    trigger_event,
    wait_for_event,
)

__all__ = [
    "init",
    "run",
    "run_async",
    "resume",
    "get_status",
    "get_output",
    "list_all",
    "delete",
    "continuation",
    "Continuation",
    "wait_for_event",
    "trigger_event",
]
