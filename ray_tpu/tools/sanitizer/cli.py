"""``ray-tpu sanitize`` — the ConcSan concurrency-correctness gate.

One command, three passes, one verdict:

1. **static guards** — lint rules RTL009–RTL011 over the guard
   annotations (``GuardedDict``/``GuardedSet``/``@guarded_by``);
2. **static lock graph** — RTL005's lexical acquisition graph plus
   one-hop call-through derived edges (``lockorder.build_static``);
3. **dynamic** (optional) — ConcSan process reports from
   ``--dynamic-dir`` (or produced on the spot by ``--pytest``): runtime
   witness findings (empty locksets, owner-thread violations,
   ``@guarded_by`` contract breaks) and the static↔dynamic lock-order
   cross-check. A dynamic-only edge — an acquisition order the AST
   cannot see and no allowlist entry explains — fails the gate, because
   RTL005's inversion detection is blind to it.

Exit-code contract (stable for CI):
  0  clean
  1  findings (static guard findings, runtime findings, or unexplained
     dynamic-only lock-order edges)
  2  usage or configuration error

``--json`` emits one machine-readable document on stdout.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
from typing import List, Optional

from ray_tpu.tools.lint.framework import _find_root, load_config, run_lint
from ray_tpu.tools.sanitizer import lockorder
from ray_tpu.tools.sanitizer.runtime import load_reports

GUARD_RULES = ["RTL009", "RTL010", "RTL011"]


def add_sanitize_args(sp: argparse.ArgumentParser):
    sp.add_argument(
        "paths", nargs="*", help="files/dirs to analyze (default: config paths)"
    )
    sp.add_argument("--root", default=None, help="project root (default: auto)")
    sp.add_argument("--json", action="store_true", help="machine-readable output")
    sp.add_argument(
        "--dynamic-dir",
        default=None,
        metavar="DIR",
        help="directory of concsan-<pid>.json process reports to cross-check "
        "(produced by running any workload with RAY_TPU_CONCSAN=1 and "
        "RAY_TPU_CONCSAN_DIR=DIR)",
    )
    sp.add_argument(
        "--pytest",
        nargs=argparse.REMAINDER,
        default=None,
        metavar="ARGS",
        help="run `pytest ARGS` under ConcSan in a subprocess, then analyze "
        "its reports (convenience wrapper around --dynamic-dir)",
    )
    sp.add_argument(
        "--no-baseline",
        action="store_true",
        help="report baselined static findings too",
    )


def _run_pytest_under_concsan(
    pytest_args: List[str], report_dir: str, json_mode: bool
) -> int:
    env = dict(os.environ)
    env["RAY_TPU_CONCSAN"] = "1"
    env["RAY_TPU_CONCSAN_DIR"] = report_dir
    cmd = [sys.executable, "-m", "pytest", *pytest_args]
    print(f"ray-tpu sanitize: running {' '.join(cmd)} under ConcSan", file=sys.stderr)
    if not json_mode:
        return subprocess.call(cmd, env=env)
    # --json promises a single JSON document on stdout; the workload's
    # output must not interleave with it.
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True)
    sys.stderr.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    return proc.returncode


def cmd_sanitize(args) -> int:
    root = os.path.abspath(args.root) if args.root else _find_root()
    try:
        config = load_config(root)
    except Exception as e:  # malformed pyproject section
        print(f"ray-tpu sanitize: bad config: {e}", file=sys.stderr)
        return 2
    paths = args.paths or None

    # -- pass 1: static guard checking (RTL009–011) ---------------------
    config.enable = list(GUARD_RULES)
    config.disable = []
    static_result = run_lint(
        paths=paths, root=root, config=config, use_baseline=not args.no_baseline
    )
    # Only guard rules ran, so baseline entries for the other rules
    # naturally went unmatched — that is `ray-tpu lint`'s staleness to
    # police, not this gate's.
    static_result.stale_baseline = [
        e for e in static_result.stale_baseline if e.get("rule") in GUARD_RULES
    ]
    if static_result.files_checked == 0:
        print(
            f"ray-tpu sanitize: no Python files found under "
            f"{paths or config.paths} (root {root})",
            file=sys.stderr,
        )
        return 2

    # -- pass 2: static lock graph --------------------------------------
    static_graph = lockorder.build_static(root, paths=paths, config=load_config(root))

    # -- pass 3: dynamic reports (optional) -----------------------------
    dynamic_dir: Optional[str] = args.dynamic_dir
    pytest_rc: Optional[int] = None
    tmp: Optional[tempfile.TemporaryDirectory] = None
    if args.pytest is not None:
        if dynamic_dir is None:
            tmp = tempfile.TemporaryDirectory(prefix="concsan-")
            dynamic_dir = tmp.name
        pytest_rc = _run_pytest_under_concsan(args.pytest, dynamic_dir, args.json)

    runtime_findings: List[dict] = []
    cross: Optional[dict] = None
    reports: List[dict] = []
    try:
        if dynamic_dir is not None:
            reports = load_reports(dynamic_dir)
            if not reports:
                print(
                    f"ray-tpu sanitize: no ConcSan reports under {dynamic_dir} "
                    "(was the workload run with RAY_TPU_CONCSAN=1 and "
                    "RAY_TPU_CONCSAN_DIR set?)",
                    file=sys.stderr,
                )
                return 2
            dynamic_edges = [e for r in reports for e in r.get("lock_graph", [])]
            runtime_findings = [
                f for r in reports for f in r.get("findings", [])
            ]
            cross = lockorder.cross_check(
                root, dynamic_edges, static=static_graph, paths=paths
            )
    finally:
        if tmp is not None:
            tmp.cleanup()

    dynamic_only = cross["dynamic_only"] if cross else []
    failed = bool(
        not static_result.clean or runtime_findings or dynamic_only
        or (pytest_rc not in (None, 0))
    )

    doc = {
        "version": 1,
        "clean": not failed,
        "static": static_result.to_json(),
        "lock_graph": {
            "static_edges": len(static_graph.edges),
            "derived_edges": len(static_graph.derived),
            "creation_sites": len(static_graph.creation_sites),
        },
        "runtime_findings": runtime_findings,
        "cross_check": cross,
        "processes_reported": len(reports),
        "pytest_exit": pytest_rc,
    }
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 1 if failed else 0

    for f in static_result.findings:
        print(f.render())
    for f in runtime_findings:
        print(
            f"runtime {f.get('kind')}: {f.get('state')} "
            f"op={f.get('op')} at {f.get('site')} thread={f.get('thread')} "
            f"held={f.get('held')}"
            + (
                f" fuzz_seed={f['fuzz_seed']}"
                if f.get("fuzz_seed") is not None
                else ""
            )
        )
    for e in dynamic_only:
        print(
            f"dynamic-only lock edge: {e['src']} -> {e['dst']} "
            f"(observed {e['observed_at']}; no lexical/derived/allowlisted "
            "explanation — RTL005 cannot see inversions against it)"
        )
    summary = (
        f"ray-tpu sanitize: {static_result.files_checked} files, "
        f"{len(static_result.findings)} static finding(s), "
        f"{len(static_graph.edges)} static lock edges "
        f"(+{len(static_graph.derived)} derived)"
    )
    if cross is not None:
        summary += (
            f"; dynamic: {len(reports)} process report(s), "
            f"{len(runtime_findings)} runtime finding(s), "
            f"{len(cross['matched'])} matched / {len(dynamic_only)} "
            f"dynamic-only / {len(cross['allowlisted'])} allowlisted edges "
            f"({cross['external_edges']} external)"
        )
    if pytest_rc is not None:
        summary += f"; pytest exit {pytest_rc}"
    print(summary)
    return 1 if failed else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray-tpu sanitize", description=__doc__)
    add_sanitize_args(p)
    return cmd_sanitize(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
