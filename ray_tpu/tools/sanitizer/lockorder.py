"""Static ↔ dynamic lock-order cross-check.

RTL005 builds the project's lexical lock-acquisition graph from nested
``with`` statements; lockwatch observes the real one at runtime. Each
side sees things the other cannot:

* **static-only edges** — orders written in the source but never
  exercised by the suite (coverage gaps: informational);
* **dynamic-only edges** — orders the AST cannot see (locks taken
  through calls, callbacks, or data-driven dispatch). These are the
  dangerous ones: RTL005's inversion detection is blind to them, so an
  inversion against a dynamic-only edge ships silently.

The join key is the lock's CREATION site: lockwatch records the
``(file, line)`` where ``threading.Lock()`` ran (see
``lockwatch.graph_snapshot``), and this module AST-scans the same
assignment sites (``self._lock = threading.Lock()``) to name them
canonically the way RTL005 does (``module.Class._lock``).

To keep "dynamic-only" honest, the static side is widened with ONE hop
of call-through: a lock held around ``self.m(...)`` reaches the locks
``m`` acquires lexically, and a ``@guarded_by("g")`` method's body
counts as holding ``g``. Derived edges EXPLAIN dynamic observations;
they never feed RTL005's inversion reporting. Remaining explained
dynamic-only edges live in a committed allowlist
(``.concsan-edges.json``) with one-line justifications — the gate
fails on any edge in none of these buckets.
"""
from __future__ import annotations

import ast
import json
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.tools.lint.framework import (
    LintConfig,
    ModuleContext,
    iter_python_files,
    load_config,
)
from ray_tpu.tools.lint.rules import (
    LockOrder,
    dotted,
    import_aliases,
    is_lock_expr,
    lock_text,
)

ALLOWLIST_FILE = ".concsan-edges.json"

_LOCK_FACTORIES = {"threading.Lock", "threading.RLock", "Lock", "RLock"}


def _is_lock_ctor(call: ast.Call, aliases: Dict[str, str]) -> bool:
    d = dotted(call.func)
    if not d:
        return False
    head = d.split(".", 1)[0]
    resolved = d.replace(head, aliases.get(head, head), 1)
    return resolved in _LOCK_FACTORIES or resolved.endswith(
        ("threading.Lock", "threading.RLock")
    )


class StaticGraph:
    """The static side: lexical edges (RTL005's), one-hop derived edges,
    and the creation-site → canonical-name map."""

    def __init__(self):
        self.edges: Set[Tuple[str, str]] = set()
        self.derived: Set[Tuple[str, str]] = set()
        self.creation_sites: Dict[Tuple[str, int], str] = {}
        # class canon prefix -> method name -> locks acquired lexically
        self._method_locks: Dict[str, Dict[str, Set[str]]] = {}
        # (held lock, class canon, called method name)
        self._calls_under_lock: Set[Tuple[str, str, str]] = set()
        # (guard canon, class canon, method name) for @guarded_by bodies
        self._guarded_methods: List[Tuple[str, str, str]] = []


def build_static(
    root: str,
    paths: Optional[Iterable[str]] = None,
    config: Optional[LintConfig] = None,
) -> StaticGraph:
    root = os.path.abspath(root)
    config = config or load_config(root)
    lock_order = LockOrder()
    g = StaticGraph()
    for path in iter_python_files(list(paths or config.paths), root, config.exclude):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
        ctx = ModuleContext(path, rel, source, tree)
        lock_order.check(ctx)  # accumulates lexical edges
        _scan_module(ctx, g)
    g.edges = set(lock_order.edges)
    _expand_one_hop(g)
    return g


def _scan_module(ctx: ModuleContext, g: StaticGraph) -> None:
    aliases = import_aliases(ctx.tree)
    canon = LockOrder()._canon  # reuse RTL005's identity rules

    for node in ast.walk(ctx.tree):
        # creation sites: self.X = threading.Lock() / module _X = Lock()
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            value = node.value
            if isinstance(value, ast.Call) and _is_lock_ctor(value, aliases):
                for target in targets:
                    name = canon(ctx, aliases, target, node)
                    g.creation_sites[(ctx.relpath, value.lineno)] = name
            continue

        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cls = ctx.enclosing_class(node)
        cls_canon = (
            f"{ctx.module_name}.{cls.name}" if cls else ctx.module_name
        )
        acquired = _locks_acquired(ctx, aliases, canon, node)
        if acquired:
            g._method_locks.setdefault(cls_canon, {})[node.name] = acquired
        for guard in _guard_decorations(node):
            g._guarded_methods.append(
                (f"{cls_canon}.{guard.lstrip('.')}", cls_canon, node.name)
            )
        for held, callee in _self_calls_under_locks(ctx, aliases, canon, node):
            g._calls_under_lock.add((held, cls_canon, callee))


def _guard_decorations(fn: ast.AST) -> Iterable[str]:
    for dec in getattr(fn, "decorator_list", ()):
        if (
            isinstance(dec, ast.Call)
            and dotted(dec.func) in ("guarded_by", "guards.guarded_by")
            and dec.args
            and isinstance(dec.args[0], ast.Constant)
            and isinstance(dec.args[0].value, str)
            and dec.args[0].value != "@owner-thread"
        ):
            yield dec.args[0].value


def _locks_acquired(ctx, aliases, canon, fn) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if is_lock_expr(item.context_expr):
                    out.add(canon(ctx, aliases, item.context_expr, node))
    return out


def _self_calls_under_locks(ctx, aliases, canon, fn):
    """(held lock canon, method name) for every ``self.m(...)`` call
    lexically inside a lock-holding ``with`` within this function."""
    for node in ast.walk(fn):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        held = [
            canon(ctx, aliases, item.context_expr, node)
            for item in node.items
            if is_lock_expr(item.context_expr)
        ]
        if not held:
            continue
        for call in ast.walk(node):
            if (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and dotted(call.func.value) in ("self", "cls")
            ):
                for h in held:
                    yield h, call.func.attr


def _expand_one_hop(g: StaticGraph) -> None:
    for held, cls_canon, callee in g._calls_under_lock:
        for inner in g._method_locks.get(cls_canon, {}).get(callee, ()):
            if inner != held:
                g.derived.add((held, inner))
    for guard_canon, cls_canon, method in g._guarded_methods:
        for inner in g._method_locks.get(cls_canon, {}).get(method, ()):
            if inner != guard_canon:
                g.derived.add((guard_canon, inner))


def load_allowlist(root: str) -> Dict[Tuple[str, str], str]:
    path = os.path.join(root, ALLOWLIST_FILE)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    return {
        (e["src"], e["dst"]): e.get("justification", "")
        for e in data.get("edges", [])
    }


def cross_check(
    root: str,
    dynamic_edges: Iterable[dict],
    static: Optional[StaticGraph] = None,
    paths: Optional[Iterable[str]] = None,
) -> dict:
    """Classify every observed (dynamic) edge against the static graph.

    ``dynamic_edges`` is a concatenation of ``lock_graph`` lists from
    ConcSan process reports (``lockwatch.graph_snapshot`` format).
    Edges whose endpoints are not package creation sites (locks made by
    tests, or created before lockwatch installed) classify as
    ``external`` — visible in the report, not gate failures.
    """
    root = os.path.abspath(root)
    static = static or build_static(root, paths=paths)
    allow = load_allowlist(root)

    def _canon_of(site: dict) -> Optional[str]:
        path, line = site.get("file", "?"), site.get("line", 0)
        if not path or path == "?":
            return None
        try:
            rel = os.path.relpath(path, root).replace(os.sep, "/")
        except ValueError:
            return None
        if rel.startswith(".."):
            return None
        return static.creation_sites.get((rel, line))

    matched: List[dict] = []
    dynamic_only: List[dict] = []
    allowlisted: List[dict] = []
    external: List[dict] = []
    seen: Set[Tuple[str, str]] = set()
    for edge in dynamic_edges:
        a = _canon_of(edge.get("src_site", {}))
        b = _canon_of(edge.get("dst_site", {}))
        if a is None or b is None:
            external.append(edge)
            continue
        pair = (a, b)
        if pair in seen:
            continue
        seen.add(pair)
        entry = {"src": a, "dst": b, "observed_at": edge.get("observed_at", "?")}
        if pair in static.edges or pair in static.derived:
            matched.append(entry)
        elif pair in allow:
            allowlisted.append({**entry, "justification": allow[pair]})
        else:
            dynamic_only.append(entry)

    static_only = sorted(
        f"{a} -> {b}"
        for (a, b) in static.edges
        if (a, b) not in {(e["src"], e["dst"]) for e in matched}
    )
    return {
        "matched": matched,
        "dynamic_only": dynamic_only,
        "allowlisted": allowlisted,
        "external_edges": len(external),
        "static_only": static_only,
        "static_edges": len(static.edges),
        "derived_edges": len(static.derived),
        "creation_sites": len(static.creation_sites),
    }
