"""ConcSan — the two-sided concurrency sanitizer.

Static side: lint rules RTL009–RTL011 (``tools/lint/guard_rules.py``)
check the ``@guarded_by`` / ``GuardedDict`` annotation vocabulary
(``util/guards.py``) lexically. Dynamic side, this package:

* :mod:`runtime` — the lockset-style runtime witness (Eraser
  algorithm): records the held-lock set at every annotated-state access
  and flags accesses whose lockset intersection goes empty, plus
  owner-thread violations on the control plane's single-writer state.
* :mod:`fuzzer` — a seeded deterministic thread-interleaving fuzzer
  injecting preemptions at lock-boundary yield points; a finding's seed
  replays the schedule that produced it.
* :mod:`lockorder` — cross-checks lockwatch's observed lock-order
  edges against the static graph RTL005 builds, reporting static-only
  (never exercised) and dynamic-only (invisible to the AST) edges.
* :mod:`cli` — ``ray-tpu sanitize`` (human + ``--json``).

Enable per process with ``RAY_TPU_CONCSAN=1`` (+ optionally
``RAY_TPU_CONCSAN_DIR=<dir>`` to have every cluster process dump its
findings as ``concsan-<pid>.json`` at exit — the controller and agents
are subprocesses, so in-memory state never crosses back).
"""
