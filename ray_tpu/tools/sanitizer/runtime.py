"""ConcSan runtime witness: the Eraser lockset algorithm over guarded state.

Every access to a ``GuardedDict`` / ``GuardedSet`` (checked variants,
selected only when this module is enabled) lands in :func:`note_access`
with the container's :class:`~ray_tpu.util.guards.GuardMeta`. The witness
piggybacks on lockwatch — :func:`ray_tpu.util.lockwatch.current_held`
gives the calling thread's held watched-lock set for free — and runs the
classic per-variable state machine:

    virgin → exclusive → shared_read → shared_mod

* ``virgin → exclusive``: first access binds the owning thread; a
  single-threaded container never refines a lockset (constructor fills,
  test-local use, etc. stay silent).
* ``exclusive → shared_*``: a second thread arrives. For lock-guarded
  state the candidate lockset C(v) initializes to the held set and every
  later access intersects it; C(v) = ∅ on a *write-shared* container is
  the race candidate (``empty_lockset`` finding, counted through
  ``lockwatch_empty_lockset_total`` so it lands in the Grafana
  Self-healing row).
* ``OWNER_THREAD`` guards (the controller/agent asyncio single-writer
  discipline) use thread identity instead of locksets, with exactly ONE
  ownership transfer allowed — the constructor-thread → loop-thread
  handoff every cluster process performs — after which any foreign
  access is an ``owner_thread`` finding.

Deliberately NOT here: sampling or probabilistic throttling. The checked
containers only exist when ConcSan is on, so the full-fidelity witness
costs nothing in production.
"""
from __future__ import annotations

import atexit
import contextlib
import itertools
import json
import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from ray_tpu.util import lockwatch
from ray_tpu.util.guards import OWNER_THREAD, GuardMeta

logger = logging.getLogger("ray_tpu.concsan")

_enabled = False
# Raw (never-watched, never-fuzzed) lock for the findings list: watched
# locks would feed the witness's own bookkeeping back into locksets.
_state_lock = lockwatch._REAL_LOCK()
_MAX_FINDINGS = 256
_findings: List[dict] = []
_finding_keys: set = set()
_tls = threading.local()
_thread_names: Dict[int, str] = {}
# threading.get_ident() values are RECYCLED when threads exit — two
# sequential short-lived threads routinely get the same ident, which
# would make the witness see one thread where there were two (missed
# sharing) or mistake a fresh thread for a dead owner. Each OS thread
# instead gets a process-unique token on first contact, pinned in its
# TLS for its lifetime.
_thread_tokens = itertools.count(1)


def _thread_token() -> int:
    tok = getattr(_tls, "token", None)
    if tok is None:
        tok = _tls.token = next(_thread_tokens)
        _thread_names[tok] = threading.current_thread().name
    return tok

# Installed by the fuzzer: called as hook("access", describe) before each
# guarded access so injected preemptions widen read-modify-write windows.
_access_hook = None
# The active fuzzer seed, stamped into findings so any race the fuzzer
# surfaces carries its replay schedule.
_fuzz_seed: Optional[int] = None


def enabled() -> bool:
    return _enabled


def enable(report_dir: Optional[str] = None) -> None:
    """Turn the witness on for THIS process (idempotent).

    Installs lockwatch if needed (the held-set source), registers this
    module with ``util.guards`` so containers pick the checked variants,
    and — when ``report_dir`` is given — registers an atexit dump so
    subprocess findings survive process exit.
    """
    global _enabled
    if _enabled:
        return
    lockwatch.install()
    from ray_tpu.util import guards

    guards._runtime = sys.modules[__name__]
    _enabled = True
    if report_dir:
        atexit.register(_dump_report, report_dir)
    logger.info("ConcSan enabled (report_dir=%s)", report_dir or "<none>")


def disable() -> None:
    """Turn the witness off (tests). Containers constructed while it was
    on keep their checked accessors but ``note_access`` early-outs, so
    they revert to plain-dict cost minus one predictable branch;
    containers constructed after this are plain again. Does not
    uninstall lockwatch (other tooling shares it)."""
    global _enabled
    _enabled = False


def maybe_enable() -> bool:
    """Enable iff ``RAY_TPU_CONCSAN=1`` — called from ``ray_tpu/__init__``
    so every cluster process (controller/agents/workers are subprocesses
    inheriting the env) self-arms on import."""
    if os.environ.get("RAY_TPU_CONCSAN", "") == "1":
        enable(os.environ.get("RAY_TPU_CONCSAN_DIR") or None)
    return _enabled


def set_fuzz_seed(seed: Optional[int]) -> None:
    global _fuzz_seed
    _fuzz_seed = seed


def set_access_hook(hook) -> None:
    global _access_hook
    _access_hook = hook


@contextlib.contextmanager
def sanctioned():
    """Mark this thread's accesses as sanctioned (the ``snapshot()`` /
    ``cycle_snapshot()`` helpers: one atomic GIL-protected copy is the
    blessed way to read guarded state without its guard)."""
    prev = getattr(_tls, "sanctioned", 0)
    _tls.sanctioned = prev + 1
    try:
        yield
    finally:
        _tls.sanctioned = prev


def _site() -> str:
    """First stack frame outside guards.py/runtime.py — the user access."""
    try:
        f = sys._getframe(2)
        while f is not None and f.f_code.co_filename.endswith(
            ("guards.py", os.path.join("sanitizer", "runtime.py"))
        ):
            f = f.f_back
        if f is None:
            return "?"
        return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"
    except Exception:  # noqa: BLE001 — frame depth off at thread exit
        return "?"


def _add_finding(kind: str, meta_desc: str, detail: dict) -> bool:
    """Record one deduplicated finding; returns True if it was new."""
    site = detail.get("site", "?")
    key = (kind, meta_desc, site)
    with _state_lock:
        if key in _finding_keys or len(_findings) >= _MAX_FINDINGS:
            return False
        _finding_keys.add(key)
        finding = {
            "kind": kind,
            "state": meta_desc,
            "fuzz_seed": _fuzz_seed,
            "pid": os.getpid(),
            "time": time.time(),
            **detail,
        }
        _findings.append(finding)
    logger.warning("ConcSan %s: %s %s", kind, meta_desc, detail)
    return True


def note_access(meta: GuardMeta, op: str) -> None:
    """One guarded-container access (called by the checked variants)."""
    if not _enabled or getattr(_tls, "sanctioned", 0):
        return
    hook = _access_hook
    if hook is not None:
        try:
            hook("access", meta.describe())
        except Exception as e:  # noqa: BLE001
            # the fuzzer must never break the program under test
            logger.debug("ConcSan access hook failed: %s", e)
    t = _thread_token()
    held = lockwatch.current_held()
    held_ids = frozenset(id(entry[0]) for entry in held)

    with _state_lock:
        kind = _step(meta, op, t, held_ids)
    if kind is None:
        return
    # Finding emission happens OUTSIDE _state_lock: _add_finding retakes
    # it, and the metrics counter behind note_empty_lockset acquires a
    # watched lock — neither may nest inside the state machine's lock.
    new = _add_finding(
        kind,
        meta.describe(),
        {
            "op": op,
            "site": _site(),
            "thread": _thread_names.get(t, str(t)),
            "owner": _thread_names.get(meta.owner_thread, str(meta.owner_thread)),
            "held": [_lock_name(e[0]) for e in held],
            "guard": meta.guard,
        },
    )
    if new and kind == "empty_lockset":
        lockwatch.note_empty_lockset()


def _step(meta: GuardMeta, op: str, t: int, held_ids) -> Optional[str]:
    """Advance one meta's Eraser state machine (caller holds _state_lock).
    Returns the finding kind to emit, or None."""
    if len(meta.threads_seen) < 32:
        meta.threads_seen.add(t)

    if meta.state == "virgin":
        meta.state = "exclusive"
        meta.owner_thread = t
        return None

    if meta.guard == OWNER_THREAD:
        if t == meta.owner_thread:
            return None
        if not meta.transferred:
            # the one blessed handoff: constructed on the spawning thread,
            # owned by the event-loop thread ever after
            meta.transferred = True
            meta.owner_thread = t
            return None
        if "owner_thread" in meta.reported:
            return None
        meta.reported.add("owner_thread")
        return "owner_thread"

    # lock-named guard: Eraser proper
    if meta.state == "exclusive":
        if t == meta.owner_thread:
            return None
        meta.state = "shared_mod" if op == "write" else "shared_read"
        meta.lockset = held_ids
        return None

    if op == "write":
        meta.state = "shared_mod"
    meta.lockset = (
        held_ids if meta.lockset is None else meta.lockset & held_ids
    )
    if meta.state != "shared_mod" or meta.lockset:
        return None
    if "empty_lockset" in meta.reported:
        return None
    meta.reported.add("empty_lockset")
    return "empty_lockset"


def note_method_entry(obj, guard: str, qualname: str) -> None:
    """``@guarded_by("<lock>")`` contract check on method entry: the named
    lock must already be held by this thread (callers acquire)."""
    if not _enabled or guard == OWNER_THREAD:
        return
    lock = getattr(obj, guard, None)
    if lock is None or not isinstance(lock, lockwatch.WatchedLock):
        return  # unwatched guard: identity can't be checked, skip
    if any(entry[0] is lock for entry in lockwatch.current_held()):
        return
    _add_finding(
        "guard_method",
        f"{qualname} (guarded_by {guard})",
        {
            "op": "call",
            "site": _site(),
            "thread": threading.current_thread().name,
            "held": [
                _lock_name(e[0]) for e in lockwatch.current_held()
            ],
        },
    )


def _lock_name(lock) -> str:
    try:
        return lockwatch._names.get(lock._wuid, "?")
    except Exception:  # noqa: BLE001 — foreign lock object
        return "?"


def report() -> dict:
    """Everything the CLI / gate consumes, JSON-safe."""
    with _state_lock:
        findings = list(_findings)
    return {
        "enabled": _enabled,
        "pid": os.getpid(),
        "fuzz_seed": _fuzz_seed,
        "findings": findings,
        "lock_graph": lockwatch.graph_snapshot(),
    }


def reset() -> None:
    """Clear findings (tests). Does not touch lockwatch's graph."""
    with _state_lock:
        _findings.clear()
        _finding_keys.clear()


def _dump_report(report_dir: str) -> None:
    try:
        os.makedirs(report_dir, exist_ok=True)
        path = os.path.join(report_dir, f"concsan-{os.getpid()}.json")
        with open(path, "w") as f:
            json.dump(report(), f, indent=1, sort_keys=True)
    except Exception as e:  # noqa: BLE001 — exit path, nothing to crash
        logger.warning("ConcSan report dump failed: %s", e)


def load_reports(report_dir: str) -> List[dict]:
    """Read every ``concsan-*.json`` a cluster's processes dumped."""
    out: List[dict] = []
    try:
        names = sorted(os.listdir(report_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("concsan-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(report_dir, name)) as f:
                out.append(json.load(f))
        except (OSError, ValueError) as e:
            logger.warning("unreadable ConcSan report %s: %s", name, e)
    return out
