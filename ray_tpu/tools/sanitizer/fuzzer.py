"""Seeded thread-interleaving fuzzer: deterministic preemption injection.

A data race needs two things to be SEEN: the buggy access pattern and an
unlucky interleaving. The lockset witness (runtime.py) removes the
second requirement for lock-discipline bugs, but actually *corrupting*
state — and re-corrupting it in a regression test — takes control over
where threads get preempted. This module injects sleeps at the three
yield points the sanitizer already instruments:

* ``("acquire", lock)`` — before blocking on a watched lock;
* ``("release", lock)`` — just after letting one go;
* ``("access", state)`` — before each guarded-container access.

Decisions are a pure function of ``(seed, thread name, point kind,
per-thread counter)`` through crc32 — **not** the builtin ``hash``
(randomized per process) and **not** wall-clock or ``random`` state — so
the same seed replays the same injection schedule in any process. A
finding records the active seed; a regression test replays it:

    with fuzzing(seed=finding["fuzz_seed"]):
        run_the_racy_workload()

The schedule keeps a bounded trace of its decisions for debugging and
for asserting replay identity in tests.
"""
from __future__ import annotations

import contextlib
import threading
import time
import zlib
from typing import Iterable, List, Optional, Tuple

from ray_tpu.util import lockwatch
from ray_tpu.tools.sanitizer import runtime

_POINTS = ("acquire", "release", "access")


class FuzzSchedule:
    """One deterministic preemption schedule, parameterized by seed.

    ``period`` controls injection density (one preemption per ~period
    decisions per thread); ``max_sleep_us`` bounds each injected sleep.
    Defaults are tuned so a fuzzed test runs ~2-3x its normal wall time,
    not 100x.
    """

    def __init__(
        self,
        seed: int,
        period: int = 4,
        max_sleep_us: int = 500,
        points: Iterable[str] = _POINTS,
    ):
        self.seed = int(seed)
        self.period = max(1, int(period))
        self.max_sleep_us = max(1, int(max_sleep_us))
        self.points = frozenset(points)
        self._tls = threading.local()
        self._trace_lock = lockwatch._REAL_LOCK()
        self._trace: List[Tuple[str, str, int, int]] = []
        self._MAX_TRACE = 4096

    def decide(self, thread_name: str, point: str, counter: int) -> int:
        """Pure decision function: microseconds to sleep (0 = don't).
        Exposed for replay-identity tests."""
        h = zlib.crc32(
            f"{self.seed}:{thread_name}:{point}:{counter}".encode()
        )
        if h % self.period:
            return 0
        return 1 + (h >> 8) % self.max_sleep_us

    def __call__(self, point: str, detail: str) -> None:
        if point not in self.points:
            return
        counters = getattr(self._tls, "counters", None)
        if counters is None:
            counters = self._tls.counters = {}
        n = counters.get(point, 0)
        counters[point] = n + 1
        name = threading.current_thread().name
        us = self.decide(name, point, n)
        if not us:
            return
        with self._trace_lock:
            if len(self._trace) < self._MAX_TRACE:
                self._trace.append((name, point, n, us))
        time.sleep(us / 1e6)

    def trace(self) -> List[Tuple[str, str, int, int]]:
        with self._trace_lock:
            return list(self._trace)


_active: Optional[FuzzSchedule] = None


def install(schedule: FuzzSchedule) -> None:
    """Route both yield-point sources (lockwatch lock boundaries, ConcSan
    guarded accesses) through the schedule and stamp its seed into
    findings."""
    global _active
    _active = schedule
    lockwatch.set_yield_hook(schedule)
    runtime.set_access_hook(schedule)
    runtime.set_fuzz_seed(schedule.seed)


def uninstall() -> None:
    global _active
    _active = None
    lockwatch.set_yield_hook(None)
    runtime.set_access_hook(None)
    runtime.set_fuzz_seed(None)


def active() -> Optional[FuzzSchedule]:
    return _active


@contextlib.contextmanager
def fuzzing(seed: int, **kw):
    """Run a block under a seeded preemption schedule (replay entry
    point: pass a finding's ``fuzz_seed``)."""
    sched = FuzzSchedule(seed, **kw)
    install(sched)
    try:
        yield sched
    finally:
        uninstall()


def sweep(workload, seeds: Iterable[int], **kw) -> Optional[int]:
    """Run ``workload()`` once per seed; return the first seed whose run
    produced a ConcSan finding (None if all clean). The witness findings
    are reset per seed so attribution is unambiguous."""
    for seed in seeds:
        runtime.reset()
        with fuzzing(seed, **kw):
            workload()
        if runtime.report()["findings"]:
            return seed
    runtime.reset()
    return None
