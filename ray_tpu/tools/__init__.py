"""Developer tooling that ships with ray_tpu (static analysis, etc.).

Nothing here is imported by the runtime — tools are reached via the
``ray-tpu`` CLI or directly (``python -m ray_tpu.tools.lint.cli``).
"""
