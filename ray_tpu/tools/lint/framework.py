"""Project-aware static-analysis framework for ray_tpu.

Reference shape: a tiny flake8/ruff-style engine, but with whole-project
context (cross-module lock-order graphs need more than one file at a time)
and rules tuned to this codebase's real failure classes: blocking calls
under locks, event-loop stalls, XLA recompile storms, metric-cardinality
blowups, lock-order inversions, and silent exception swallows.

Three layers:

* ``Finding`` / ``Checker`` / ``register`` — the rule surface. A checker
  sees one parsed module at a time (``check``) and may emit project-wide
  findings after every module has been visited (``finalize`` — used by the
  lock-order graph).
* suppression — ``# ray-tpu: lint-ignore[RTL001]`` on the finding line or
  the line above silences one line; ``# ray-tpu: lint-ignore-file[RTL003]``
  anywhere in a file silences the whole file. An empty rule list
  (``lint-ignore[]``) is invalid and ignored — directives always name rules.
* baseline — pre-existing, justified findings live in a committed JSON
  file keyed by (rule, path, scope, normalized source line) so they stay
  matched across unrelated line drift. The tier-1 gate asserts zero
  non-baselined findings AND that every baseline entry still matches (the
  baseline may only shrink; stale entries fail the gate).

Exit-code contract (see ``cli.py``): 0 clean, 1 findings, 2 usage/config
error.
"""
from __future__ import annotations

import ast
import fnmatch
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# Findings


@dataclass
class Finding:
    rule: str
    message: str
    path: str  # repo-relative, posix separators
    line: int
    col: int = 0
    scope: str = ""  # dotted class/function scope, "" at module level
    snippet: str = ""  # stripped source line — part of the stable identity

    @property
    def key(self) -> Tuple[str, str, str, str]:
        """Line-drift-stable identity used for baseline matching."""
        return (self.rule, self.path, self.scope, self.snippet)

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha1("|".join(self.key).encode()).hexdigest()
        return h[:12]

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "message": self.message,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "scope": self.scope,
            "snippet": self.snippet,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col + 1}"
        scope = f" [{self.scope}]" if self.scope else ""
        return f"{loc}: {self.rule} {self.message}{scope}"


# ---------------------------------------------------------------------------
# Module / project context handed to checkers


class ModuleContext:
    """One parsed module plus the shared helpers every rule needs."""

    def __init__(self, path: str, relpath: str, source: str, tree: ast.Module):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.module_name = _module_name(relpath)
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def scope_of(self, node: ast.AST) -> str:
        """Dotted class/function scope containing ``node``."""
        parts: List[str] = []
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                parts.append(anc.name)
        return ".".join(reversed(parts))

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for anc in self.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a class nested further out is not *this* node's class
                continue
        return None

    def snippet_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule,
            message=message,
            path=self.relpath,
            line=line,
            col=getattr(node, "col_offset", 0),
            scope=self.scope_of(node),
            snippet=self.snippet_at(line),
        )


def _module_name(relpath: str) -> str:
    name = relpath[:-3] if relpath.endswith(".py") else relpath
    name = name.replace("/", ".").replace("\\", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


# ---------------------------------------------------------------------------
# Checker registry


class Checker:
    rule: str = ""
    name: str = ""
    description: str = ""

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        """Project-wide findings after every module was visited."""
        return ()


_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    assert cls.rule, f"checker {cls.__name__} has no rule id"
    _REGISTRY[cls.rule] = cls
    return cls


def all_rules() -> Dict[str, type]:
    # rules.py self-registers on import
    from ray_tpu.tools.lint import rules  # noqa: F401

    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Suppression directives

_IGNORE_RE = re.compile(r"ray-tpu:\s*lint-ignore\[([A-Za-z0-9_,\s]+)\]")
_IGNORE_FILE_RE = re.compile(r"ray-tpu:\s*lint-ignore-file\[([A-Za-z0-9_,\s]+)\]")


@dataclass
class Suppressions:
    by_line: Dict[int, Set[str]] = field(default_factory=dict)
    file_rules: Set[str] = field(default_factory=set)

    def covers(self, finding: Finding) -> bool:
        if finding.rule in self.file_rules:
            return True
        for line in (finding.line, finding.line - 1):
            rules = self.by_line.get(line)
            if rules and finding.rule in rules:
                return True
        return False


def scan_suppressions(source: str) -> Suppressions:
    """Collect directives from real comment tokens (never from strings)."""
    sup = Suppressions()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _IGNORE_FILE_RE.search(tok.string)
            if m:
                sup.file_rules.update(_parse_rule_list(m.group(1)))
                continue
            m = _IGNORE_RE.search(tok.string)
            if m:
                line = tok.start[0]
                sup.by_line.setdefault(line, set()).update(
                    _parse_rule_list(m.group(1))
                )
    except tokenize.TokenError:
        pass
    return sup


def _parse_rule_list(raw: str) -> Set[str]:
    return {r.strip().upper() for r in raw.split(",") if r.strip()}


# ---------------------------------------------------------------------------
# Baseline


@dataclass
class Baseline:
    path: str
    entries: List[dict] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path, entries=[])
        with open(path) as f:
            data = json.load(f)
        return cls(path=path, entries=list(data.get("findings", [])))

    def save(self):
        with open(self.path, "w") as f:
            json.dump(
                {"version": 1, "findings": self.entries}, f, indent=2, sort_keys=False
            )
            f.write("\n")

    @staticmethod
    def entry_key(entry: dict) -> Tuple[str, str, str, str]:
        return (
            entry.get("rule", ""),
            entry.get("path", ""),
            entry.get("scope", ""),
            entry.get("snippet", ""),
        )

    def match(self, findings: Sequence[Finding], checked_paths: Optional[Set[str]] = None):
        """Split findings into (new, matched); also return stale entries.

        A baseline entry may match several findings with the same identity
        (e.g. two identical swallows in one function) — identity matching is
        by key, not 1:1 position. Staleness is only judged for entries whose
        file was actually checked this run: a path-scoped `ray-tpu lint
        some/subdir` must not flag out-of-scope entries as stale.
        """
        keys = {self.entry_key(e) for e in self.entries}
        new: List[Finding] = []
        matched: List[Finding] = []
        seen_keys: Set[Tuple[str, str, str, str]] = set()
        for f in findings:
            if f.key in keys:
                matched.append(f)
                seen_keys.add(f.key)
            else:
                new.append(f)
        stale = [
            e
            for e in self.entries
            if self.entry_key(e) not in seen_keys
            and (checked_paths is None or e.get("path", "") in checked_paths)
        ]
        return new, matched, stale


def baseline_entry(finding: Finding, justification: str) -> dict:
    return {
        "rule": finding.rule,
        "path": finding.path,
        "scope": finding.scope,
        "snippet": finding.snippet,
        "line": finding.line,  # informational only — not part of identity
        "justification": justification,
    }


# ---------------------------------------------------------------------------
# Config ([tool.ray-tpu-lint] in pyproject.toml)


@dataclass
class LintConfig:
    paths: List[str] = field(default_factory=lambda: ["ray_tpu"])
    enable: List[str] = field(default_factory=list)  # empty = all registered
    disable: List[str] = field(default_factory=list)
    exclude: List[str] = field(default_factory=lambda: ["*/__pycache__/*"])
    baseline: str = ".lint-baseline.json"
    root: str = "."

    def rule_ids(self) -> List[str]:
        rules = all_rules()
        ids = self.enable or sorted(rules)
        return [r for r in ids if r in rules and r not in set(self.disable)]


def load_config(root: str) -> LintConfig:
    cfg = LintConfig(root=root)
    pyproject = os.path.join(root, "pyproject.toml")
    if not os.path.exists(pyproject):
        return cfg
    with open(pyproject) as f:
        text = f.read()
    section = _toml_section(text, "tool.ray-tpu-lint")
    if not section:
        return cfg
    for key, value in section.items():
        if key == "paths" and isinstance(value, list):
            cfg.paths = value
        elif key == "enable" and isinstance(value, list):
            cfg.enable = [v.upper() for v in value]
        elif key == "disable" and isinstance(value, list):
            cfg.disable = [v.upper() for v in value]
        elif key == "exclude" and isinstance(value, list):
            cfg.exclude = value
        elif key == "baseline" and isinstance(value, str):
            cfg.baseline = value
    return cfg


def _toml_section(text: str, name: str) -> Dict[str, object]:
    """Minimal TOML-subset reader for our own config section.

    py3.10 has no tomllib and the container must not grow dependencies, so
    parse just what we emit: string / bool / int scalars and single-line or
    multi-line arrays of strings.
    """
    out: Dict[str, object] = {}
    lines = text.splitlines()
    in_section = False
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        i += 1
        if line.startswith("["):
            in_section = line == f"[{name}]"
            continue
        if not in_section or not line or line.startswith("#"):
            continue
        if "=" not in line:
            continue
        key, _, raw = line.partition("=")
        key = key.strip()
        raw = raw.strip()
        if raw.startswith("[") and not raw.rstrip().rstrip(",").endswith("]"):
            # multi-line array: accumulate until the closing bracket
            while i < len(lines) and "]" not in raw:
                raw += " " + lines[i].strip()
                i += 1
        out[key] = _toml_value(raw)
    return out


def _toml_value(raw: str):
    raw = raw.split("#", 1)[0].strip() if not raw.startswith(('"', "'")) else raw
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1]
        items = [s.strip() for s in inner.split(",")]
        return [_strip_quotes(s) for s in items if s]
    if raw in ("true", "false"):
        return raw == "true"
    if re.fullmatch(r"-?\d+", raw):
        return int(raw)
    return _strip_quotes(raw)


def _strip_quotes(s: str) -> str:
    s = s.strip()
    if len(s) >= 2 and s[0] == s[-1] and s[0] in "\"'":
        return s[1:-1]
    return s


# ---------------------------------------------------------------------------
# Runner


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)  # non-baselined
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[dict] = field(default_factory=list)
    suppressed: int = 0
    files_checked: int = 0
    checked_paths: List[str] = field(default_factory=list)  # relpaths seen
    parse_errors: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        # a file we could not parse was not checked — that is not clean
        return not self.findings and not self.stale_baseline and not self.parse_errors

    def to_json(self) -> dict:
        return {
            "version": 1,
            "clean": self.clean,
            "files_checked": self.files_checked,
            "suppressed": self.suppressed,
            "findings": [f.to_json() for f in self.findings],
            "baselined": [f.to_json() for f in self.baselined],
            "stale_baseline": self.stale_baseline,
            "parse_errors": self.parse_errors,
        }


def iter_python_files(paths: Sequence[str], root: str, exclude: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            out.append(full)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    rels = []
    for f in sorted(set(out)):
        rel = os.path.relpath(f, root).replace(os.sep, "/")
        if any(fnmatch.fnmatch(rel, pat) or fnmatch.fnmatch("/" + rel, pat) for pat in exclude):
            continue
        rels.append(f)
    return rels


def run_lint(
    paths: Optional[Sequence[str]] = None,
    root: Optional[str] = None,
    config: Optional[LintConfig] = None,
    use_baseline: bool = True,
) -> LintResult:
    root = os.path.abspath(root or _find_root())
    config = config or load_config(root)
    rule_ids = config.rule_ids()
    rules = all_rules()
    checkers: List[Checker] = [rules[r]() for r in rule_ids]

    result = LintResult()
    raw: List[Finding] = []
    files = iter_python_files(paths or config.paths, root, config.exclude)
    for path in files:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError) as e:
            result.parse_errors.append(f"{rel}: {e}")
            continue
        result.files_checked += 1
        result.checked_paths.append(rel)
        ctx = ModuleContext(path, rel, source, tree)
        sup = scan_suppressions(source)
        for checker in checkers:
            for finding in checker.check(ctx):
                if sup.covers(finding):
                    result.suppressed += 1
                else:
                    raw.append(finding)
    # project-wide rules (lock-order graph): suppression re-checked against
    # the file each finding lands in.
    sup_cache: Dict[str, Suppressions] = {}
    for checker in checkers:
        for finding in checker.finalize():
            sup = sup_cache.get(finding.path)
            if sup is None:
                try:
                    with open(os.path.join(root, finding.path), encoding="utf-8") as f:
                        sup = scan_suppressions(f.read())
                except OSError:
                    sup = Suppressions()
                sup_cache[finding.path] = sup
            if sup.covers(finding):
                result.suppressed += 1
            else:
                raw.append(finding)

    raw.sort(key=lambda f: (f.path, f.line, f.rule))
    if use_baseline:
        baseline = Baseline.load(os.path.join(root, config.baseline))
        new, matched, stale = baseline.match(raw, set(result.checked_paths))
        result.findings = new
        result.baselined = matched
        result.stale_baseline = stale
    else:
        result.findings = raw
    return result


def _find_root(start: Optional[str] = None) -> str:
    """Walk up from ``start`` to the directory holding pyproject.toml."""
    cur = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.exists(os.path.join(cur, "pyproject.toml")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start or os.getcwd())
        cur = parent
