"""ray_tpu lint: project-aware static analysis.

Public surface:

* :func:`ray_tpu.tools.lint.framework.run_lint` — programmatic runner
  (used by the tier-1 gate in ``tests/test_lint_clean.py``).
* ``ray-tpu lint`` / ``python -m ray_tpu.tools.lint.cli`` — the CLI.
* Rules RTL001–RTL006 live in :mod:`ray_tpu.tools.lint.rules` and
  self-register on import.
"""
from ray_tpu.tools.lint.framework import (  # noqa: F401
    Baseline,
    Checker,
    Finding,
    LintConfig,
    LintResult,
    all_rules,
    load_config,
    run_lint,
)
