"""ray_tpu lint rules RTL001–RTL008.

Each rule targets a failure class this codebase has actually hit (or that
Ray itself accumulates at scale):

* RTL001 blocking-call-under-lock — a blocking operation (``time.sleep``,
  socket ops, ``Future.result()``, the sync RPC surface ``._call(...)`` /
  ``loop_runner.run(...)``, subprocess) inside a ``with <lock>:`` body or
  between ``.acquire()``/``.release()``. Every waiter on that lock stalls
  for the full duration; under the GIL-released RPC wait this is the
  classic source of cluster-wide convoy effects.
* RTL002 blocking-call-in-async — the same blocking set inside
  ``async def``. One blocked coroutine stalls the whole event loop: every
  RPC peer sharing it times out (the py3.10 ``_maybe_async`` generator bug
  fixed in PR 1 lived one street over from this class).
* RTL003 jit-recompile-hazard — (a) ``jax.jit``/``pjit`` wrapper
  construction inside a loop body (a fresh wrapper = a fresh compile cache
  = one XLA compile per iteration) and (b) calls to jit-decorated
  functions (no ``static_argnums``/``static_argnames``) passing
  shape-derived Python ints (``len(...)``, ``.shape``) or ``range()`` loop
  variables positionally — each distinct value retraces. Static sibling of
  ``util/compile_tracker.py``'s runtime storm detector.
* RTL004 unbounded-metric-tags — Counter/Gauge/Histogram record calls
  whose tag values derive from request/object/task IDs or loop variables.
  Every distinct value mints a new series; the runtime cardinality cap
  (PR 3) drops the overflow silently, so the data just vanishes.
* RTL005 lock-order — builds the project-wide lock-acquisition graph from
  nested ``with`` statements (lock identities canonicalized through import
  aliases so cross-module edges meet) and flags A→B / B→A inversions —
  the static sibling of ``util/lockwatch.py``'s runtime watchdog.
* RTL006 silent-exception-swallow — bare ``except:`` anywhere, and
  ``except Exception/BaseException: pass`` bodies. Swallows on control
  paths turn hard failures into hangs; convert to logged warnings or
  narrow the type.
* RTL007 print-in-package — bare ``print()`` inside library code
  (CLI/tools modules exempt). Cluster-process output belongs on a
  logger so the structured log plane (core/log_plane.py) can stamp it
  with severity + task attribution; a print is invisible to
  ``ray-tpu logs --err`` and the error index.
* RTL008 unbounded-wait — a thread-blocking wait with no bound:
  zero-argument ``.result()`` / ``.get()`` / ``.join()`` / ``.wait()``
  (``Future.result`` / ``Queue.get`` / ``Thread.join`` / ``Event.wait``
  all default to forever), and the explicit ``timeout=None`` opt-out on
  the RPC surface (``call`` / ``_call``, whose bare default is the
  bounded ``control_call_timeout_s``). A wedged peer turns every such
  wait into a silent hang the failure detector can't see past — the
  static sibling of the elastic-train detect path. Waits that are
  unbounded BY DESIGN (writer-loop queue pops, workload-duration data
  waits, serve-forever parks) carry a suppression naming the reason.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.tools.lint.framework import Checker, Finding, ModuleContext, register

# ---------------------------------------------------------------------------
# Shared AST helpers


def dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` -> "a.b.c"; calls/subscripts terminate the chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


_LOCK_NAME_RE = re.compile(r"(^|_)(lock|mutex|rlock)s?$", re.IGNORECASE)


def is_lock_expr(node: ast.AST) -> bool:
    """Heuristic: the context-manager expression names a lock.

    Matches ``self._lock``, ``_registry_lock``, ``cls._LOCK``, and
    ``self._locks[key]``; deliberately does NOT match conditions or
    semaphores (waiting on a Condition while holding its lock is the
    correct protocol, not a finding).
    """
    if isinstance(node, ast.Subscript):
        return is_lock_expr(node.value)
    if isinstance(node, ast.Call):  # e.g. self._lock_for(key)
        return is_lock_expr(node.func)
    d = dotted(node)
    if not d:
        return False
    terminal = d.rsplit(".", 1)[-1]
    return bool(_LOCK_NAME_RE.search(terminal))


def lock_text(node: ast.AST) -> str:
    """Source-ish text of a lock expression, for messages and graph keys."""
    if isinstance(node, ast.Subscript):
        return lock_text(node.value) + "[...]"
    if isinstance(node, ast.Call):
        return lock_text(node.func) + "(...)"
    return dotted(node) or "<lock>"


_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep()",
    "socket.create_connection": "socket.create_connection()",
    "subprocess.run": "subprocess.run()",
    "subprocess.call": "subprocess.call()",
    "subprocess.check_call": "subprocess.check_call()",
    "subprocess.check_output": "subprocess.check_output()",
    "subprocess.Popen": "subprocess.Popen()",
    "urllib.request.urlopen": "urlopen()",
    "requests.get": "requests.get()",
    "requests.post": "requests.post()",
    "requests.request": "requests.request()",
}

# method names that block regardless of receiver (project RPC surface
# included: Client._call is the sync controller RPC, loop_runner.run pumps
# a coroutine to completion on the IO thread)
_BLOCKING_ATTRS = {
    "result": "Future.result()",
    "_call": "sync RPC ._call()",
    "accept": "socket.accept()",
    "connect": "socket.connect()",
    "recv": "socket.recv()",
    "recv_into": "socket.recv_into()",
    "sendall": "socket.sendall()",
}


def blocking_call(node: ast.Call, ctx: Optional[ModuleContext] = None) -> Optional[str]:
    """Return a human label if ``node`` is a known blocking call.

    An awaited call is never blocking (``await rpc.connect(...)`` yields to
    the loop — only the sync socket/RPC surfaces block the thread).
    """
    if ctx is not None and isinstance(ctx.parent(node), ast.Await):
        return None
    d = dotted(node.func)
    if d:
        if d in _BLOCKING_DOTTED:
            return _BLOCKING_DOTTED[d]
        terminal = d.rsplit(".", 1)[-1]
        if terminal in _BLOCKING_ATTRS:
            return _BLOCKING_ATTRS[terminal]
        # loop_runner.run(coro, timeout): the sync bridge into the IO loop
        if terminal == "run" and "runner" in d.lower():
            return "loop_runner.run()"
        # thread/process join — NOT str.join (receiver must look threadish)
        if terminal == "join":
            recv = d.rsplit(".", 1)[0].lower()
            if any(w in recv for w in ("thread", "proc", "worker", "flusher")):
                return f"{d}()"
    return None


def iter_calls_shallow(nodes: Iterable[ast.stmt]) -> Iterable[ast.Call]:
    """Walk statements but do not descend into nested function/class
    definitions or lambdas — their bodies run later, outside this scope."""
    stack: List[ast.AST] = list(nodes)
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """alias -> fully-qualified module (or module attribute) name."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


# ---------------------------------------------------------------------------
# RTL001 — blocking call under a held lock


@register
class BlockingUnderLock(Checker):
    rule = "RTL001"
    name = "blocking-call-under-lock"
    description = "blocking operation while holding a lock"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                locks = [
                    lock_text(item.context_expr)
                    for item in node.items
                    if is_lock_expr(item.context_expr)
                ]
                if not locks:
                    continue
                for call in self._calls_excluding_inner_locks(node.body):
                    label = blocking_call(call, ctx)
                    if label:
                        findings.append(
                            ctx.finding(
                                self.rule,
                                call,
                                f"{label} inside `with {locks[0]}:` — blocking "
                                "while holding a lock stalls every waiter",
                            )
                        )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._acquire_spans(ctx, node))
        return findings

    @staticmethod
    def _calls_excluding_inner_locks(body: List[ast.stmt]) -> Iterable[ast.Call]:
        """Like iter_calls_shallow, but stops at nested lock-holding
        `with` blocks — ast.walk visits those separately, and one blocking
        call must yield ONE finding (attributed to its innermost lock),
        not one per enclosing lock."""
        stack: List[ast.AST] = list(body)
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
            ):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)) and any(
                is_lock_expr(item.context_expr) for item in node.items
            ):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    def _acquire_spans(self, ctx: ModuleContext, fn: ast.AST) -> Iterable[Finding]:
        """Flag blocking calls between explicit .acquire() and .release()
        at one statement-sequence level (straight-line approximation)."""
        findings: List[Finding] = []
        held: List[str] = []
        for stmt in getattr(fn, "body", ()):
            acq = self._lock_method(stmt, "acquire")
            rel = self._lock_method(stmt, "release")
            if acq:
                held.append(acq)
                continue
            if rel and rel in held:
                held.remove(rel)
                continue
            if held:
                for call in iter_calls_shallow([stmt]):
                    label = blocking_call(call, ctx)
                    if label:
                        findings.append(
                            ctx.finding(
                                self.rule,
                                call,
                                f"{label} between {held[-1]}.acquire() and "
                                ".release()",
                            )
                        )
        return findings

    @staticmethod
    def _lock_method(stmt: ast.stmt, method: str) -> Optional[str]:
        if not isinstance(stmt, ast.Expr) or not isinstance(stmt.value, ast.Call):
            return None
        func = stmt.value.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == method
            and is_lock_expr(func.value)
        ):
            return lock_text(func.value)
        return None


# ---------------------------------------------------------------------------
# RTL002 — blocking call in async def


@register
class BlockingInAsync(Checker):
    rule = "RTL002"
    name = "blocking-call-in-async"
    description = "blocking operation inside async def stalls the event loop"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for call in iter_calls_shallow(node.body):
                label = blocking_call(call, ctx)
                if not label:
                    continue
                findings.append(
                    ctx.finding(
                        self.rule,
                        call,
                        f"{label} inside `async def {node.name}` — blocks the "
                        "event loop; use await/asyncio.sleep or an executor",
                    )
                )
        return findings


# ---------------------------------------------------------------------------
# RTL003 — XLA recompile hazards


_JIT_NAMES = {"jit", "pjit", "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}


def _is_jit_func(node: ast.AST) -> bool:
    d = dotted(node)
    return d in _JIT_NAMES if d else False


def _shapeish_arg(arg: ast.AST) -> Optional[str]:
    """Positional args whose distinct values force retraces."""
    if isinstance(arg, ast.Call) and dotted(arg.func) == "len":
        return "len(...)"
    if isinstance(arg, ast.Attribute) and arg.attr in ("shape", "ndim", "size"):
        return f".{arg.attr}"
    if (
        isinstance(arg, ast.Subscript)
        and isinstance(arg.value, ast.Attribute)
        and arg.value.attr == "shape"
    ):
        return ".shape[...]"
    return None


@register
class JitRecompileHazard(Checker):
    rule = "RTL003"
    name = "jit-recompile-hazard"
    description = "pattern that forces repeated XLA compilation"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        findings.extend(self._jit_in_loop(ctx))
        findings.extend(self._scalar_callsites(ctx))
        return findings

    def _jit_in_loop(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for call in iter_calls_shallow(loop.body + loop.orelse):
                if _is_jit_func(call.func):
                    out.append(
                        ctx.finding(
                            self.rule,
                            call,
                            "jit wrapper constructed inside a loop — each "
                            "iteration gets a fresh compile cache (recompile "
                            "storm); hoist the jit() out of the loop",
                        )
                    )
        return out

    def _scalar_callsites(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        # jit-decorated functions in this module without static argument
        # declarations
        hazard_fns: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                if _is_jit_func(dec):
                    hazard_fns.add(node.name)
                elif isinstance(dec, ast.Call) and _is_jit_func(dec.func):
                    kw = {k.arg for k in dec.keywords}
                    if not kw & {"static_argnums", "static_argnames"}:
                        hazard_fns.add(node.name)
        if not hazard_fns:
            return out
        # range()-loop index variables per enclosing loop
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            fname = call.func.id if isinstance(call.func, ast.Name) else None
            if fname not in hazard_fns:
                continue
            range_vars = self._enclosing_range_vars(ctx, call)
            for arg in call.args:
                why = _shapeish_arg(arg)
                if why is None and isinstance(arg, ast.Name) and arg.id in range_vars:
                    why = f"range() loop variable `{arg.id}`"
                if why:
                    out.append(
                        ctx.finding(
                            self.rule,
                            call,
                            f"`{fname}` is jit-compiled without static_argnums/"
                            f"static_argnames but is called with {why} "
                            "positionally — every distinct value retraces",
                        )
                    )
        return out

    @staticmethod
    def _enclosing_range_vars(ctx: ModuleContext, node: ast.AST) -> Set[str]:
        vars_: Set[str] = set()
        for anc in ctx.ancestors(node):
            if (
                isinstance(anc, ast.For)
                and isinstance(anc.target, ast.Name)
                and isinstance(anc.iter, ast.Call)
                and dotted(anc.iter.func) == "range"
            ):
                vars_.add(anc.target.id)
        return vars_


# ---------------------------------------------------------------------------
# RTL004 — unbounded metric tag values


_ID_NAME_RE = re.compile(
    r"(^|_)(request|req|task|object|obj|job|actor|session|trace|span|replica)_?id$|^rid$|^oid$|^tid$",
    re.IGNORECASE,
)

_RECORD_METHODS = {"inc", "observe"}  # value [, tags]
_RECORD_METHODS_SET = {"set"}  # Gauge.set(value [, tags])


def _id_like(node: ast.AST, loop_vars: Set[str]) -> Optional[str]:
    """Does this tag-value expression derive from an unbounded id?"""
    if isinstance(node, ast.Name):
        if _ID_NAME_RE.search(node.id):
            return f"`{node.id}`"
        if node.id in loop_vars:
            return f"loop variable `{node.id}`"
    if isinstance(node, ast.Attribute) and _ID_NAME_RE.search(node.attr):
        return f"`.{node.attr}`"
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        if d in ("str", "repr", "hex") and node.args:
            return _id_like(node.args[0], loop_vars)
    if isinstance(node, ast.JoinedStr):
        for part in node.values:
            if isinstance(part, ast.FormattedValue):
                hit = _id_like(part.value, loop_vars)
                if hit:
                    return hit
    if isinstance(node, ast.Subscript):
        return _id_like(node.value, loop_vars)
    return None


@register
class UnboundedMetricTags(Checker):
    rule = "RTL004"
    name = "unbounded-metric-tags"
    description = "metric tag value derived from an unbounded id"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            if not isinstance(call.func, ast.Attribute):
                continue
            method = call.func.attr
            if method not in _RECORD_METHODS | _RECORD_METHODS_SET:
                continue
            tags = self._tags_arg(call)
            if not isinstance(tags, ast.Dict):
                continue
            loop_vars = self._loop_vars(ctx, call)
            for key_node, val_node in zip(tags.keys, tags.values):
                hit = _id_like(val_node, loop_vars)
                if not hit:
                    continue
                key_repr = (
                    key_node.value
                    if isinstance(key_node, ast.Constant)
                    else "<dynamic>"
                )
                findings.append(
                    ctx.finding(
                        self.rule,
                        val_node,
                        f"metric tag {key_repr!r} set from {hit} — every "
                        "distinct value mints a new series; the runtime cap "
                        "will silently drop the overflow. Aggregate or drop "
                        "the tag",
                    )
                )
        return findings

    @staticmethod
    def _tags_arg(call: ast.Call) -> Optional[ast.AST]:
        for kw in call.keywords:
            if kw.arg == "tags":
                return kw.value
        # positional: inc(value, tags) / set(value, tags) / observe(value, tags)
        if len(call.args) >= 2:
            return call.args[1]
        return None

    @staticmethod
    def _loop_vars(ctx: ModuleContext, node: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.For):
                for t in ast.walk(anc.target):
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out


# ---------------------------------------------------------------------------
# RTL005 — lock-order inversions (project-wide graph)


@register
class LockOrder(Checker):
    rule = "RTL005"
    name = "lock-order"
    description = "conflicting lock-acquisition order across the project"

    def __init__(self):
        # (outer_key, inner_key) -> list of (path, line, scope, snippet)
        self.edges: Dict[Tuple[str, str], List[Tuple[str, int, str, str]]] = {}

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        aliases = import_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            outer_locks = [
                self._canon(ctx, aliases, item.context_expr, node)
                for item in node.items
                if is_lock_expr(item.context_expr)
            ]
            if not outer_locks:
                continue
            for inner in self._inner_withs(node.body):
                for item in inner.items:
                    if not is_lock_expr(item.context_expr):
                        continue
                    inner_key = self._canon(ctx, aliases, item.context_expr, inner)
                    for outer_key in outer_locks:
                        if outer_key == inner_key:
                            continue  # reacquisition; RLock-or-bug, not order
                        site = (
                            ctx.relpath,
                            inner.lineno,
                            ctx.scope_of(inner),
                            ctx.snippet_at(inner.lineno),
                        )
                        self.edges.setdefault((outer_key, inner_key), []).append(site)
        return ()

    @staticmethod
    def _inner_withs(body: List[ast.stmt]) -> Iterable[ast.With]:
        stack: List[ast.AST] = list(body)
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
            ):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    def _canon(self, ctx: ModuleContext, aliases: Dict[str, str], expr: ast.AST,
               site: ast.AST) -> str:
        """Canonical lock identity: `self._lock` -> module.Class._lock,
        bare `_lock` -> module._lock, `metrics._lock` resolved through the
        import table so cross-module references meet at one node."""
        text = lock_text(expr)
        parts = text.split(".")
        if parts[0] == "self" or parts[0] == "cls":
            cls = ctx.enclosing_class(site)
            owner = f"{ctx.module_name}.{cls.name}" if cls else ctx.module_name
            return ".".join([owner] + parts[1:])
        if parts[0] in aliases:
            return ".".join([aliases[parts[0]]] + parts[1:])
        return f"{ctx.module_name}.{text}"

    def finalize(self) -> Iterable[Finding]:
        findings: List[Finding] = []
        reported: Set[Tuple[str, str]] = set()
        for (a, b), sites in sorted(self.edges.items()):
            if (b, a) not in self.edges or (b, a) in reported:
                continue
            reported.add((a, b))
            other = self.edges[(b, a)][0]
            for path, line, scope, snippet in sites:
                findings.append(
                    Finding(
                        rule=self.rule,
                        message=(
                            f"lock-order inversion: {a} → {b} here, but "
                            f"{b} → {a} at {other[0]}:{other[1]} — concurrent "
                            "callers can deadlock"
                        ),
                        path=path,
                        line=line,
                        scope=scope,
                        snippet=snippet,
                    )
                )
        return findings


# ---------------------------------------------------------------------------
# RTL006 — silent exception swallows


_CLEANUP_FN_RE = re.compile(
    r"(shutdown|teardown|close|stop|kill|__del__|disconnect|cleanup|drain)",
    re.IGNORECASE,
)


@register
class SilentSwallow(Checker):
    rule = "RTL006"
    name = "silent-exception-swallow"
    description = "bare except or except Exception: pass hides failures"

    _WIDE = {"Exception", "BaseException"}

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                findings.append(
                    ctx.finding(
                        self.rule,
                        node,
                        "bare `except:` also catches KeyboardInterrupt/"
                        "SystemExit — name the exception type",
                    )
                )
                continue
            type_name = dotted(node.type)
            if type_name in self._WIDE and self._is_silent(node.body):
                # Project convention: best-effort cleanup (shutdown/close/
                # __del__/teardown/kill/drain paths) legitimately swallows —
                # the resource is going away and there is nobody to tell.
                # Control paths (everything else) must log or narrow.
                scope = ctx.scope_of(node)
                innermost = scope.rsplit(".", 1)[-1] if scope else ""
                if _CLEANUP_FN_RE.search(innermost):
                    continue
                findings.append(
                    ctx.finding(
                        self.rule,
                        node,
                        f"`except {type_name}: pass` silently swallows "
                        "failures on this path — log it or narrow the type",
                    )
                )
        return findings

    @staticmethod
    def _is_silent(body: List[ast.stmt]) -> bool:
        for stmt in body:
            if isinstance(stmt, ast.Pass):
                continue
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
                continue  # docstring/ellipsis only
            return False
        return True


# ---------------------------------------------------------------------------
# RTL008 — unbounded waits


@register
class UnboundedWait(Checker):
    rule = "RTL008"
    name = "unbounded-wait"
    description = (
        "blocking wait with no timeout — zero-arg result()/get()/join()/"
        "wait(), or an RPC call explicitly opting out with timeout=None"
    )

    # CLI surfaces (scripts/, tools/) legitimately block for as long as
    # the user's command runs.
    _EXEMPT_SEGMENTS = ("scripts", "tools")
    _WAIT_METHODS = {
        "result": "Future.result()",
        "get": "Queue.get()",
        "join": "Thread.join()",
        "wait": "Event.wait()",
    }
    # The project's sync RPC surface: Connection._call applies the bounded
    # control_call_timeout_s default, so bare calls are fine — only an
    # EXPLICIT timeout=None opts back into waiting forever.
    _RPC_NAMES = {"call", "_call"}

    def __init__(self):
        # "module.name" of every ContextVar assignment seen project-wide;
        # zero-arg .get() on one of these is an instant read, not a wait.
        # Resolution is deferred to finalize() because the ContextVar may
        # be defined in a module visited AFTER its importer.
        self._ctxvars: Set[str] = set()
        self._deferred: List[Tuple[Finding, Optional[str]]] = []

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        parts = ctx.relpath.replace("\\", "/").split("/")
        exempt = any(seg in parts[:-1] for seg in self._EXEMPT_SEGMENTS)
        aliases = import_aliases(ctx.tree)
        self._collect_contextvars(ctx)
        if exempt:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (
                fn.attr
                if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else ""
            )
            if (
                isinstance(fn, ast.Attribute)
                and name in self._WAIT_METHODS
                and not node.args
                and not node.keywords
                and not self._bounded_context(ctx, node)
            ):
                recv = dotted(fn.value) or ""
                if recv in ("self", "cls"):
                    # A method calling its own result()/get()/... — that is
                    # an ordinary method dispatch, not a stdlib wait.
                    continue
                finding = ctx.finding(
                    self.rule,
                    node,
                    f"`{recv or '<expr>'}.{name}()` "
                    f"({self._WAIT_METHODS[name]} semantics) waits forever "
                    "— pass a timeout, or suppress with the reason this "
                    "wait is unbounded by design",
                )
                if name == "get":
                    qual = None
                    if isinstance(fn.value, ast.Name):
                        qual = aliases.get(
                            fn.value.id, f"{ctx.module_name}.{fn.value.id}"
                        )
                    self._deferred.append((finding, qual))
                else:
                    findings.append(finding)
                continue
            if name in self._RPC_NAMES:
                for kw in node.keywords:
                    if (
                        kw.arg == "timeout"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is None
                    ):
                        findings.append(
                            ctx.finding(
                                self.rule,
                                node,
                                "explicit timeout=None opts this RPC out of "
                                "the bounded control-call default — give it "
                                "a real bound or suppress with justification",
                            )
                        )
        return findings

    def _collect_contextvars(self, ctx: ModuleContext):
        for node in ast.walk(ctx.tree):
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign):
                target = node.target
            else:
                continue
            value = getattr(node, "value", None)
            if not isinstance(value, ast.Call) or not isinstance(target, ast.Name):
                continue
            d = dotted(value.func) or ""
            if d.rsplit(".", 1)[-1] == "ContextVar":
                self._ctxvars.add(f"{ctx.module_name}.{target.id}")

    @staticmethod
    def _bounded_context(ctx: ModuleContext, node: ast.Call) -> bool:
        parent = ctx.parent(node)
        if isinstance(parent, ast.Await):
            # Awaited waits are cancellable from the loop and boundable by
            # the caller's asyncio.wait_for — not thread-blocking.
            return True
        if isinstance(parent, ast.Call):
            d = dotted(parent.func) or ""
            if d.rsplit(".", 1)[-1] == "wait_for":
                return True  # asyncio.wait_for(x.wait(), timeout=...)
        return False

    def finalize(self) -> Iterable[Finding]:
        return [
            finding
            for finding, qual in self._deferred
            if qual is None or qual not in self._ctxvars
        ]


# ---------------------------------------------------------------------------
# RTL007 — bare print() in package code (registration order is by rule id
# in the CLI listing; definition order here is immaterial)


@register
class PrintInPackage(Checker):
    rule = "RTL007"
    name = "print-in-package"
    description = (
        "bare print() in library code bypasses the structured log plane"
    )

    # CLI surfaces legitimately print to the user's console: the
    # ``ray-tpu`` entrypoints (scripts/) and the lint tool itself
    # (tools/). Everything else in the package runs inside cluster
    # processes whose output should carry severity + task attribution
    # through the log plane (core/log_plane.py) — a logger call does,
    # a bare print() does not.
    _EXEMPT_SEGMENTS = ("scripts", "tools")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        parts = ctx.relpath.replace("\\", "/").split("/")
        if any(seg in parts[:-1] for seg in self._EXEMPT_SEGMENTS):
            return ()
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                findings.append(
                    ctx.finding(
                        self.rule,
                        node,
                        "bare print() in package code — route through a "
                        "logger (captured + attributed by the log plane) "
                        "or add a lint-ignore with justification",
                    )
                )
        return findings


# RTL009–RTL011 (the ConcSan guard-annotation rules) live in their own
# module; importing it here self-registers them alongside RTL001–RTL008.
from ray_tpu.tools.lint import guard_rules  # noqa: E402,F401
