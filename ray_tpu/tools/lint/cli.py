"""``ray-tpu lint`` — run the project-aware static analyzer.

Exit-code contract (stable for CI):
  0  clean: no non-baselined findings, no stale baseline entries
  1  findings (or stale baseline entries — the baseline may only shrink)
  2  usage or configuration error

``--format json`` emits a single machine-readable document on stdout for
CI annotation; text mode prints one `path:line:col: RULE message` line per
finding plus a summary.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ray_tpu.tools.lint.framework import (
    Baseline,
    all_rules,
    baseline_entry,
    load_config,
    run_lint,
    _find_root,
)


def add_lint_args(sp: argparse.ArgumentParser):
    sp.add_argument("paths", nargs="*", help="files/dirs (default: config paths)")
    sp.add_argument("--format", choices=["text", "json"], default="text")
    sp.add_argument("--root", default=None, help="project root (default: auto)")
    sp.add_argument(
        "--no-baseline", action="store_true", help="report baselined findings too"
    )
    sp.add_argument(
        "--write-baseline",
        action="store_true",
        help="re-baseline: write every current finding to the baseline file "
        "(justifications for existing entries are preserved)",
    )
    sp.add_argument("--rules", default=None, help="comma-separated rule subset")
    sp.add_argument(
        "--list-rules", action="store_true", help="print the rule table and exit"
    )


def cmd_lint(args) -> int:
    if args.list_rules:
        for rule_id, cls in sorted(all_rules().items()):
            print(f"{rule_id}  {cls.name:<28} {cls.description}")
        return 0
    root = os.path.abspath(args.root) if args.root else _find_root()
    try:
        config = load_config(root)
    except Exception as e:  # malformed pyproject section
        print(f"ray-tpu lint: bad config: {e}", file=sys.stderr)
        return 2
    if args.rules:
        wanted = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        unknown = set(wanted) - set(all_rules())
        if unknown:
            print(f"ray-tpu lint: unknown rules: {sorted(unknown)}", file=sys.stderr)
            return 2
        config.enable = wanted
        config.disable = []  # an explicit --rules request overrides config disables
    paths = args.paths or None
    if args.write_baseline:
        return _write_baseline(root, config, paths)
    result = run_lint(paths=paths, root=root, config=config,
                      use_baseline=not args.no_baseline)
    if result.files_checked == 0:
        # checking nothing is a config error, not a clean run — a CI job
        # with a wrong root/paths must not silently pass
        print(
            f"ray-tpu lint: no Python files found under {paths or config.paths} "
            f"(root {root})",
            file=sys.stderr,
        )
        return 2

    if args.format == "json":
        print(json.dumps(result.to_json(), indent=2))
        return 0 if result.clean else 1

    for f in result.findings:
        print(f.render())
    for entry in result.stale_baseline:
        print(
            f"{entry.get('path')}: stale baseline entry "
            f"{entry.get('rule')} [{entry.get('scope', '')}] — the finding is "
            "gone; remove it from the baseline (baseline may only shrink)"
        )
    n, b, s = len(result.findings), len(result.baselined), result.suppressed
    print(
        f"ray-tpu lint: {result.files_checked} files, {n} finding(s), "
        f"{b} baselined, {s} suppressed"
        + (f", {len(result.stale_baseline)} stale baseline entr(ies)" if result.stale_baseline else "")
    )
    if result.parse_errors:
        for err in result.parse_errors:
            print(f"parse error: {err}", file=sys.stderr)
    return 0 if result.clean else 1


def _write_baseline(root: str, config, paths: Optional[List[str]]) -> int:
    """Capture current findings as the new baseline, keeping existing
    justifications for entries that survive — and keeping entries for
    files OUTSIDE the scoped paths untouched (a path-scoped re-baseline
    must not erase the rest of the committed baseline)."""
    result = run_lint(paths=paths, root=root, config=config, use_baseline=False)
    if result.files_checked == 0:
        print(
            f"ray-tpu lint: refusing to write baseline — no Python files found "
            f"under {paths or config.paths} (root {root})",
            file=sys.stderr,
        )
        return 2
    bl_path = os.path.join(root, config.baseline)
    old = Baseline.load(bl_path)
    just = {Baseline.entry_key(e): e.get("justification", "") for e in old.entries}
    checked = set(result.checked_paths)
    entries = [e for e in old.entries if e.get("path", "") not in checked]
    kept = len(entries)
    seen = set()
    for f in result.findings:
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append(baseline_entry(f, just.get(f.key, "TODO: justify")))
    entries.sort(key=lambda e: (e.get("path", ""), e.get("line", 0), e.get("rule", "")))
    old.entries = entries
    old.save()
    print(
        f"wrote {len(entries)} baseline entr(ies) to {bl_path}"
        + (f" ({kept} out-of-scope kept)" if kept else "")
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray-tpu lint", description=__doc__)
    add_lint_args(p)
    return cmd_lint(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
