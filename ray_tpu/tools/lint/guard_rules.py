"""Lint rules RTL009–RTL011: the static side of ConcSan.

These check the ``@guarded_by`` / ``GuardedDict`` / ``GuardedSet``
annotation vocabulary (``ray_tpu/util/guards.py``) lexically:

* RTL009 unguarded-access — a read/write of a lock-guarded attribute
  that is not inside ``with self.<guard>:`` (without crossing a
  function boundary — a nested def's body runs later, on somebody
  else's stack), not in a ``@guarded_by("<guard>")`` method, not in
  ``__init__``/``__new__`` (construction is single-threaded by
  definition), and not a sanctioned atomic read (``snapshot()`` /
  ``cycle_snapshot()`` argument, ``len()``/``bool()``).
* RTL010 guard-inconsistency — the annotation itself is incoherent:
  the same attribute declared with two different guards, an access
  lexically under a *different* lock than the declared one (the
  classic wrong-lock bug TSan calls "mutex mismatch"), or a
  ``@guarded_by`` naming an attribute the class never assigns.
* RTL011 callback-touches-guarded-state — a nested function or lambda
  handed to a registrar (``subscribe``, ``add_done_callback``,
  ``add_callback``, ...) whose body touches a guard-annotated
  attribute directly. Callbacks run on whatever thread the registrar
  chooses — pubsub IO threads, executor completion threads — so the
  lexical guard context where the callback was *created* proves
  nothing about where it *runs*. OWNER_THREAD state is checked here
  too (its whole contract is "only the owner thread touches this").

Scope: self/cls attribute accesses within the declaring module. The
dynamic witness (``tools/sanitizer/runtime.py``) covers what the AST
cannot see — aliased references, cross-module access, real thread
identities.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.tools.lint.framework import Checker, Finding, ModuleContext, register
from ray_tpu.tools.lint.rules import dotted, lock_text, is_lock_expr

OWNER_THREAD = "@owner-thread"

_GUARD_CTORS = {"GuardedDict", "GuardedSet"}
# Sanctioned atomic single-op reads of a guarded container: one C-level
# operation under the GIL, no torn state observable.
_SNAPSHOT_FUNCS = {"snapshot", "cycle_snapshot"}
_ATOMIC_FUNCS = {"len", "bool"}
# Callback registrars whose callables run on another thread (or on a
# thread the AST cannot determine).
_REGISTRARS = {
    "subscribe",
    "add_done_callback",
    "add_callback",
    "add_listener",
    "register",
    "register_handler",
    "on_message",
    "call_soon_threadsafe",
    "Thread",  # target=... callables literally run on another thread
}


class _Decl:
    __slots__ = ("guard", "node", "cls_name")

    def __init__(self, guard: str, node: ast.AST, cls_name: str):
        self.guard = guard
        self.node = node
        self.cls_name = cls_name


def _guard_arg(call: ast.Call) -> str:
    """The declared guard of a GuardedDict/GuardedSet constructor call."""
    if not call.args:
        return OWNER_THREAD
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    d = dotted(arg)
    if d and d.rsplit(".", 1)[-1] == "OWNER_THREAD":
        return OWNER_THREAD
    return OWNER_THREAD


def _is_guard_ctor(call: ast.AST) -> bool:
    if not isinstance(call, ast.Call):
        return False
    d = dotted(call.func)
    return bool(d) and d.rsplit(".", 1)[-1] in _GUARD_CTORS


class _ModuleGuards:
    """Per-module annotation inventory shared by the three rules."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        # class name -> attr -> _Decl
        self.decls: Dict[str, Dict[str, _Decl]] = {}
        self.conflicts: List[Tuple[_Decl, _Decl, str]] = []
        # class name -> every self.<attr> ever assigned (for RTL010's
        # unknown-guard check)
        self.assigned: Dict[str, Set[str]] = {}
        self._collect()

    def _collect(self):
        ctx = self.ctx
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            cls = ctx.enclosing_class(node)
            if cls is None:
                continue
            for target in node.targets:
                if not (
                    isinstance(target, ast.Attribute)
                    and dotted(target.value) in ("self", "cls")
                ):
                    continue
                self.assigned.setdefault(cls.name, set()).add(target.attr)
                if not _is_guard_ctor(node.value):
                    continue
                guard = _guard_arg(node.value)
                decl = _Decl(guard, node, cls.name)
                prev = self.decls.setdefault(cls.name, {}).setdefault(
                    target.attr, decl
                )
                if prev is not decl and prev.guard != guard:
                    self.conflicts.append((prev, decl, target.attr))

    def decl_for(self, cls_name: str, attr: str) -> Optional[_Decl]:
        return self.decls.get(cls_name, {}).get(attr)

    def guarded_accesses(self) -> Iterable[Tuple[ast.Attribute, _Decl]]:
        """Every self/cls access of an annotated attribute, minus the
        declaration assignments themselves."""
        ctx = self.ctx
        decl_targets = {
            id(t)
            for attrs in self.decls.values()
            for d in attrs.values()
            for t in getattr(d.node, "targets", ())
        }
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Attribute):
                continue
            if dotted(node.value) not in ("self", "cls"):
                continue
            if id(node) in decl_targets:
                continue
            cls = ctx.enclosing_class(node)
            if cls is None:
                continue
            decl = self.decl_for(cls.name, node.attr)
            if decl is not None:
                yield node, decl


_cache: Dict[int, _ModuleGuards] = {}


def _guards_of(ctx: ModuleContext) -> _ModuleGuards:
    # The three rules run over the same module in sequence; build the
    # inventory once per module (keyed by tree identity — a tmp-path
    # fixture module and a real module never collide).
    mg = _cache.get(id(ctx.tree))
    if mg is None or mg.ctx is not ctx:
        _cache.clear()
        mg = _cache[id(ctx.tree)] = _ModuleGuards(ctx)
    return mg


def _enclosing_fn(ctx: ModuleContext, node: ast.AST):
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return anc
    return None


def _locks_between(ctx: ModuleContext, node: ast.AST) -> List[str]:
    """Lock names held lexically at ``node`` — ``with`` items on the
    ancestor path up to (not crossing) the first function boundary."""
    out: List[str] = []
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            break
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                if is_lock_expr(item.context_expr):
                    text = lock_text(item.context_expr)
                    if text.startswith(("self.", "cls.")):
                        out.append(text.split(".", 1)[1])
                    else:
                        out.append(text)
    return out


def _fn_guard_decoration(fn) -> Optional[str]:
    for dec in getattr(fn, "decorator_list", ()):
        if (
            isinstance(dec, ast.Call)
            and (dotted(dec.func) or "").rsplit(".", 1)[-1] == "guarded_by"
            and dec.args
            and isinstance(dec.args[0], ast.Constant)
            and isinstance(dec.args[0].value, str)
        ):
            return dec.args[0].value
    return None


def _sanctioned_read(ctx: ModuleContext, access: ast.AST) -> bool:
    parent = ctx.parent(access)
    if not isinstance(parent, ast.Call) or access not in parent.args:
        return False
    d = dotted(parent.func) or ""
    return d.rsplit(".", 1)[-1] in _SNAPSHOT_FUNCS | _ATOMIC_FUNCS


def _classify(ctx: ModuleContext, access: ast.Attribute, decl: _Decl) -> str:
    """'ok' | 'unguarded' (RTL009) | 'wrong_lock' (RTL010)."""
    if _sanctioned_read(ctx, access):
        return "ok"
    fn = _enclosing_fn(ctx, access)
    fn_name = getattr(fn, "name", "")
    if fn_name in ("__init__", "__new__"):
        cls = ctx.enclosing_class(fn)
        if cls is not None and cls.name == decl.cls_name:
            return "ok"  # construction is single-threaded
    if fn is not None and _fn_guard_decoration(fn) == decl.guard:
        return "ok"
    held = _locks_between(ctx, access)
    if decl.guard in held:
        return "ok"
    if held:
        return "wrong_lock"
    return "unguarded"


# ---------------------------------------------------------------------------
# RTL009 — unguarded access to guard-annotated state


@register
class UnguardedAccess(Checker):
    rule = "RTL009"
    name = "unguarded-access"
    description = "guard-annotated attribute accessed without its lock"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for access, decl in _guards_of(ctx).guarded_accesses():
            if decl.guard == OWNER_THREAD:
                continue  # thread affinity is RTL011's + the runtime's job
            if _classify(ctx, access, decl) != "unguarded":
                continue
            parent = ctx.parent(access)
            op = "read"
            if isinstance(access.ctx, (ast.Store, ast.Del)) or (
                isinstance(parent, ast.Subscript)
                and isinstance(parent.ctx, (ast.Store, ast.Del))
            ):
                op = "write"
            findings.append(
                ctx.finding(
                    self.rule,
                    access,
                    f"{op} of {decl.cls_name}.{access.attr} (guarded by "
                    f"`self.{decl.guard}`) outside `with self.{decl.guard}:`"
                    " — take the lock, use snapshot()/cycle_snapshot(), or "
                    "mark the method @guarded_by",
                )
            )
        return findings


# ---------------------------------------------------------------------------
# RTL010 — inconsistent guard annotations


@register
class GuardInconsistency(Checker):
    rule = "RTL010"
    name = "guard-inconsistency"
    description = "guard annotation conflicts with itself or with usage"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        mg = _guards_of(ctx)
        findings: List[Finding] = []
        for prev, dup, attr in mg.conflicts:
            findings.append(
                ctx.finding(
                    self.rule,
                    dup.node,
                    f"{dup.cls_name}.{attr} re-declared with guard "
                    f"`{dup.guard}` but first declared with `{prev.guard}` "
                    f"(line {prev.node.lineno}) — one structure, one guard",
                )
            )
        for access, decl in mg.guarded_accesses():
            if decl.guard == OWNER_THREAD:
                continue
            if _classify(ctx, access, decl) != "wrong_lock":
                continue
            held = _locks_between(ctx, access)
            findings.append(
                ctx.finding(
                    self.rule,
                    access,
                    f"{decl.cls_name}.{access.attr} is guarded by "
                    f"`self.{decl.guard}` but accessed under "
                    f"`{held[0]}` — holding the wrong lock protects "
                    "nothing",
                )
            )
        # rebinding an OWNER_THREAD-annotated attribute outside __init__
        # silently REPLACES the GuardedDict with whatever plain value the
        # right-hand side built — the annotation (and the runtime witness
        # with it) is gone. Rebuild in place: clear() + update().
        # (lock-guarded rebinds are already RTL009 unguarded-writes.)
        for access, decl in mg.guarded_accesses():
            if decl.guard != OWNER_THREAD:
                continue
            if not isinstance(access.ctx, ast.Store):
                continue
            parent = ctx.parent(access)
            if isinstance(parent, ast.Assign) and _is_guard_ctor(parent.value):
                continue  # re-annotating is fine
            fn = _enclosing_fn(ctx, access)
            if getattr(fn, "name", "") in ("__init__", "__new__"):
                continue
            findings.append(
                ctx.finding(
                    self.rule,
                    access,
                    f"rebinding {decl.cls_name}.{access.attr} discards its "
                    "guard annotation (the new value is a plain container) "
                    "— mutate in place (clear() + update()) or re-declare "
                    "the GuardedDict/GuardedSet",
                )
            )
        # @guarded_by naming an attribute the class never assigns
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            guard = _fn_guard_decoration(node)
            if guard is None or guard == OWNER_THREAD:
                continue
            cls = ctx.enclosing_class(node)
            if cls is None:
                continue
            if guard not in mg.assigned.get(cls.name, set()):
                findings.append(
                    ctx.finding(
                        self.rule,
                        node,
                        f"@guarded_by({guard!r}) on {cls.name}.{node.name} "
                        f"but {cls.name} never assigns `self.{guard}` — "
                        "the contract names a lock that does not exist",
                    )
                )
        return findings


# ---------------------------------------------------------------------------
# RTL011 — callbacks touching guarded state


@register
class CallbackTouchesGuarded(Checker):
    rule = "RTL011"
    name = "callback-touches-guarded-state"
    description = "cross-thread callback touches guard-annotated state"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        mg = _guards_of(ctx)
        if not mg.decls:
            return ()
        findings: List[Finding] = []
        for call in ast.walk(ctx.tree):
            registrar = self._registrar_name(call)
            if registrar is None:
                continue
            for cb in self._callback_nodes(ctx, call):
                findings.extend(self._scan_callback(ctx, mg, registrar, cb))
        return findings

    @staticmethod
    def _registrar_name(node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        fn = node.func
        name = (
            fn.attr
            if isinstance(fn, ast.Attribute)
            else fn.id if isinstance(fn, ast.Name) else ""
        )
        return name if name in _REGISTRARS else None

    @staticmethod
    def _callback_nodes(ctx: ModuleContext, call: ast.Call) -> Iterable[ast.AST]:
        """The callable AST nodes handed to this registrar: inline
        lambdas, or nested defs referenced by name from the same
        function scope."""
        candidates = list(call.args) + [
            kw.value for kw in call.keywords if kw.arg in ("callback", "target", "fn", "handler")
        ]
        local_defs: Dict[str, ast.AST] = {}
        fn = _enclosing_fn(ctx, call)
        if fn is not None:
            for stmt in ast.walk(fn):
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    local_defs[stmt.name] = stmt
        for arg in candidates:
            if isinstance(arg, ast.Lambda):
                yield arg
            elif isinstance(arg, ast.Name) and arg.id in local_defs:
                yield local_defs[arg.id]

    def _scan_callback(
        self, ctx: ModuleContext, mg: _ModuleGuards, registrar: str, cb: ast.AST
    ) -> Iterable[Finding]:
        out: List[Finding] = []
        cls = ctx.enclosing_class(cb)
        if cls is None:
            return out
        for node in ast.walk(cb):
            if not (
                isinstance(node, ast.Attribute)
                and dotted(node.value) in ("self", "cls")
            ):
                continue
            decl = mg.decl_for(cls.name, node.attr)
            if decl is None:
                continue
            # a callback that takes the declared lock itself is fine
            if decl.guard != OWNER_THREAD and decl.guard in _locks_between(
                ctx, node
            ):
                continue
            out.append(
                ctx.finding(
                    self.rule,
                    node,
                    f"callback registered via .{registrar}() touches "
                    f"{decl.cls_name}.{node.attr} (guarded by "
                    f"`{decl.guard}`) directly — callbacks run on the "
                    "registrar's thread; marshal onto the owner (loop/"
                    "queue) or take the guard inside the callback",
                )
            )
        return out
