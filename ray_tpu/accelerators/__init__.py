"""Accelerator managers: detection, isolation, TPU pod topology.

Reference: python/ray/_private/accelerators/ — per-vendor
``AcceleratorManager`` subclasses; the rebuild keeps the registry but TPU
is the first-class citizen (reference: accelerators/tpu.py:71
TPUAcceleratorManager).
"""
from ray_tpu.accelerators.tpu import TPUAcceleratorManager

_managers = {"TPU": TPUAcceleratorManager()}


def get_accelerator_manager(resource_name: str):
    return _managers.get(resource_name)


def register_accelerator_manager(resource_name: str, manager):
    _managers[resource_name] = manager


__all__ = [
    "TPUAcceleratorManager",
    "get_accelerator_manager",
    "register_accelerator_manager",
]
