"""TPUAcceleratorManager.

Reference: python/ray/_private/accelerators/tpu.py:71 —
- chip detection via /dev/accel* and vfio (:98-117)
- ``TPU_VISIBLE_CHIPS`` isolation (:155-195) with valid per-host chip
  counts {1, 2, 4, 8} (:14 TPU_VALID_CHIP_OPTIONS)
- GCE/GKE metadata pod-type lookup (:198-228)
- pod-slice resources: ``TPU-<pod_type>-head`` on worker 0 and a
  ``TPU-<pod_type>`` name resource on every pod host (:334-397) so
  STRICT_PACK placement groups gang-schedule whole slices.
"""
from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional

TPU_VALID_CHIP_OPTIONS = (1, 2, 4, 8)
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
TPU_ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"  # e.g. "v5p-64"
TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
GCE_METADATA_URL = "http://metadata.google.internal/computeMetadata/v1/instance/attributes/"


class TPUAcceleratorManager:
    resource_name = "TPU"

    # -- detection ----------------------------------------------------------
    @staticmethod
    def get_current_node_num_accelerators() -> int:
        """Count local chips (reference: tpu.py:98-117)."""
        n = len(glob.glob("/dev/accel*"))
        if n == 0:
            entries = glob.glob("/dev/vfio/*")
            n = max(len([e for e in entries if not e.endswith("/vfio")]), 0)
        return n

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        """Pod type, e.g. "v5p-64": env override first, then GCE metadata
        (reference: tpu.py:198-228 — metadata lookup with env fallbacks)."""
        env = os.environ.get(TPU_ACCELERATOR_TYPE_ENV)
        if env:
            return env
        try:
            import urllib.request

            req = urllib.request.Request(
                GCE_METADATA_URL + "accelerator-type",
                headers={"Metadata-Flavor": "Google"},
            )
            with urllib.request.urlopen(req, timeout=1) as r:
                return r.read().decode().strip()
        except Exception:  # noqa: BLE001 — not on GCE
            return None

    @staticmethod
    def get_current_node_tpu_worker_id() -> int:
        return int(os.environ.get(TPU_WORKER_ID_ENV, "0"))

    # -- isolation ----------------------------------------------------------
    @staticmethod
    def validate_resource_request_quantity(quantity: float) -> tuple[bool, str]:
        """Per-host chip requests must be 1/2/4/8 (reference: tpu.py:140)."""
        if quantity in TPU_VALID_CHIP_OPTIONS or quantity % 8 == 0:
            return True, ""
        return False, (
            f"num_tpus must be one of {TPU_VALID_CHIP_OPTIONS} per host "
            f"(or a multiple of 8 for multi-host slices); got {quantity}"
        )

    @staticmethod
    def set_current_process_visible_accelerators(chip_ids: List[int]):
        """TPU_VISIBLE_CHIPS must be set before the first jax import in the
        process (libtpu reads it at initialization)."""
        os.environ[TPU_VISIBLE_CHIPS_ENV] = ",".join(str(i) for i in chip_ids)

    @staticmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[int]]:
        raw = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
        if raw is None or raw == "":
            return None
        return [int(x) for x in raw.split(",")]

    # -- pod topology resources --------------------------------------------
    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        """Slice-topology resources for this host (reference: tpu.py:334-397).

        Every host of pod slice "v5p-64" gets ``TPU-v5p-64: 1``; host 0
        additionally gets ``TPU-v5p-64-head: 1``. A STRICT_PACK PG on the
        head resource + per-host name resources gang-reserves the slice.
        """
        pod_type = TPUAcceleratorManager.get_current_node_accelerator_type()
        if not pod_type:
            return {}
        out = {f"TPU-{pod_type}": 1.0}
        if TPUAcceleratorManager.get_current_node_tpu_worker_id() == 0:
            out[f"TPU-{pod_type}-head"] = 1.0
        return out

    @staticmethod
    def num_hosts_in_slice(pod_type: str) -> int:
        """e.g. v5p-64 → 64 chips / 4 chips-per-host = 16... chips-per-host
        varies by generation; v5e=8 (1 host unit), v4/v5p=4."""
        try:
            gen, chips = pod_type.split("-")
            chips = int(chips)
        except ValueError:
            return 1
        per_host = 8 if gen in ("v5litepod", "v5e", "v6e") else 4
        return max(1, chips // per_host)
