"""Alias so the reference's import path works: ``ray.util.collective`` →
``ray_tpu.util.collective`` (reference: python/ray/util/collective/)."""
from ray_tpu.collective import *  # noqa: F401,F403
from ray_tpu.collective import __all__  # noqa: F401
