"""Actuator framework: bounded, rate-limited, audited remediation actions.

The observability legs (PR 9 incidents, PR 10 leak/pressure detectors,
PR 11 error-signature spikes, the compile-storm tracker) DETECT problems;
this module is the half that ACTS on them. Reference analogues: the
reference's raylet drains nodes it deems unhealthy and its memory monitor
kills workers past the usage threshold — detection wired straight into a
bounded actuator, audited through events. Same discipline here,
generalized: every remediation is an :class:`Actuator` registered in one
:class:`ActuatorRegistry` that enforces

- a per-(actuator, signal-key) COOLDOWN (the same remedy never hammers
  the same target in a loop),
- a global actions-per-minute budget (a detector storm cannot turn the
  health plane into its own denial of service),
- per-actuator DRY-RUN (config ``health_dry_run``: the decision is made,
  audited, and visible everywhere — the side effect is skipped),
- a bounded audit ring + ``health_actions_total{actuator, outcome}``
  metrics + first-class ``action`` lifecycle events (TRIGGERED →
  FINISHED/FAILED), so "what did the cluster do to itself and why" is
  answerable from ``state.summarize_health()`` alone.

The registry is single-writer by design: the controller dispatches only
from its asyncio loop (the controller-state discipline), so no lock is
needed. Actuator ``fire`` may return a coroutine for remediations that
cross the RPC plane; the registry schedules it and finalizes the audit
row / lifecycle chain on completion.
"""
from __future__ import annotations

import asyncio
import collections
import inspect
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("ray_tpu.actuators")

# Bounded outcome vocabulary — these become metric tags.
OUTCOMES = (
    "acted",      # the remediation ran (or was scheduled and completed)
    "dry_run",    # decision made, side effect suppressed by config
    "skipped",    # no viable target (e.g. the offender is the head node)
    "cooldown",   # same (actuator, key) fired too recently
    "throttled",  # global actions-per-minute budget exhausted
    "failed",     # the remediation raised / its RPC failed
)

_metrics: Optional[Dict[str, Any]] = None


def _get_metrics() -> Dict[str, Any]:
    """Process-wide singletons (Metric registers globally; a registry
    re-created in tests must not duplicate series)."""
    global _metrics
    if _metrics is None:
        from ray_tpu.util.metrics import Counter, Gauge

        _metrics = {
            "actions": Counter(
                "health_actions_total",
                "Self-healing actions dispatched, by actuator and outcome",
                ("actuator", "outcome"),
            ),
            "signals": Counter(
                "health_signals_total",
                "Detector signals observed by the health engine, by trigger",
                ("trigger",),
            ),
            "avoids": Gauge(
                "health_active_avoids",
                "Nodes currently quarantined (hard) or admission-throttled "
                "(soft) by the health plane",
                ("mode",),
            ),
        }
    return _metrics


@dataclass
class HealthSignal:
    """One detector observation handed to the health plane.

    ``trigger`` is the bounded trigger vocabulary (the incident-trigger
    names plus detector-only ones); ``key`` is the cooldown/dedup key —
    the node hex, call-site, or function name the signal is ABOUT;
    ``target`` is where a remediation would aim (often == key)."""

    trigger: str
    key: str
    detail: dict = field(default_factory=dict)
    target: str = ""
    ts: float = 0.0

    def __post_init__(self):
        if not self.ts:
            self.ts = time.time()


class Actuator:
    """One bounded remediation. Subclasses set ``name`` (metric tag /
    config key / audit label) and ``triggers`` (the signal kinds it
    handles) and implement :meth:`fire`.

    ``fire`` returns an outcome dict ``{"outcome": <OUTCOMES>, ...}``
    (extra keys land in the audit row) or a coroutine resolving to one;
    raising marks the action ``failed``."""

    name: str = "base"
    triggers: Tuple[str, ...] = ()

    def __init__(self, cooldown_s: float = 30.0, dry_run: bool = False):
        self.cooldown_s = float(cooldown_s)
        self.dry_run = bool(dry_run)

    def fire(self, signal: HealthSignal) -> Any:
        raise NotImplementedError

    def describe(self) -> dict:
        return {
            "name": self.name,
            "triggers": list(self.triggers),
            "cooldown_s": self.cooldown_s,
            "dry_run": self.dry_run,
        }


class ActuatorRegistry:
    """Dispatch detector signals to registered actuators under the
    cooldown / budget / dry-run / audit rules (module docstring)."""

    def __init__(
        self,
        audit_ring: int = 256,
        max_actions_per_min: int = 6,
        recorder: Optional[Callable[..., Any]] = None,
    ):
        self._actuators: List[Actuator] = []
        self.actions: "collections.deque[dict]" = collections.deque(
            maxlen=max(8, int(audit_ring))
        )
        self._last_fired: Dict[Tuple[str, str], float] = {}
        self._fired_window: "collections.deque[float]" = collections.deque()
        self.max_actions_per_min = int(max_actions_per_min)
        # Lifecycle hook: record(kind, eid, state, **attrs). None in
        # processes without a recorder (driver-side registries audit +
        # ship events themselves).
        self._recorder = recorder
        self._seq = 0
        self.signals_seen: Dict[str, int] = {}

    # ------------------------------------------------------------------
    def register(self, actuator: Actuator) -> Actuator:
        self._actuators.append(actuator)
        return actuator

    def get(self, name: str) -> Optional[Actuator]:
        for a in self._actuators:
            if a.name == name:
                return a
        return None

    # ------------------------------------------------------------------
    def dispatch(self, signal: HealthSignal) -> List[dict]:
        """Hand one signal to every actuator claiming its trigger.
        Returns the audit rows created (possibly still ``pending`` for
        coroutine-backed remediations)."""
        self.signals_seen[signal.trigger] = (
            self.signals_seen.get(signal.trigger, 0) + 1
        )
        try:
            _get_metrics()["signals"].inc(1, {"trigger": signal.trigger})  # ray-tpu: lint-ignore[RTL004] — bounded trigger vocabulary
        except Exception as e:  # noqa: BLE001 — metrics must not break dispatch
            logger.debug("signal metric failed: %s", e)
        rows = []
        for act in self._actuators:
            if signal.trigger not in act.triggers:
                continue
            rows.append(self._fire_one(act, signal))
        return rows

    def _fire_one(self, act: Actuator, signal: HealthSignal) -> dict:
        now = time.monotonic()
        self._seq += 1
        row = {
            "id": f"act-{self._seq}-{int(signal.ts * 1000) % 10_000_000}",
            "ts": signal.ts,
            "actuator": act.name,
            "trigger": signal.trigger,
            "key": signal.key,
            "target": signal.target or signal.key,
            "dry_run": act.dry_run,
            "outcome": "pending",
            "detail": dict(signal.detail),
        }
        ckey = (act.name, signal.key)
        last = self._last_fired.get(ckey)
        if last is not None and now - last < act.cooldown_s:
            # Cooldown hits are NOT audited as actions (a sustained
            # detector would flood the ring with no-ops) — only counted.
            self._count(act.name, "cooldown")
            row["outcome"] = "cooldown"
            return row
        while self._fired_window and now - self._fired_window[0] > 60.0:
            self._fired_window.popleft()
        if len(self._fired_window) >= self.max_actions_per_min:
            self._count(act.name, "throttled")
            row["outcome"] = "throttled"
            return row
        self._last_fired[ckey] = now
        self._fired_window.append(now)
        self.actions.append(row)
        self._record(row, "TRIGGERED")
        if act.dry_run:
            self._finish(row, {"outcome": "dry_run"})
            return row
        try:
            res = act.fire(signal)
        except Exception as e:  # noqa: BLE001 — a broken actuator must not kill dispatch
            logger.exception("actuator %s failed", act.name)
            self._finish(row, {"outcome": "failed", "error": str(e)})
            return row
        if inspect.iscoroutine(res):
            self._schedule(row, res, act.name)
        else:
            self._finish(row, res or {"outcome": "acted"})
        return row

    def _schedule(self, row: dict, coro, name: str):
        """Run a remediation coroutine on the current loop; finalize the
        audit row + lifecycle chain when it lands."""
        try:
            task = asyncio.ensure_future(coro)
        except RuntimeError:  # no running loop (unit tests)
            coro.close()
            self._finish(row, {"outcome": "failed", "error": "no event loop"})
            return

        def done(t):
            try:
                res = t.result()  # ray-tpu: lint-ignore[RTL008] — done-callback: the task is already resolved, never waits
            except Exception as e:  # noqa: BLE001 — remediation RPC failed
                logger.warning("actuator %s remediation failed: %s", name, e)
                self._finish(row, {"outcome": "failed", "error": str(e)})
                return
            self._finish(row, res or {"outcome": "acted"})

        task.add_done_callback(done)

    def _finish(self, row: dict, res: dict):
        outcome = res.get("outcome", "acted")
        if outcome not in OUTCOMES:
            outcome = "acted"
        row["outcome"] = outcome
        for k, v in res.items():
            if k != "outcome":
                row["detail"][k] = v
        self._count(row["actuator"], outcome)
        self._record(
            row, "FAILED" if outcome == "failed" else "FINISHED"
        )

    def _count(self, actuator: str, outcome: str):
        try:
            _get_metrics()["actions"].inc(1, {"actuator": actuator, "outcome": outcome})  # ray-tpu: lint-ignore[RTL004] — bounded actuator + outcome vocabularies
        except Exception as e:  # noqa: BLE001
            logger.debug("action metric failed: %s", e)

    def _record(self, row: dict, state: str):
        if self._recorder is None:
            return
        try:
            self._recorder(
                "action",
                row["id"],
                state,
                actuator=row["actuator"],
                trigger=row["trigger"],
                target=row["target"],
                outcome=row["outcome"] if state != "TRIGGERED" else None,
                dry_run=row["dry_run"] or None,
            )
        except Exception as e:  # noqa: BLE001 — recorder must not break actions
            logger.debug("action lifecycle record failed: %s", e)

    # ------------------------------------------------------------------
    def snapshot(self, limit: int = 50) -> dict:
        outcomes: Dict[str, Dict[str, int]] = {}
        for row in self.actions:
            by = outcomes.setdefault(row["actuator"], {})
            by[row["outcome"]] = by.get(row["outcome"], 0) + 1
        return {
            "actuators": [a.describe() for a in self._actuators],
            "max_actions_per_min": self.max_actions_per_min,
            "signals": dict(self.signals_seen),
            "actions_recent": list(self.actions)[-max(1, limit):],
            "outcomes": outcomes,
        }


def parse_dry_run(spec: str, name: str) -> bool:
    """``health_dry_run`` config: comma-separated actuator names forced
    into dry-run; ``*`` (or ``all``) covers every actuator."""
    toks = {t.strip() for t in (spec or "").split(",") if t.strip()}
    return bool(toks) and ("*" in toks or "all" in toks or name in toks)
