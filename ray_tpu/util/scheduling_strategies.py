"""User-facing scheduling strategy objects.

Reference: python/ray/util/scheduling_strategies.py:15
(PlacementGroupSchedulingStrategy), :41 (NodeAffinitySchedulingStrategy).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: object  # PlacementGroup
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False


# Node-label operators (reference: python/ray/util/scheduling_strategies
# .py:94-115 — In/NotIn/Exists/DoesNotExist label matching).
@dataclass
class In:
    values: list

    def __init__(self, *values):
        self.values = list(values)

    def to_wire(self):
        return ("in", self.values)


@dataclass
class NotIn:
    values: list

    def __init__(self, *values):
        self.values = list(values)

    def to_wire(self):
        return ("not_in", self.values)


class Exists:
    def to_wire(self):
        return ("exists", [])


class DoesNotExist:
    def to_wire(self):
        return ("does_not_exist", [])


@dataclass
class NodeLabelSchedulingStrategy:
    """Schedule onto nodes whose labels satisfy the expressions.

    ``hard``: must match or the task stays pending (and its demand is
    surfaced to the autoscaler as label-constrained). ``soft``: prefer
    matching nodes, fall back to any hard-feasible node.
    """

    hard: Optional[dict] = None
    soft: Optional[dict] = None

    def to_wire(self) -> dict:
        def conv(exprs):
            return {k: op.to_wire() for k, op in (exprs or {}).items()}

        return {"hard": conv(self.hard), "soft": conv(self.soft)}
