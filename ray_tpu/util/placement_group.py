"""Placement group public API.

Reference: python/ray/util/placement_group.py (``placement_group`` /
``remove_placement_group`` / ``placement_group_table`` / ``get``-style
readiness).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu.core.api import _require_worker
from ray_tpu.utils.ids import PlacementGroupID


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundle_specs = bundles

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until all bundles are reserved (the reference returns an
        ObjectRef; we block directly — await-able form comes with the async
        API)."""
        return _require_worker().pg_wait_ready(self.id, timeout)

    def wait(self, timeout_seconds: float = 30) -> bool:
        return self.ready(timeout=timeout_seconds)

    def bundle_nodes(self) -> List[Optional[str]]:
        """Node (hex id) hosting each bundle — used by the trainer to
        co-locate TPU worker groups."""
        return _require_worker().pg_bundle_nodes(self.id)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
) -> PlacementGroup:
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"invalid strategy {strategy}")
    pg_id = _require_worker().pg_create(bundles, strategy, name)
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup):
    _require_worker().pg_remove(pg.id)


def placement_group_table() -> dict:
    return _require_worker().pg_table()
