"""State API: cluster introspection for users and tools.

Reference: python/ray/util/state/api.py (:551-1431 — list_*/get_*/
summarize_*/get_log/list_logs) backed by the dashboard StateHead; here the
queries go straight to the controller (which is also reachable over HTTP at
``/api/v0/<resource>`` — ray_tpu/core/http_gateway.py).
"""
from __future__ import annotations

import json
import os
from collections import Counter as _Counter
from typing import List, Optional

from ray_tpu.core.api import _require_worker


def _list(what: str, **kwargs) -> List[dict]:
    return _require_worker().list_state(what, **kwargs)


def list_nodes() -> List[dict]:
    return _list("nodes")


def list_workers() -> List[dict]:
    return _list("workers")


def list_tasks(limit: int = 1000) -> List[dict]:
    return _list("tasks", limit=limit)


def list_actors() -> List[dict]:
    return _list("actors")


def list_objects(limit: int = 1000) -> List[dict]:
    return _list("objects", limit=limit)


def list_placement_groups() -> List[dict]:
    return _require_worker().pg_table()


def list_cluster_events(limit: int = 10000) -> List[dict]:
    return _list("events", limit=limit)


def _get_targeted(rpc: str, key: str, value: str, lister) -> Optional[dict]:
    """Point lookup via the controller's targeted get RPC, falling back
    to the legacy client-side scan over the full list_* dump (servers
    predating the get RPCs)."""
    try:
        return _require_worker()._call(rpc, **{key: value})
    except Exception:  # noqa: BLE001 — legacy server without the RPC
        for row in lister():
            if row.get(key) == value:
                return row
        return None


def get_task(task_id: str) -> Optional[dict]:
    return _get_targeted(
        "get_task", "task_id", task_id, lambda: list_tasks(limit=100000)
    )


def get_actor(actor_id: str) -> Optional[dict]:
    return _get_targeted("get_actor", "actor_id", actor_id, list_actors)


def get_node(node_id: str) -> Optional[dict]:
    return _get_targeted("get_node", "node_id", node_id, list_nodes)


def get_worker(worker_id: str) -> Optional[dict]:
    return _get_targeted("get_worker", "worker_id", worker_id, list_workers)


def get_placement_group(pg_id: str) -> Optional[dict]:
    for pg in list_placement_groups():
        if pg.get("placement_group_id") == pg_id or pg.get("id") == pg_id:
            return pg
    return None


# ---------------------------------------------------------------------------
# Summaries (reference: api.py summarize_tasks/actors/objects)
# ---------------------------------------------------------------------------
def summarize_tasks(limit: int = 1000) -> dict:
    """Counts by (name, state), computed controller-side so the RPC stays
    O(limit) at 40k+ tasks: the ``limit`` busiest task names get per-state
    rows; the reserved ``_totals`` key carries UNCAPPED counts-by-state,
    live pending-reason attribution, the total task count, and whether
    names were truncated."""
    res = _require_worker()._call("summarize_tasks", limit=limit)
    out: dict = dict(res.get("tasks", {}))
    out["_totals"] = {
        "by_state": res.get("counts_by_state", {}),
        "pending_reasons": res.get("pending_reasons", {}),
        "total": res.get("total", 0),
        "truncated": res.get("truncated", False),
    }
    return out


def summarize_actors() -> dict:
    by = _Counter()
    for a in list_actors():
        by[a["state"]] += 1
    return dict(by)


def summarize_objects(limit: int = 100) -> dict:
    """Controller-side object rollup (O(limit) wire cost — the old
    client-side path fetched 100k full rows over one RPC just to count
    them): uncapped totals by state/tier plus the ``limit`` largest
    creation call-sites. Falls back to the legacy scan against servers
    without the RPC."""
    try:
        return _require_worker()._call("summarize_objects", limit=limit)
    except Exception:  # noqa: BLE001 — legacy server without the RPC
        objs = list_objects(limit=100000)
        return {
            "total": len(objs),
            "total_size": sum(o["size"] or 0 for o in objs),
            "by_state": dict(_Counter(o["state"] for o in objs)),
        }


def summarize_memory(limit: int = 50, node: Optional[str] = None) -> dict:
    """Cluster-wide memory census (`ray-tpu memory`; reference: `ray
    memory` over core-worker reference counting): every process's open
    refs grouped by creation call-site, owner-local memory-store
    occupancy, zero-copy arena pins, per-node store stats (occupancy /
    spill-dir bytes / pins / deferred deletes), and the leak detector's
    live flags. ``node``: restrict the fan-out to one node's processes
    (node-id hex prefix)."""
    return _require_worker()._call(
        "summarize_memory", limit=limit, node=node, timeout=20,
    )


def list_object_refs(limit: int = 1000, node: Optional[str] = None) -> List[dict]:
    """Per-object census rows across all four tiers: directory objects
    (inline / shm / spilled) with owner + creation call-site + holder
    processes, plus owner-local memory-store objects the controller
    directory never sees, attributed via the process fan-out."""
    return _require_worker()._call(
        "list_object_refs", limit=limit, node=node, timeout=20,
    )


def summarize_lifecycle() -> dict:
    """Control-plane flight-recorder rollup (core/lifecycle.py): per-
    (kind, state) transition counts and dwell-time p50/p95/p99 for tasks,
    actors, placement groups, worker leases, and worker startup, plus
    why-pending attribution counters (insufficient_resources /
    no_idle_worker / pg_unready / spillback / infeasible / waiting_*)."""
    return _require_worker()._call("summarize_lifecycle")


def summarize_health(limit: int = 50) -> dict:
    """Self-healing plane summary (core/health.py): registered actuators
    with cooldown/dry-run config, recent actions with their trigger →
    target → outcome audit rows, per-trigger signal counts, per-actuator
    outcome tallies, and nodes currently quarantined or admission-
    throttled by the health plane. Rendered by ``ray-tpu health``."""
    return _require_worker()._call("summarize_health", limit=limit)


def list_lifecycle_events(limit: int = 10000) -> List[dict]:
    """The newest ``limit`` lifecycle transition events from the
    controller's bounded ring ({ts, kind, id, state, prev?, dwell_ms?,
    ...context})."""
    return _require_worker()._call("list_lifecycle_events", limit=limit)


def summarize_resources() -> dict:
    """Cluster resource rollup: per-node host CPU/mem + object-store
    occupancy (agent telemetry heartbeats), per-device HBM used/limit and
    compile activity (worker device reports), cluster totals, and the
    cross-rank collective skew table. Rendered by ``ray-tpu status``."""
    return _require_worker()._call("summarize_resources")


def compile_state() -> dict:
    """Per-process XLA compile-tracker snapshots ({node/proc: snapshot}),
    including active recompilation storms with the offending shape
    strings (see ray_tpu.util.compile_tracker)."""
    return _require_worker()._call("compile_state")


def lockwatch_state() -> dict:
    """THIS process's lock-order-watchdog snapshot (util.lockwatch,
    enabled via RAY_TPU_LOCKWATCH=1): watched-lock count, the acquisition-
    order edge count, and bounded rings of detected order cycles and
    long holds. Cluster-wide counts ride the normal metric flush
    (``lockwatch_order_cycles_total`` / ``lockwatch_long_holds_total``)."""
    from ray_tpu.util import lockwatch

    return lockwatch.state()


def collective_skew() -> list:
    """Cross-rank skew (max-min last-op latency, ms) per collective
    (group, op) key, worst first — the straggler view per ring/mesh."""
    return _require_worker()._call("collective_skew")


def serve_state() -> dict:
    """Raw engine flight-recorder snapshots, keyed
    ``deployment/replica/engine`` (pushed by LLM engines ~1/s; also at
    ``GET /api/serve/engine`` on the dashboard gateway)."""
    return _require_worker()._call("serve_state")


def summarize_serve() -> dict:
    """Per-deployment serving summary from the engine flight recorders:
    occupancy, token/preemption totals, and p50/p95/p99 latency
    breakdowns (queue/TTFT/TPOT/e2e) over the recent-request rings —
    percentiles without a Prometheus scrape (reference:
    ``summarize_*`` in api.py + the serve dashboard's replica detail).
    """
    from ray_tpu.serve.metrics import summarize_latencies

    out: dict = {}
    pooled: dict = {}
    for key, snap in serve_state().items():
        dep = snap.get("tags", {}).get("deployment", key.split("/")[0])
        d = out.setdefault(
            dep,
            {
                "engines": 0,
                "active": 0,
                "waiting": 0,
                "kv_blocks_free": 0,
                "kv_blocks_total": 0,
                "tokens": 0,
                "prompt_tokens": 0,
                "preemptions": 0,
                "finished_requests": 0,
                "prefix_cached_blocks": 0,
                "prefix_hit_tokens": 0,
                "prefix_lookup_tokens": 0,
                "prefill_chunks": 0,
                "overlap_windows": 0,
                "decode_windows": 0,
            },
        )
        occ = snap.get("occupancy", {})
        stats = snap.get("stats", {})
        pc = snap.get("prefix_cache", {})
        d["engines"] += 1
        d["active"] += occ.get("active", 0)
        d["waiting"] += occ.get("waiting", 0)
        d["kv_blocks_free"] += occ.get("kv_blocks_free", 0)
        d["kv_blocks_total"] += occ.get("kv_blocks_total", 0)
        d["tokens"] += stats.get("tokens", 0)
        d["prompt_tokens"] += stats.get("prompt_tokens", 0)
        d["preemptions"] += stats.get("preemptions", 0)
        d["finished_requests"] += stats.get("finished", 0)
        d["prefix_cached_blocks"] += pc.get("resident_blocks", 0)
        d["prefix_hit_tokens"] += stats.get("prefix_hit_tokens", 0)
        d["prefix_lookup_tokens"] += stats.get("prefix_lookup_tokens", 0)
        d["prefill_chunks"] += stats.get("prefill_chunks", 0)
        d["overlap_windows"] += stats.get("spec_windows", 0)
        d["decode_windows"] += stats.get("steps", 0)
        pool = pooled.setdefault(dep, {})
        for rec in snap.get("recent_requests", ()):
            for field in ("queue_ms", "ttft_ms", "tpot_ms", "e2e_ms"):
                if rec.get(field) is not None:
                    pool.setdefault(field, []).append(rec[field])
    for dep, pool in pooled.items():
        out[dep]["latency_ms"] = summarize_latencies(pool)
    for d in out.values():
        d["prefix_hit_rate"] = d["prefix_hit_tokens"] / max(
            1, d["prefix_lookup_tokens"]
        )
        d["overlap_occupancy"] = d["overlap_windows"] / max(
            1, d["decode_windows"]
        )
    return out


def _hist_rollup(entry: Optional[dict]) -> dict:
    """Merge a controller histogram entry's series and derive count/mean
    plus bucket-resolution p50/p95/p99 (each quantile reported as the
    upper boundary of the bucket its rank lands in; the overflow bucket
    reports the last boundary)."""
    if not entry:
        return {}
    merged = None
    boundaries: List[float] = []
    for _tags, payload in entry.get("series", []):
        st = payload.get("state", [])
        boundaries = payload.get("boundaries", boundaries)
        merged = st if merged is None else [a + b for a, b in zip(merged, st)]
    if not merged:
        return {}
    buckets, total, count = merged[:-2], merged[-2], merged[-1]
    if count <= 0:
        return {"count": 0}

    def pct(q: float) -> float:
        rank = q * count
        cum = 0
        for i, c in enumerate(buckets):
            cum += c
            if cum >= rank:
                return boundaries[i] if i < len(boundaries) else boundaries[-1]
        return boundaries[-1]

    return {
        "count": int(count),
        "mean": round(total / count, 3),
        "p50": pct(0.5),
        "p95": pct(0.95),
        "p99": pct(0.99),
    }


def summarize_rl() -> dict:
    """Podracer RL pipeline rollup from the controller's metric snapshot
    (ray_tpu.rllib.podracer): env-step throughput, sample-queue health
    (depth/wait/drops), policy-staleness distribution, learner step time,
    weight-broadcast and runner-restart counts. All series aggregate
    cluster-wide — queue actors, env runners, and the learner driver all
    flush into the same pipeline."""
    snap = metrics_snapshot()

    def counter(name: str) -> float:
        return sum(v for _t, v in (snap.get(name) or {}).get("series", []))

    def counter_by(name: str, tag: str) -> dict:
        out: dict = {}
        for tags, v in (snap.get(name) or {}).get("series", []):
            key = dict(tuple(t) for t in tags).get(tag, "")
            out[key] = out.get(key, 0.0) + v
        return out

    def gauge(name: str) -> float:
        vals = [v for _t, v in (snap.get(name) or {}).get("series", [])]
        return vals[-1] if vals else 0.0

    return {
        "env_steps_total": counter("rl_env_steps_total"),
        "fragments": {
            "enqueued": counter("rl_fragments_total"),
            "dropped": counter_by("rl_fragments_dropped_total", "reason"),
        },
        "queue": {
            "depth": gauge("rl_queue_depth"),
            "wait_ms": _hist_rollup(snap.get("rl_queue_wait_ms")),
        },
        "policy_lag": _hist_rollup(snap.get("rl_policy_lag")),
        "learner_step_ms": _hist_rollup(snap.get("rl_learner_step_ms")),
        "weights_published": counter("rl_weights_published_total"),
        "runner_restarts": counter("rl_runner_restarts_total"),
    }


def summarize_train() -> dict:
    """Train rollup from the controller's metric snapshot: step/report
    pacing plus the elastic-recovery counters (``ray-tpu summary
    train``) — gang member deaths observed, recoveries by mode
    (rejoin / remesh / rebuild / none), and the MTTR phase breakdown
    (detect → repair → resume latencies)."""
    snap = metrics_snapshot()

    def counter(name: str) -> float:
        return sum(v for _t, v in (snap.get(name) or {}).get("series", []))

    def counter_by(name: str, tag: str) -> dict:
        out: dict = {}
        for tags, v in (snap.get(name) or {}).get("series", []):
            key = dict(tuple(t) for t in tags).get(tag, "")
            out[key] = out.get(key, 0.0) + v
        return out

    return {
        "reports_total": counter("train_reports_total"),
        "step_wall_ms": _hist_rollup(snap.get("train_step_wall_ms")),
        "report_ms": _hist_rollup(snap.get("train_report_ms")),
        "driver_wait_ms": _hist_rollup(snap.get("train_driver_wait_ms")),
        "worker_deaths": counter("train_worker_deaths_total"),
        "recoveries": counter_by("train_recoveries_total", "mode"),
        "detect_ms": _hist_rollup(snap.get("train_detect_ms")),
        "repair_ms": _hist_rollup(snap.get("train_repair_ms")),
        "resume_ms": _hist_rollup(snap.get("train_resume_ms")),
    }


def summarize_data() -> list:
    """Per-operator stats of this process's most recent Dataset execution
    (reference: the dashboard data module's per-op metrics)."""
    from ray_tpu.data.executor import last_execution_stats

    return last_execution_stats()


def summarize_ingest() -> dict:
    """This process's consumption-side data-pipeline counters (zero-copy
    hits/misses, blocks fetched) plus total executor backpressure stalls
    from the last execution — the local companion to the cluster-wide
    ``data_*`` Prometheus series."""
    from ray_tpu.data.executor import last_execution_stats
    from ray_tpu.data.metrics import data_metrics

    out = dict(data_metrics().counts)
    out["backpressure_stalls_last_execution"] = sum(
        r.get("backpressure_stalls", 0) for r in last_execution_stats()
    )
    return out


# ---------------------------------------------------------------------------
# Logs (reference: api.py get_log :1262 / list_logs)
# ---------------------------------------------------------------------------
def _logs_dir() -> str:
    return os.path.join(_require_worker().session_dir, "logs")


def get_stack_traces(timeout_s: float = 10.0) -> dict:
    """Live thread stacks of every cluster process (reference: `ray
    stack` / the dashboard reporter's py-spy dumps): {process: text}."""
    from ray_tpu.core.api import _require_worker

    return _require_worker()._call("stack_dump_all", timeout_s)


# ---------------------------------------------------------------------------
# On-demand distributed profiling (util/profiling.py; reference: the
# dashboard reporter's py-spy stack/CPU-profile endpoints per worker)
# ---------------------------------------------------------------------------
def profile_stacks(node: Optional[str] = None, actor: Optional[str] = None,
                   timeout_s: float = 10.0) -> dict:
    """Cluster-wide structured stack dump — controller + agents + workers
    + drivers — with current-task attribution and lockwatch held-lock
    annotations. Returns {procs: {name: dump}, merged: deduplicated
    text}. Filter to one node's processes (``node``: node-id hex prefix)
    or one actor's worker (``actor``: actor-id hex prefix)."""
    return _require_worker()._call(
        "profile_stacks", node=node, actor=actor, timeout_s=timeout_s,
        timeout=timeout_s + 15,
    )


def profile_cpu(duration_s: float = 5.0, hz: Optional[float] = None,
                node: Optional[str] = None,
                workers: Optional[List[str]] = None) -> dict:
    """Cluster-wide sampling CPU profile: every selected process samples
    itself concurrently for ``duration_s`` at ``hz`` (default
    ``profiling_sample_hz``); samples are tagged with the executing
    task's name. Returns merged collapsed stacks + per-task CPU ms —
    render with ``ray-tpu profile cpu`` or profiling.speedscope_json."""
    return _require_worker()._call(
        "profile_cpu_all", duration_s=duration_s, hz=hz, node=node,
        workers=workers, timeout=duration_s + 30,
    )


def profile_device(workers: Optional[List[str]] = None,
                   duration_s: float = 5.0,
                   capture: Optional[str] = None) -> dict:
    """Attach ``jax.profiler`` traces to already-running workers for
    ``duration_s`` (no restart). Captures land in the session
    ``profiles/`` root next to runtime_env captures — list with
    :func:`list_profiles` / ``ray-tpu profile captures``."""
    # Timeout covers the controller's worst case — a 15s start timeout on
    # a wedged worker, the capture sleep, and a 15s stop timeout — with
    # margin, so one hung worker can't eat the others' finished captures.
    return _require_worker()._call(
        "profile_device_all", workers=workers, duration_s=duration_s,
        capture=capture, timeout=duration_s + 45,
    )


def list_incidents() -> List[dict]:
    """Incident capture bundles auto-written by the detector hooks
    (lockwatch long-hold/cycle, recompile storms, serve SLO breaches):
    {id, trigger, ts, process, pid, path, files} rows, oldest first."""
    return _require_worker()._call("profile_incidents")


def get_incident(incident_id: str) -> dict:
    """One incident bundle's metadata + file contents (stacks.txt,
    samples.collapsed, lifecycle_tail.json)."""
    return _require_worker()._call("get_incident", incident_id)


def summarize_profiling() -> dict:
    """Profiling rollup from the controller metric snapshot: per-task
    sampled CPU time (bucket-quantile p50/p95/p99 over ``task_cpu_ms``
    windows), total samples by mode, and incident counts by trigger."""
    snap = metrics_snapshot()

    def counter_by(name: str, tag: str) -> dict:
        out: dict = {}
        for tags, v in (snap.get(name) or {}).get("series", []):
            key = dict(tuple(t) for t in tags).get(tag, "")
            out[key] = out.get(key, 0.0) + v
        return out

    per_task: dict = {}
    for tags, payload in (snap.get("task_cpu_ms") or {}).get("series", []):
        tname = dict(tuple(t) for t in tags).get("name", "")
        per_task.setdefault(tname, {"series": []})["series"].append(
            (tags, payload)
        )
    tasks = {name: _hist_rollup(entry) for name, entry in per_task.items()}
    return {
        "task_cpu_ms": dict(
            sorted(tasks.items(), key=lambda kv: -kv[1].get("count", 0))
        ),
        "samples_total": counter_by("profiling_samples_total", "mode"),
        "incidents_total": counter_by("profiling_incidents_total", "trigger"),
    }


def list_logs(node: Optional[str] = None) -> List[str]:
    """Cluster-wide log file names (controller + every node's agent leg,
    merged/deduplicated; reference: ``ray logs`` / StateHead list_logs).
    ``node``: restrict to one node (node-id hex prefix). Falls back to
    the local session log dir against servers without the RPC."""
    return [r["filename"] for r in list_log_files(node=node)]


def list_log_files(node: Optional[str] = None) -> List[dict]:
    """Detail rows: {filename, size (rotated half folded in), mtime,
    structured (has a JSONL sidecar), node}."""
    try:
        return _require_worker()._call("list_logs", node=node, timeout=20)
    except Exception:  # noqa: BLE001 — legacy server without the RPC
        from ray_tpu.core.log_plane import list_local

        return list_local(_logs_dir())


def get_log(filename: str, tail: int = 1000, node: Optional[str] = None) -> str:
    """One log file's tail, wherever in the cluster it lives (rotation-
    aware: a freshly-rotated file borrows its ``.1`` half's tail).
    Raises ValueError on paths escaping the log dir."""
    try:
        return _require_worker()._call(
            "get_log", filename, tail=tail, node=node, timeout=20,
        )
    except (ValueError, FileNotFoundError):
        raise
    except Exception:  # noqa: BLE001 — legacy server without the RPC
        from ray_tpu.core.log_plane import read_local

        return read_local(_logs_dir(), filename, tail)


def search_logs(pattern: Optional[str] = None, *,
                severity: Optional[str] = None,
                task: Optional[str] = None,
                actor: Optional[str] = None,
                node: Optional[str] = None,
                since: Optional[float] = None,
                until: Optional[float] = None,
                limit: int = 1000) -> List[dict]:
    """Cluster-wide structured log search (the ``ray-tpu logs --grep``
    backend; reference: ``ray logs --actor-id/--task-id`` + the StateHead
    logs API): regex over messages, severity floor (``"ERROR"`` etc.),
    time range, and entity filters (task name / task-id prefix,
    actor-id prefix), fanned out to every node's JSONL sidecars. Rows
    carry {ts, sev, msg, node, worker, task, task_id, actor_id, file,
    line}; raw .log files without sidecars fall back to plain grep."""
    return _require_worker()._call(
        "search_logs", pattern=pattern, severity=severity, task=task,
        actor=actor, node=node, since=since, until=until, limit=limit,
        timeout=25,
    )


def summarize_errors(limit: int = 50) -> dict:
    """The cluster error index: ERROR/exception log records deduplicated
    controller-side by bounded signature (exception type + interned top
    user frames — the PR 10 CallsiteTable pattern) with counts,
    first/last seen, a sample traceback, and the lifecycle entity link
    ({total, distinct, signatures: {sig: {...}}})."""
    return _require_worker()._call("summarize_errors", limit=limit)


def follow_logs(callback=None, *, pattern: Optional[str] = None,
                severity: Optional[str] = None, task: Optional[str] = None,
                actor: Optional[str] = None, node: Optional[str] = None,
                err: bool = False):
    """Live-follow structured worker logs (``ray-tpu logs --follow``):
    registers this driver connection with the controller's record tailer;
    matching records arrive as pushed batches on the existing
    LogTailer→driver channel. ``callback(batch: List[dict])`` consumes
    them (default: render to stderr). Returns a ``stop()`` callable."""
    from ray_tpu.core.log_monitor import set_follow_sink

    core = _require_worker()
    if callback is not None:
        set_follow_sink(callback)
    core._call("log_follow", {
        "pattern": pattern, "severity": severity, "task": task,
        "actor": actor, "node": node, "err": err,
    })

    def stop():
        try:
            core._call("log_unfollow")
        finally:
            set_follow_sink(None)

    return stop


# ---------------------------------------------------------------------------
# Metrics + timeline
# ---------------------------------------------------------------------------
def metrics_snapshot() -> dict:
    return _require_worker()._call("metrics_snapshot")


def dashboard_url() -> Optional[str]:
    port_file = os.path.join(_require_worker().session_dir, "dashboard_port")
    if not os.path.exists(port_file):
        return None
    with open(port_file) as f:
        return f"http://127.0.0.1:{f.read().strip()}"


def timeline_chrome(
    filename: Optional[str] = None,
    include_lifecycle: bool = True,
    include_spans: bool = True,
    include_device: bool = True,
) -> list:
    """Chrome-trace (catapult) JSON merging four event sources into ONE
    chrome://tracing load (reference: `ray timeline` →
    chrome_tracing_dump, python/ray/_private/state.py:438):

    - task execution slices paired from the task event buffer
      (RUNNING → FINISHED/FAILED)
    - control-plane lifecycle slices from the flight recorder
      (``include_lifecycle``): scheduler decisions — queue/lease/dispatch
      dwell — rendered under ``lifecycle:<kind>`` process rows
    - user/application spans from the per-process JSONL sinks
      (``include_spans``, populated when RAY_TPU_TRACE=1)
    - XLA device-trace events from captured jax.profiler runs
      (``include_device``): every ``*.trace.json[.gz]`` under the session
      profiles root, re-labelled onto ``xla:<capture>`` rows (device
      timestamps are capture-relative — own tracks, not wall-aligned)
    """
    events = list_cluster_events(limit=1000000)
    open_spans: dict = {}
    trace = []
    for ev in events:
        key = ev.get("task_id")
        state = ev.get("state")
        if key is None or state is None:
            continue
        if state == "RUNNING":
            open_spans[key] = ev
        elif state in ("FINISHED", "FAILED") and key in open_spans:
            start = open_spans.pop(key)
            trace.append(
                {
                    "cat": "task",
                    "name": ev["name"],
                    "ph": "X",
                    "ts": start["ts"] * 1e6,
                    "dur": (ev["ts"] - start["ts"]) * 1e6,
                    "pid": ev.get("node_id", "cluster"),
                    "tid": ev.get("worker_id", ev["task_id"][:8]),
                    "args": {"task_id": key, "outcome": state},
                }
            )
    if include_lifecycle:
        from ray_tpu.core.lifecycle import to_chrome

        trace.extend(to_chrome(list_lifecycle_events(limit=1000000)))
    if include_spans:
        from ray_tpu.util.tracing import collect_spans

        trace.extend(collect_spans(_require_worker().session_dir))
    if include_device:
        from ray_tpu.util.profiling import collect_device_traces

        trace.extend(collect_device_traces(_require_worker().session_dir))
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


def list_profiles(session_dir: Optional[str] = None) -> List[dict]:
    """Captured jax.profiler traces in this session (reference: the
    nsight runtime-env plugin's reports, surfaced like `ray logs`).
    Rows: {id, name, task_id, captured_at, duration_s, path}.
    ``session_dir``: explicit session (the dashboard gateway passes its
    own; default = the connected driver's)."""
    import json as _json

    from ray_tpu.runtime_env.jax_profiler import profiles_root

    if session_dir is None:
        from ray_tpu.core.api import _require_worker

        session_dir = _require_worker().session_dir
    root = profiles_root(session_dir)
    rows = []
    if not os.path.isdir(root):
        return rows
    for entry in sorted(os.listdir(root)):
        d = os.path.join(root, entry)
        if entry.endswith(".external.json"):
            # pointer to a capture written to a custom dir
            row = {"id": entry[: -len(".external.json")]}
            try:
                with open(d) as f:
                    row.update(_json.load(f))
            except (OSError, ValueError):
                row["path"] = d
            rows.append(row)
            continue
        meta_path = os.path.join(d, "profile.json")
        row = {"id": entry, "path": d}
        if os.path.exists(meta_path):
            try:
                with open(meta_path) as f:
                    row.update(_json.load(f))
            except (OSError, ValueError):
                pass
        rows.append(row)
    return rows


def get_profile(profile_id: str) -> dict:
    """One capture's metadata + its trace files (absolute paths)."""
    from ray_tpu.core.api import _require_worker
    from ray_tpu.runtime_env.jax_profiler import profiles_root

    root = os.path.realpath(profiles_root(_require_worker().session_dir))
    rows = list_profiles()
    row = next((r for r in rows if r["id"] == profile_id), None)
    if row is not None and row.get("path") and os.path.isdir(row["path"]):
        d = row["path"]  # may be a custom capture dir outside the root
    else:
        d = os.path.realpath(os.path.join(root, profile_id))
        if os.path.commonpath([d, root]) != root:
            raise ValueError("profile path escapes the session profiles dir")
        if not os.path.isdir(d):
            raise FileNotFoundError(f"no profile {profile_id!r}")
        row = row or {"id": profile_id, "path": d}
    files = []
    for base, _dirs, names in os.walk(d):
        files.extend(os.path.join(base, n) for n in names)
    row["files"] = sorted(files)
    return row
