"""Runtime lock-order watchdog — the dynamic sibling of lint rule RTL005.

Wraps ``threading.Lock`` / ``threading.RLock`` *creation* in ray_tpu
modules (caller-module check at the factory, so stdlib and user locks stay
raw) and bookkeeps every acquire/release:

* **per-thread acquisition stacks** — each thread's currently-held locks
  with their acquire sites;
* **order-cycle detection** — a global acquisition-order graph (edge
  A→B whenever a thread acquires B while holding A). Acquiring an edge
  whose reverse path already exists is a potential deadlock: it is logged
  with both acquire sites, counted, and kept in a bounded ring for
  :func:`state`;
* **long holds** — releases after more than ``RAY_TPU_LOCKWATCH_HOLD_MS``
  (default 200) are recorded the same way: a lock held across a blocking
  call (RTL001's runtime shadow) shows up here even when the static rule
  could not see it.

Enable with ``RAY_TPU_LOCKWATCH=1`` + :func:`maybe_install` — the tier-1
conftest does both, so the whole test suite runs under the watchdog.
Reports flow through the existing plumbing: counters in
``ray_tpu.util.metrics`` (``lockwatch_order_cycles_total``,
``lockwatch_long_holds_total``) and the :func:`state` snapshot.

This module must import standalone (no ray_tpu imports at module level):
the conftest loads it *before* the package so that locks created during
``import ray_tpu`` are themselves instrumented.
"""
from __future__ import annotations

import itertools
import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

logger = logging.getLogger("ray_tpu.lockwatch")

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_meta_lock = _REAL_LOCK()  # guards the order graph + report rings (never wrapped)
_tls = threading.local()

_installed = False
_uid = itertools.count(1)

# order graph: lock uid -> set of successor uids (A held while acquiring B)
_graph: Dict[int, Set[int]] = {}
# edge -> (site of first observation)
_edge_sites: Dict[Tuple[int, int], str] = {}
_names: Dict[int, str] = {}

_MAX_REPORTS = 64
_cycles: List[dict] = []
_long_holds: List[dict] = []
_cycle_pairs_reported: Set[Tuple[int, int]] = set()
_watched_locks = 0
# lock uid -> (absolute creation file, line): lets the ConcSan lock-order
# cross-check map a runtime lock back to the `self._lock = Lock()` site
# the static graph (RTL005) names.
_creation_sites: Dict[int, Tuple[str, int]] = {}
# Optional preemption hook installed by the interleaving fuzzer
# (tools/sanitizer/fuzzer.py): called as hook(point_kind, lock_name) at
# lock-boundary yield points. Plain module global read — when None (the
# default) the hot path pays one load + is-None test.
_yield_hook = None
# thread ident -> that thread's held-stack LIST OBJECT (the same list
# _tls.held aliases): lets the profiling stack dumper annotate OTHER
# threads' held locks. Entries for dead threads are pruned on snapshot.
_held_registry: Dict[int, list] = {}

# counters are created lazily (metrics imports config; this module must
# stay importable before the package)
_metric_cycles = None
_metric_long_holds = None
_metric_empty_locksets = None


def _hold_threshold_ms() -> float:
    try:
        return float(os.environ.get("RAY_TPU_LOCKWATCH_HOLD_MS", "200"))
    except ValueError:
        return 200.0


def _caller_site(depth: int) -> str:
    """Cheap acquire-site tag (no traceback machinery on the hot path).
    Walks past lockwatch's own frames (``with lock:`` enters via
    __enter__ → acquire) so the tag names user code."""
    try:
        f = sys._getframe(depth)
        while f is not None and f.f_code.co_filename.endswith("lockwatch.py"):
            f = f.f_back
        if f is None:
            return "?"
        return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"
    except Exception:  # noqa: BLE001 — frame depth off at thread exit
        return "?"


def _caller_frame(depth: int):
    try:
        f = sys._getframe(depth)
        while f is not None and f.f_code.co_filename.endswith("lockwatch.py"):
            f = f.f_back
        return f
    except Exception:  # noqa: BLE001 — frame depth off at thread exit
        return None


def _caller_full_site(depth: int) -> Tuple[str, int]:
    """Creation-site tag with the FULL path (the short :func:`_caller_site`
    form is ambiguous across same-named files; the ConcSan lock-order
    cross-check needs to join on (path, line))."""
    f = _caller_frame(depth + 1)
    if f is None:
        return ("?", 0)
    return (f.f_code.co_filename, f.f_lineno)


def _held_stack() -> list:
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
        _held_registry[threading.get_ident()] = st
    return st


def current_held() -> List[tuple]:
    """The CURRENT thread's held watched locks, innermost last, as
    ``(WatchedLock, acquired_monotonic, acquire_site)`` tuples. Lock-free
    (the list is only mutated by this thread); the ConcSan runtime calls
    this on every guarded-state access, so it must stay allocation-light.
    """
    return list(getattr(_tls, "held", None) or ())


def set_yield_hook(hook) -> None:
    """Install (or clear, with ``None``) the fuzzer's preemption hook.

    The hook runs at every lock-boundary yield point —
    ``("acquire", name)`` before blocking on a watched lock and
    ``("release", name)`` after letting it go — and may sleep to widen
    race windows. Installed only by the interleaving fuzzer; anything
    else should leave this alone.
    """
    global _yield_hook
    _yield_hook = hook


def _maybe_yield(point: str, wuid: int) -> None:
    hook = _yield_hook
    if hook is None or _in_watchdog():
        return
    try:
        hook(point, _names.get(wuid, "?"))
    except Exception as e:  # noqa: BLE001 — fuzzer must never take the process down
        logger.debug("lockwatch yield hook failed: %s", e)


def held_snapshot() -> Dict[int, List[dict]]:
    """Per-thread currently-held watched locks, for stack-dump
    annotation ({ident: [{lock, acquired_at, held_ms}]}). Deliberately
    lock-free: it reads each thread's held list (mutated only by its
    owner; list copies are atomic under the GIL) and the append-only
    ``_names`` map — so a process wedged on these very locks can still
    dump itself."""
    live = {t.ident for t in threading.enumerate()}
    now = time.monotonic()
    out: Dict[int, List[dict]] = {}
    for ident in list(_held_registry):
        if ident not in live:
            _held_registry.pop(ident, None)
            continue
        items = []
        for entry in list(_held_registry.get(ident) or ()):
            try:
                lock, t0, site = entry
            except (TypeError, ValueError):
                continue
            items.append(
                {
                    "lock": _names.get(lock._wuid, "?"),
                    "acquired_at": site,
                    "held_ms": round((now - t0) * 1000.0, 1),
                }
            )
        if items:
            out[ident] = items
    return out


def _in_watchdog() -> bool:
    return getattr(_tls, "in_watchdog", False)


def _report_metrics(cycles: int = 0, long_holds: int = 0, empty_locksets: int = 0):
    """Bump the lockwatch counters through util.metrics. Guarded by the
    reentrancy flag: Counter.inc acquires the (instrumented) metrics lock,
    which must not recurse into bookkeeping."""
    global _metric_cycles, _metric_long_holds, _metric_empty_locksets
    _tls.in_watchdog = True
    try:
        if _metric_cycles is None:
            from ray_tpu.util.metrics import Counter

            _metric_cycles = Counter(
                "lockwatch_order_cycles_total",
                "Lock-order inversions detected by the runtime watchdog",
            )
            _metric_long_holds = Counter(
                "lockwatch_long_holds_total",
                "Lock holds exceeding RAY_TPU_LOCKWATCH_HOLD_MS",
            )
            _metric_empty_locksets = Counter(
                "lockwatch_empty_lockset_total",
                "Guarded-state accesses whose Eraser lockset went empty "
                "(ConcSan race candidates)",
            )
        if cycles:
            _metric_cycles.inc(cycles)
        if long_holds:
            _metric_long_holds.inc(long_holds)
        if empty_locksets:
            _metric_empty_locksets.inc(empty_locksets)
    except Exception as e:  # noqa: BLE001 — watchdog must never take the process down
        logger.debug("lockwatch metric report failed: %s", e)
    finally:
        _tls.in_watchdog = False


def note_empty_lockset(n: int = 1) -> None:
    """ConcSan entry point: a guarded access's lockset intersection went
    empty. Exported here (not in the sanitizer) so the finding rides the
    lockwatch metric plumbing into the Grafana Self-healing row."""
    _report_metrics(empty_locksets=n)


def _maybe_incident(trigger: str, info: dict):
    """Flush an incident capture bundle for a detector hit (profiling
    subsystem; rate-limited + bounded there). Runs with the reentrancy
    flag set so the capture's own lock traffic skips bookkeeping."""
    _tls.in_watchdog = True
    try:
        from ray_tpu.util.profiling import incident

        incident(trigger, info)
    except Exception as e:  # noqa: BLE001 — watchdog must never take the process down
        logger.debug("lockwatch incident capture failed: %s", e)
    finally:
        _tls.in_watchdog = False


def _path_exists(src: int, dst: int) -> bool:
    """DFS in the order graph (caller holds _meta_lock)."""
    stack, seen = [src], set()
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(_graph.get(cur, ()))
    return False


class WatchedLock:
    """Instrumented wrapper over a raw Lock/RLock.

    Supports the full context-manager + acquire/release protocol;
    everything else (``locked``, RLock owner introspection for
    ``threading.Condition``) is delegated to the raw lock.
    """

    def __init__(self, raw, name: str, csite: Optional[Tuple[str, int]] = None):
        self._raw = raw
        self._wuid = next(_uid)
        _names[self._wuid] = name
        if csite is not None:
            _creation_sites[self._wuid] = csite

    # -- protocol -----------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _in_watchdog():
            return self._raw.acquire(blocking, timeout)
        if _yield_hook is not None:
            _maybe_yield("acquire", self._wuid)
        held = _held_stack()
        # Record intent BEFORE blocking: the edge must exist while we wait,
        # or two threads deadlocking right now would each report nothing.
        if held:
            self._note_edges(held)
        got = self._raw.acquire(blocking, timeout)
        if got:
            held.append((self, time.monotonic(), _caller_site(2)))
        return got

    def release(self):
        popped = None
        if not _in_watchdog():
            held = _held_stack()
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] is self:
                    popped = held.pop(i)
                    break
        self._raw.release()
        if _yield_hook is not None:
            _maybe_yield("release", self._wuid)
        # Long-hold reporting AFTER the raw release — logging/metrics must
        # not extend the very hold they are complaining about.
        if popped is not None:
            self._check_hold(popped[1], popped[2])

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        # Everything else — locked() on Lock, RLock internals for
        # Condition (_is_owned, _release_save, ...) — delegates to the raw
        # lock, so the wrapper's attribute surface exactly matches what
        # the unwrapped object would expose on this Python version.
        return getattr(self._raw, name)

    # -- bookkeeping --------------------------------------------------------
    def _note_edges(self, held):
        site = _caller_site(3)
        new_cycles = 0
        with _meta_lock:
            for other, _t0, other_site in held:
                if other is self:
                    return  # re-entrant acquire (RLock): no ordering info
                a, b = other._wuid, self._wuid
                succ = _graph.setdefault(a, set())
                if b in succ:
                    continue
                # cycle iff the REVERSE direction is already reachable
                if _path_exists(b, a):
                    pair = (min(a, b), max(a, b))
                    if pair not in _cycle_pairs_reported:
                        _cycle_pairs_reported.add(pair)
                        new_cycles += 1
                        info = {
                            "locks": (_names[a], _names[b]),
                            "forward": f"{_names[a]} -> {_names[b]} at {site} "
                                       f"(outer held at {other_site})",
                            "reverse_first_seen": _edge_sites.get(
                                (b, a), "(via longer path)"
                            ),
                            # the full held SET, not just the edge pair —
                            # with three or more locks in play, the pair
                            # alone hides which discipline was violated
                            "held": [_names[o._wuid] for o, _, _ in held],
                            "thread": threading.current_thread().name,
                            "time": time.time(),
                        }
                        if len(_cycles) < _MAX_REPORTS:
                            _cycles.append(info)
                succ.add(b)
                _edge_sites[(a, b)] = site
        if new_cycles:
            logger.warning(
                "lock-order cycle: acquiring %s while holding %s at %s — "
                "reverse order seen at %s (potential deadlock)",
                _names[self._wuid], [_names[o._wuid] for o, _, _ in held],
                site, _cycles[-1]["reverse_first_seen"] if _cycles else "?",
            )
            _report_metrics(cycles=new_cycles)
            # A cycle means a deadlock may be forming RIGHT NOW — capture
            # before this thread blocks on the raw acquire.
            _maybe_incident(
                "lockwatch_cycle", _cycles[-1] if _cycles else {"site": site}
            )

    def _check_hold(self, t0: float, site: str):
        dt_ms = (time.monotonic() - t0) * 1000.0
        if dt_ms < _hold_threshold_ms():
            return
        info = {
            "lock": _names[self._wuid],
            "held_ms": round(dt_ms, 1),
            "acquired_at": site,
            "released_at": _caller_site(3),
            # locks STILL held after this release — a non-empty set here
            # means the long hold happened inside a nested critical section
            "held": [_names.get(o._wuid, "?") for o, _, _ in _held_stack()],
            "thread": threading.current_thread().name,
            "time": time.time(),
        }
        with _meta_lock:
            if len(_long_holds) < _MAX_REPORTS:
                _long_holds.append(info)
        # warn for the first few, then demote to debug — a hot lock with a
        # systematic long hold would otherwise flood the log
        level = logging.WARNING if len(_long_holds) <= 20 else logging.DEBUG
        logger.log(
            level,
            "lock %s held %.1f ms (acquired %s, released %s) — blocking "
            "work under a lock stalls every waiter",
            info["lock"], dt_ms, site, info["released_at"],
        )
        _report_metrics(long_holds=1)
        _maybe_incident("lockwatch_long_hold", info)


def wrap(raw=None, name: Optional[str] = None) -> WatchedLock:
    """Explicitly instrument a lock (tests / ad-hoc opt-in)."""
    global _watched_locks
    if raw is None:
        raw = _REAL_LOCK()
    lock = WatchedLock(
        raw, name or f"lock@{_caller_site(2)}", csite=_caller_full_site(2)
    )
    with _meta_lock:
        _watched_locks += 1
    return lock


def _should_wrap(module: str) -> bool:
    return module.startswith("ray_tpu") and module != "ray_tpu.util.lockwatch"


def _lock_factory():
    if _should_wrap(sys._getframe(1).f_globals.get("__name__", "")):
        return wrap(_REAL_LOCK(), name=f"Lock@{_caller_site(2)}")
    return _REAL_LOCK()


def _rlock_factory():
    if _should_wrap(sys._getframe(1).f_globals.get("__name__", "")):
        return wrap(_REAL_RLOCK(), name=f"RLock@{_caller_site(2)}")
    return _REAL_RLOCK()


def install():
    """Patch threading.Lock/RLock so ray_tpu-created locks are watched.

    Locks created before install (or via ``from threading import Lock``
    bound earlier) stay raw — call this as early as possible.
    """
    global _installed
    if _installed:
        return
    _installed = True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    logger.info(
        "lockwatch installed (hold threshold %.0f ms)", _hold_threshold_ms()
    )


def uninstall():
    global _installed
    if not _installed:
        return
    _installed = False
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK


def maybe_install() -> bool:
    """Install iff RAY_TPU_LOCKWATCH=1 (the tier-1 conftest entry point)."""
    if os.environ.get("RAY_TPU_LOCKWATCH", "") == "1":
        install()
    return _installed


def state() -> dict:
    """Snapshot for the state API / debugging."""
    with _meta_lock:
        return {
            "installed": _installed,
            "watched_locks": _watched_locks,
            "hold_threshold_ms": _hold_threshold_ms(),
            "order_edges": len(_edge_sites),
            "cycles": list(_cycles),
            "long_holds": list(_long_holds),
        }


def graph_snapshot() -> List[dict]:
    """The observed lock-order graph as a list of edges, each carrying
    both locks' CREATION sites (full path + line). This is the dynamic
    half of the ConcSan lock-order cross-check: the sanitizer joins
    these creation sites against the static graph RTL005 builds from
    ``self._x = threading.Lock()`` assignment sites."""

    def _site(uid: int):
        path, line = _creation_sites.get(uid, ("?", 0))
        return {"file": path, "line": line}

    with _meta_lock:
        return [
            {
                "src": _names.get(a, "?"),
                "dst": _names.get(b, "?"),
                "src_site": _site(a),
                "dst_site": _site(b),
                "observed_at": site,
            }
            for (a, b), site in _edge_sites.items()
        ]


def reset():
    """Clear graph + reports (tests)."""
    with _meta_lock:
        _graph.clear()
        _edge_sites.clear()
        _cycles.clear()
        _long_holds.clear()
        _cycle_pairs_reported.clear()
