"""ActorPool: round-robin work distribution over a fixed set of actors.

Reference: python/ray/util/actor_pool.py (map/map_unordered/submit/
get_next/get_next_unordered).
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, List


class ActorPool:
    def __init__(self, actors: List[Any]):
        if not actors:
            raise ValueError("ActorPool needs at least one actor")
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0

    def submit(self, fn: Callable, value: Any):
        """fn(actor, value) -> ObjectRef; blocks only if no actor is idle."""
        import ray_tpu

        if not self._idle:
            # Wait for any in-flight call to finish, then reuse its actor.
            refs = list(self._future_to_actor)
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=None)
            self._reclaim(ready[0])
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._future_to_actor[ref] = actor
        self._index_to_future[self._next_task_index] = ref
        self._next_task_index += 1

    def _reclaim(self, ref):
        actor = self._future_to_actor.pop(ref, None)
        if actor is not None:
            self._idle.append(actor)

    def has_next(self) -> bool:
        return self._next_return_index < self._next_task_index

    def get_next(self, timeout=None):
        """Results in submission order."""
        import ray_tpu

        if not self.has_next():
            raise StopIteration("no pending results")
        ref = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        value = ray_tpu.get(ref, timeout=timeout)
        self._reclaim(ref)
        return value

    def get_next_unordered(self, timeout=None):
        """Whichever result lands first."""
        import ray_tpu

        refs = list(self._index_to_future.values())
        if not refs:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        ref = ready[0]
        for idx, r in list(self._index_to_future.items()):
            if r == ref:
                del self._index_to_future[idx]
                if idx == self._next_return_index:
                    while self._next_return_index not in self._index_to_future and self._next_return_index < self._next_task_index:
                        self._next_return_index += 1
                break
        value = ray_tpu.get(ref)
        self._reclaim(ref)
        return value

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self._index_to_future:
            yield self.get_next_unordered()
