"""Grafana dashboard generation from the metric registry.

Reference: python/ray/dashboard/modules/metrics/grafana_dashboard_factory.py
— the reference renders fixed panel configs into importable Grafana JSON
pointed at the Prometheus scrape of the cluster. Same product here, but
the panel list is DERIVED from the live metric registry (core metrics +
any application Counter/Gauge/Histogram), so user metrics get panels
without editing a template:

- Counter  → rate() timeseries
- Gauge    → raw timeseries
- Histogram→ p50/p95/p99 via histogram_quantile over the _bucket series

``ray-tpu metrics dashboard`` emits the JSON; point Grafana's Prometheus
datasource at this cluster's ``/metrics`` endpoint (core/http_gateway.py).
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

DATASOURCE = "${datasource}"  # Grafana template var, like the reference


def _panel(panel_id: int, title: str, targets: List[dict], y: int, x: int,
           description: str = "") -> dict:
    return {
        "id": panel_id,
        "title": title,
        "description": description,
        "type": "timeseries",
        "datasource": DATASOURCE,
        "targets": targets,
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "fieldConfig": {"defaults": {"custom": {"fillOpacity": 10}}},
    }


def _target(expr: str, legend: str) -> dict:
    return {"expr": expr, "legendFormat": legend, "datasource": DATASOURCE}


def _row_panel(panel_id: int, title: str, y: int) -> dict:
    """A Grafana row separator (reference: the fixed serve/train rows of
    the reference's default dashboards)."""
    return {
        "id": panel_id,
        "title": title,
        "type": "row",
        "collapsed": False,
        "datasource": DATASOURCE,
        "gridPos": {"h": 1, "w": 24, "x": 0, "y": y},
        "panels": [],
    }


# Dashboard rows, matched by metric-name prefix in order; unmatched
# metrics land in the catch-all Application row.
ROWS = (
    ("Serve SLO", ("serve_request_", "serve_ttft", "serve_tpot", "serve_e2e",
                   "serve_tokens_", "serve_requests_", "serve_proxy_",
                   "serve_batch_")),
    ("Serve Engine", ("serve_engine_",)),
    ("Train", ("train_",)),
    ("RL", ("rl_",)),
    ("Data", ("data_",)),
    ("Control Plane", ("task_state_", "task_pending_", "lease_",
                       "lockwatch_", "task_push_", "scheduler_")),
    ("Profiling", ("task_cpu_", "profiling_")),
    ("Logs & Errors", ("log_",)),
    ("Self-healing", ("health_", "lockwatch_empty_lockset_")),
    ("Memory", ("object_store_", "object_refs_", "object_free_",
                "memory_leak_")),
    ("Cluster Resources", ("tpu_hbm_", "node_",
                           "metrics_series_")),
    ("Compilation", ("jax_",)),
    ("Collectives", ("collective_", "object_transfer_")),
    ("Application", ("",)),
)


def _row_for(name: str) -> str:
    # Longest matching prefix wins (not first match): a specific series
    # like lockwatch_empty_lockset_* routes to Self-healing even though
    # the broader lockwatch_* family lives in Control Plane.
    best, best_len = "Application", -1
    for title, prefixes in ROWS:
        for p in prefixes:
            if name.startswith(p) and len(p) > best_len:
                best, best_len = title, len(p)
    return best


def panels_for_metric(name: str, mtype: str, description: str = "") -> List[dict]:
    """Prometheus queries per metric type (panel positions filled later)."""
    if mtype == "counter":
        return [{"title": f"{name} (rate)", "description": description,
                 "targets": [_target(f"rate({name}[5m])", "{{instance}}")]}]
    if mtype == "histogram":
        qs = [
            _target(
                f"histogram_quantile({q}, sum(rate({name}_bucket[5m])) by (le))",
                f"p{int(q * 100)}",
            )
            for q in (0.5, 0.95, 0.99)
        ]
        return [{"title": f"{name} (quantiles)", "description": description,
                 "targets": qs}]
    # gauges and anything unrecognized: plot raw
    return [{"title": name, "description": description,
             "targets": [_target(name, "{{instance}}")]}]


def generate_dashboard(
    snapshot: Optional[Dict[str, dict]] = None,
    *,
    title: str = "ray_tpu cluster",
    uid: str = "ray-tpu-default",
) -> dict:
    """Build the importable dashboard dict. ``snapshot``: the controller's
    metrics snapshot ({name: {type, description, ...}}); None → connect
    via the current driver and fetch it."""
    if snapshot is None:
        from ray_tpu.core.api import _require_worker

        snapshot = _require_worker()._call("metrics_snapshot")
    # Group panel specs into dashboard rows (Serve SLO / Serve Engine /
    # Train / Application) so the serving and training stories read as
    # units instead of one alphabetical wall.
    by_row: Dict[str, List[dict]] = {}
    for name in sorted(snapshot):
        e = snapshot[name]
        by_row.setdefault(_row_for(name), []).extend(
            panels_for_metric(name, e.get("type", "gauge"),
                              e.get("description", ""))
        )
    panels = []
    pid = 1
    y = 0
    for title, _prefixes in ROWS:
        specs = by_row.get(title)
        if not specs:
            continue
        panels.append(_row_panel(pid, title, y))
        pid += 1
        y += 1
        for i, spec in enumerate(specs):
            x = (i % 2) * 12
            panels.append(_panel(pid, spec["title"], spec["targets"],
                                 y + (i // 2) * 8, x,
                                 spec.get("description", "")))
            pid += 1
        y += -(-len(specs) // 2) * 8
    return {
        "uid": uid,
        "title": title,
        "tags": ["ray_tpu", "generated"],
        "timezone": "browser",
        "schemaVersion": 39,
        "version": 1,
        "refresh": "10s",
        "time": {"from": "now-1h", "to": "now"},
        "templating": {
            "list": [{
                "name": "datasource",
                "type": "datasource",
                "query": "prometheus",
                "label": "Datasource",
            }]
        },
        "panels": panels,
        "__meta": {
            "generated_by": "ray-tpu metrics dashboard",
            "generated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "metric_count": len(snapshot),
        },
    }


def dashboard_json(snapshot: Optional[Dict[str, dict]] = None, **kw) -> str:
    return json.dumps(generate_dashboard(snapshot, **kw), indent=1)
