"""Distributed tracing: spans with cross-task context propagation.

Reference: python/ray/util/tracing/tracing_helper.py — opt-in tracing
wraps task/actor invocation and execution with spans and propagates the
trace context inside task metadata (:88-100). Re-designed without the
OpenTelemetry dependency: spans are written as Chrome-trace events to a
per-process JSONL file in the session log dir, and ``collect_spans``
merges them — the same file-based path the task timeline uses, so one
``chrome://tracing`` load shows both.

Propagation: when a span is active in the submitting process, a
``__trace_ctx__`` entry rides in the task's runtime_env; the executing
worker re-parents its spans under it (ambient context, like OTel's
context attach).
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_state = threading.local()
_enabled = False
_sink_path: Optional[str] = None
_sink_lock = threading.Lock()

TRACE_CTX_KEY = "__trace_ctx__"
TRACE_ENV_VAR = "RAY_TPU_TRACE"


def maybe_enable_from_env() -> bool:
    """Enable tracing when ``RAY_TPU_TRACE`` is set — how long-lived
    system actors (serve proxy/replicas) opt in without a driver-side
    call reaching their process. The env var propagates driver → node
    agent → worker with the rest of the cluster env."""
    if not _enabled and os.environ.get(TRACE_ENV_VAR, "").lower() in ("1", "true", "on"):
        enable_tracing(os.environ.get("RAY_TPU_SESSION_DIR") or None)
    return _enabled


def enable_tracing(session_dir: Optional[str] = None):
    """Turn on span recording in this process (reference:
    ``ray.init(_tracing_startup_hook=...)`` opt-in)."""
    global _enabled, _sink_path
    _enabled = True
    if session_dir is None:
        from ray_tpu.core import api

        session_dir = getattr(api, "_session_dir", None) or "/tmp/ray_tpu"
    logs = os.path.join(session_dir, "logs")
    os.makedirs(logs, exist_ok=True)
    _sink_path = os.path.join(logs, f"spans-{os.getpid()}.jsonl")


def disable_tracing():
    """Stop span recording in this process (tests)."""
    global _enabled, _sink_path
    _enabled = False
    _sink_path = None


def tracing_enabled() -> bool:
    return _enabled


def _write(rec: Dict[str, Any]):
    if _sink_path is None:
        return
    try:
        with _sink_lock:
            with open(_sink_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec) + "\n")
    except (OSError, ValueError):
        # Telemetry must never take down the traced path: a full disk or
        # removed session dir silently drops spans (the sink is
        # best-effort by design; spans also close inside engine pump
        # threads and request finally blocks).
        pass


def current_context() -> Optional[Dict[str, str]]:
    """The active trace context, for injection into task metadata."""
    span = getattr(_state, "span", None)
    if span is None:
        return None
    return {"trace_id": span["trace_id"], "parent_id": span["span_id"]}


def attach_context(ctx: Optional[Dict[str, str]]):
    """Adopt a propagated context as the ambient parent (worker side)."""
    if ctx:
        _state.span = {
            "trace_id": ctx["trace_id"],
            "span_id": ctx["parent_id"],
            "name": "<remote-parent>",
        }


def inject_runtime_env(runtime_env: Optional[dict]) -> Optional[dict]:
    """Return runtime_env with the active trace context injected (no-op
    when tracing is off or no span is active)."""
    if not _enabled:
        return runtime_env
    ctx = current_context()
    if ctx is None:
        return runtime_env
    runtime_env = dict(runtime_env or {})
    runtime_env[TRACE_CTX_KEY] = ctx
    return runtime_env


def detach_context():
    """Clear the ambient context (end of a traced task execution) so a
    long-lived worker thread doesn't mis-parent later unrelated work."""
    _state.span = None


@contextlib.contextmanager
def start_span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """Record one span; nested spans parent automatically."""
    if not _enabled:
        yield None
        return
    parent = getattr(_state, "span", None)
    span = {
        "trace_id": parent["trace_id"] if parent else uuid.uuid4().hex[:16],
        "span_id": uuid.uuid4().hex[:16],
        "parent_id": parent["span_id"] if parent else None,
        "name": name,
    }
    _state.span = span
    t0 = time.time()
    try:
        yield span
    finally:
        _write(
            {
                "name": name,
                "cat": "span",
                "ph": "X",  # Chrome trace "complete" event
                "ts": t0 * 1e6,
                "dur": (time.time() - t0) * 1e6,
                "pid": os.getpid(),
                "tid": threading.get_ident() % 100000,
                "args": {
                    **(attributes or {}),
                    "trace_id": span["trace_id"],
                    "span_id": span["span_id"],
                    "parent_id": span["parent_id"],
                },
            }
        )
        _state.span = parent


def record_span(
    name: str,
    start_ts: float,
    end_ts: float,
    ctx: Optional[Dict[str, str]] = None,
    attributes: Optional[Dict[str, Any]] = None,
):
    """Write one completed span explicitly parented under ``ctx`` (a
    ``current_context()`` capture). For cross-thread work — e.g. the LLM
    engine's pump thread finishing a request submitted from a replica
    handler thread — where the ambient thread-local parent can't flow."""
    if not _enabled:
        return
    span_id = uuid.uuid4().hex[:16]
    trace_id = ctx["trace_id"] if ctx else uuid.uuid4().hex[:16]
    parent_id = ctx["parent_id"] if ctx else None
    _write(
        {
            "name": name,
            "cat": "span",
            "ph": "X",
            "ts": start_ts * 1e6,
            "dur": max(0.0, end_ts - start_ts) * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident() % 100000,
            "args": {
                **(attributes or {}),
                "trace_id": trace_id,
                "span_id": span_id,
                "parent_id": parent_id,
            },
        }
    )


def trace_span(name: Optional[str] = None):
    """Decorator form of ``start_span``."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with start_span(name or fn.__qualname__):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def collect_spans(session_dir: str) -> List[dict]:
    """Merge every process's span file into one Chrome-trace event list."""
    events: List[dict] = []
    logs = os.path.join(session_dir, "logs")
    if not os.path.isdir(logs):
        return events
    for fname in sorted(os.listdir(logs)):
        if not (fname.startswith("spans-") and fname.endswith(".jsonl")):
            continue
        with open(os.path.join(logs, fname), encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        events.append(json.loads(line))
                    except json.JSONDecodeError:
                        pass
    return events
