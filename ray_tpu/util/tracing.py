"""Distributed tracing: spans with cross-task context propagation.

Reference: python/ray/util/tracing/tracing_helper.py — opt-in tracing
wraps task/actor invocation and execution with spans and propagates the
trace context inside task metadata (:88-100). Re-designed without the
OpenTelemetry dependency: spans are written as Chrome-trace events to a
per-process JSONL file in the session log dir, and ``collect_spans``
merges them — the same file-based path the task timeline uses, so one
``chrome://tracing`` load shows both.

Propagation: when a span is active in the submitting process, a
``__trace_ctx__`` entry rides in the task's runtime_env; the executing
worker re-parents its spans under it (ambient context, like OTel's
context attach).
"""
from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_state = threading.local()
_enabled = False
_sink_path: Optional[str] = None
_sink_lock = threading.Lock()
# Sink bound (single rotation): when the JSONL file would exceed the cap
# it is renamed to <path>.1 (overwriting any previous rotation) and a
# fresh file starts — long RAY_TPU_TRACE=1 runs keep at most 2x the cap
# per process instead of growing without limit.
_sink_bytes = 0
_max_sink_bytes = 0
# Threads whose thread_name metadata has been written to the CURRENT
# sink file (guarded by _sink_lock; cleared on rotation so the fresh
# file is self-describing).
_named_tids: set = set()

TRACE_CTX_KEY = "__trace_ctx__"
TRACE_ENV_VAR = "RAY_TPU_TRACE"
TRACE_MAX_MB_VAR = "RAY_TPU_TRACE_MAX_MB"  # per-process sink cap (default 64)


def maybe_enable_from_env() -> bool:
    """Enable tracing when ``RAY_TPU_TRACE`` is set — how long-lived
    system actors (serve proxy/replicas) opt in without a driver-side
    call reaching their process. The env var propagates driver → node
    agent → worker with the rest of the cluster env."""
    if not _enabled and os.environ.get(TRACE_ENV_VAR, "").lower() in ("1", "true", "on"):
        enable_tracing(os.environ.get("RAY_TPU_SESSION_DIR") or None)
    return _enabled


def enable_tracing(session_dir: Optional[str] = None):
    """Turn on span recording in this process (reference:
    ``ray.init(_tracing_startup_hook=...)`` opt-in)."""
    global _enabled, _sink_path, _sink_bytes, _max_sink_bytes
    _enabled = True
    if session_dir is None:
        from ray_tpu.core import api

        session_dir = getattr(api, "_session_dir", None) or "/tmp/ray_tpu"
    logs = os.path.join(session_dir, "logs")
    os.makedirs(logs, exist_ok=True)
    try:
        cap_mb = float(os.environ.get(TRACE_MAX_MB_VAR, "64"))
    except ValueError:
        cap_mb = 64.0
    with _sink_lock:
        _sink_path = os.path.join(logs, f"spans-{os.getpid()}.jsonl")
        _max_sink_bytes = max(1, int(cap_mb * 1024 * 1024))
        try:
            _sink_bytes = os.path.getsize(_sink_path)
        except OSError:
            _sink_bytes = 0
        _named_tids.clear()


def disable_tracing():
    """Stop span recording in this process (tests)."""
    global _enabled, _sink_path, _sink_bytes
    _enabled = False
    with _sink_lock:
        _sink_path = None
        _sink_bytes = 0
        _named_tids.clear()


def tracing_enabled() -> bool:
    return _enabled


def _process_name() -> str:
    """Human label for this process's Chrome-trace row."""
    wid = os.environ.get("RAY_TPU_WORKER_ID", "")
    if wid:
        return f"worker-{wid[:8]}"
    argv = " ".join(sys.argv[:2])
    if "controller" in argv:
        return "controller"
    if "node_agent" in argv:
        return "node_agent"
    return f"driver-{os.getpid()}"


def _meta_event(name: str, tid: int, value: str) -> Dict[str, Any]:
    """Chrome-trace metadata ("ph":"M") event: process_name/thread_name
    records that label the pid/tid rows of merged timelines."""
    return {
        "name": name,
        "ph": "M",
        "ts": 0,
        "pid": os.getpid(),
        "tid": tid,
        "args": {"name": value},
    }


def _write(rec: Dict[str, Any]):
    global _sink_bytes
    if _sink_path is None:
        return
    lines = []
    tid = rec.get("tid")
    try:
        with _sink_lock:
            # Encoded bytes, not str length: the cap must track the real
            # file size even for multi-byte span names/args.
            line = (json.dumps(rec) + "\n").encode("utf-8")
            if _sink_bytes + len(line) > _max_sink_bytes and _sink_bytes > 0:
                # Single rotation: the previous half replaces any older
                # .1 file, so disk use is bounded at ~2x the cap.
                os.replace(_sink_path, _sink_path + ".1")
                _sink_bytes = 0
                _named_tids.clear()
            if not _named_tids:
                lines.append(
                    (json.dumps(_meta_event("process_name", 0, _process_name()))
                     + "\n").encode("utf-8")
                )
                _named_tids.add(0)
            if tid is not None and tid not in _named_tids:
                _named_tids.add(tid)
                lines.append(
                    (json.dumps(
                        _meta_event(
                            "thread_name", tid, threading.current_thread().name
                        )
                    ) + "\n").encode("utf-8")
                )
            lines.append(line)
            with open(_sink_path, "ab") as f:
                for ln in lines:
                    f.write(ln)
                    _sink_bytes += len(ln)
    except (OSError, ValueError):
        # Telemetry must never take down the traced path: a full disk or
        # removed session dir silently drops spans (the sink is
        # best-effort by design; spans also close inside engine pump
        # threads and request finally blocks).
        pass


def current_context() -> Optional[Dict[str, str]]:
    """The active trace context, for injection into task metadata."""
    span = getattr(_state, "span", None)
    if span is None:
        return None
    return {"trace_id": span["trace_id"], "parent_id": span["span_id"]}


def attach_context(ctx: Optional[Dict[str, str]]):
    """Adopt a propagated context as the ambient parent (worker side)."""
    if ctx:
        _state.span = {
            "trace_id": ctx["trace_id"],
            "span_id": ctx["parent_id"],
            "name": "<remote-parent>",
        }


def inject_runtime_env(runtime_env: Optional[dict]) -> Optional[dict]:
    """Return runtime_env with the active trace context injected (no-op
    when tracing is off or no span is active)."""
    if not _enabled:
        return runtime_env
    ctx = current_context()
    if ctx is None:
        return runtime_env
    runtime_env = dict(runtime_env or {})
    runtime_env[TRACE_CTX_KEY] = ctx
    return runtime_env


def detach_context():
    """Clear the ambient context (end of a traced task execution) so a
    long-lived worker thread doesn't mis-parent later unrelated work."""
    _state.span = None


@contextlib.contextmanager
def start_span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """Record one span; nested spans parent automatically."""
    if not _enabled:
        yield None
        return
    parent = getattr(_state, "span", None)
    span = {
        "trace_id": parent["trace_id"] if parent else uuid.uuid4().hex[:16],
        "span_id": uuid.uuid4().hex[:16],
        "parent_id": parent["span_id"] if parent else None,
        "name": name,
    }
    _state.span = span
    t0 = time.time()
    try:
        yield span
    finally:
        _write(
            {
                "name": name,
                "cat": "span",
                "ph": "X",  # Chrome trace "complete" event
                "ts": t0 * 1e6,
                "dur": (time.time() - t0) * 1e6,
                "pid": os.getpid(),
                "tid": threading.get_ident() % 100000,
                "args": {
                    **(attributes or {}),
                    "trace_id": span["trace_id"],
                    "span_id": span["span_id"],
                    "parent_id": span["parent_id"],
                },
            }
        )
        _state.span = parent


def record_span(
    name: str,
    start_ts: float,
    end_ts: float,
    ctx: Optional[Dict[str, str]] = None,
    attributes: Optional[Dict[str, Any]] = None,
):
    """Write one completed span explicitly parented under ``ctx`` (a
    ``current_context()`` capture). For cross-thread work — e.g. the LLM
    engine's pump thread finishing a request submitted from a replica
    handler thread — where the ambient thread-local parent can't flow."""
    if not _enabled:
        return
    span_id = uuid.uuid4().hex[:16]
    trace_id = ctx["trace_id"] if ctx else uuid.uuid4().hex[:16]
    parent_id = ctx["parent_id"] if ctx else None
    _write(
        {
            "name": name,
            "cat": "span",
            "ph": "X",
            "ts": start_ts * 1e6,
            "dur": max(0.0, end_ts - start_ts) * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident() % 100000,
            "args": {
                **(attributes or {}),
                "trace_id": trace_id,
                "span_id": span_id,
                "parent_id": parent_id,
            },
        }
    )


def trace_span(name: Optional[str] = None):
    """Decorator form of ``start_span``."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with start_span(name or fn.__qualname__):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def collect_spans(session_dir: str) -> List[dict]:
    """Merge every process's span file (rotated ``.jsonl.1`` halves
    included) into one Chrome-trace event list."""
    events: List[dict] = []
    logs = os.path.join(session_dir, "logs")
    if not os.path.isdir(logs):
        return events
    for fname in sorted(os.listdir(logs)):
        if not (
            fname.startswith("spans-")
            and (fname.endswith(".jsonl") or fname.endswith(".jsonl.1"))
        ):
            continue
        with open(os.path.join(logs, fname), encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        events.append(json.loads(line))
                    except json.JSONDecodeError:
                        pass
    return events
