"""XLA compilation tracking: count compiles, time them, detect storms.

Reference shape: Ray's dashboard counts GPU kernel launches per process;
the TPU/JAX analogue is XLA compilation — a silent recompile storm (a
jit'd function re-lowering every step because a shape or static arg
changes) turns a 5 ms step into a 30 s one with no error anywhere.

Three hooks, all install-once per process:
- ``jax.monitoring`` duration events — every ``backend_compile`` adds to
  ``jax_compilations_total`` / ``jax_compile_seconds_total``.
- ``jax.monitoring`` plain events — persistent-compilation-cache
  hits/misses (``jax_compile_cache_{hits,misses}_total``).
- a logging.Handler on ``jax._src.interpreters.pxla`` (the
  "Compiling <fn> with global shapes and types [...]" DEBUG line) —
  the only place jax exposes the FUNCTION NAME and argument shapes, which
  is what storm detection needs: N compiles of the same name inside a
  window flags a storm, and the last two shape strings are kept so the
  offending diff is visible through the state API and a warning log.

Everything no-ops (and imports nothing heavy) until ``install()`` /
``maybe_install()`` runs; ``maybe_install`` is called by the process
telemetry loop once jax appears in ``sys.modules``.
"""
from __future__ import annotations

import collections
import logging
import re
import sys
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger("ray_tpu.compile_tracker")

_lock = threading.Lock()
_installed = False

# Raw totals (kept separately from the metrics Counters so snapshot()
# works without a metrics flush and in processes with no cluster).
_totals = {
    "compiles": 0,
    "compile_seconds": 0.0,
    "cache_hits": 0,
    "cache_misses": 0,
    "storms": 0,
}
# per-function compile history: name -> deque[(ts, shapes_str)]
_history: Dict[str, "collections.deque"] = {}
# per-function storm records: name -> {first_ts, last_ts, count, shapes, prev_shapes}
_storms: Dict[str, dict] = {}
# functions the health plane pinned into shape bucketing (storm actuator):
# workloads consult is_pinned()/maybe_bucket() to pad dynamic dims.
_pinned: set = set()
_metrics = None  # lazy _CompileMetrics
_storm_threshold = 5
_storm_window_s = 60.0
_MAX_TRACKED_FUNCTIONS = 256

_COMPILING_RE = re.compile(r"^Compiling ([^\s]+) with global shapes and types (.*?)\.?\s*(?:Argument mapping|$)")
_BACKEND_COMPILE = "backend_compile"


class _CompileMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter

        self.compiles = Counter(
            "jax_compilations_total", "XLA backend compilations in this process"
        )
        self.seconds = Counter(
            "jax_compile_seconds_total", "Seconds spent in XLA backend compilation"
        )
        self.cache_hits = Counter(
            "jax_compile_cache_hits_total", "Persistent compilation cache hits"
        )
        self.cache_misses = Counter(
            "jax_compile_cache_misses_total", "Persistent compilation cache misses"
        )
        self.storms = Counter(
            "jax_recompile_storms_total",
            "Recompilation storms detected (same function recompiled >= "
            "threshold times inside the window)",
        )


def _on_duration(event: str, duration: float, **kw):
    if _BACKEND_COMPILE not in event:
        return
    with _lock:
        _totals["compiles"] += 1
        _totals["compile_seconds"] += duration
    if _metrics is not None:
        _metrics.compiles.inc()
        _metrics.seconds.inc(max(0.0, duration))


def _on_event(event: str, **kw):
    if "cache_hit" in event:
        with _lock:
            _totals["cache_hits"] += 1
        if _metrics is not None:
            _metrics.cache_hits.inc()
    elif "cache_miss" in event:
        with _lock:
            _totals["cache_misses"] += 1
        if _metrics is not None:
            _metrics.cache_misses.inc()


class _PxlaHandler(logging.Handler):
    """Captures the per-compile "Compiling <fn> ..." line for names and
    shape strings. Attached with propagate=False on the pxla logger so
    forcing its level to DEBUG doesn't spray every compile line onto
    stderr through jax's own stream handler; records the user's OWN
    config would have emitted (prior effective level, e.g.
    jax_log_compiles' WARNING or an explicit DEBUG) are re-dispatched to
    the parent chain so install() never hides logs that were visible
    before it."""

    def __init__(self, prior_level: int, level=logging.DEBUG):
        super().__init__(level)
        self.prior_level = prior_level

    def emit(self, record: logging.LogRecord):
        try:
            if record.levelno >= self.prior_level:
                logging.getLogger("jax").handle(record)
            m = _COMPILING_RE.match(record.getMessage())
        except Exception:  # noqa: BLE001 — logging must never raise
            return
        if m is None:
            return
        _note_compile(m.group(1), m.group(2))


def _note_compile(name: str, shapes: str, now: Optional[float] = None):
    now = time.time() if now is None else now
    newly_storming = False
    prev_shapes = None
    with _lock:
        hist = _history.get(name)
        if hist is None:
            if len(_history) >= _MAX_TRACKED_FUNCTIONS:
                # drop the coldest function so a name explosion (lambdas)
                # can't grow without bound
                coldest = min(_history, key=lambda k: _history[k][-1][0])
                _history.pop(coldest, None)
            hist = _history[name] = collections.deque(maxlen=64)
        if hist:
            prev_shapes = hist[-1][1]
        hist.append((now, shapes))
        cutoff = now - _storm_window_s
        in_window = sum(1 for ts, _ in hist if ts >= cutoff)
        if in_window >= _storm_threshold:
            rec = _storms.get(name)
            if rec is None or now - rec["last_ts"] > _storm_window_s:
                newly_storming = True
                _totals["storms"] += 1
                _storms[name] = rec = {
                    "first_ts": now,
                    "count": 0,
                }
            rec.update(
                last_ts=now,
                count=rec["count"] + 1,
                window_count=in_window,
                shapes=shapes,
                prev_shapes=prev_shapes,
            )
    if newly_storming:
        if _metrics is not None:
            _metrics.storms.inc()
        logger.warning(
            "recompilation storm: %r compiled %d times in %.0fs — "
            "latest shapes %s (previous %s). A shape/static-arg is "
            "changing per call; pad/bucket inputs or hoist the jit.",
            name, in_window, _storm_window_s, shapes, prev_shapes,
        )
        try:
            from ray_tpu.util.profiling import incident

            incident(
                "recompile_storm",
                {"function": name, "window_count": in_window,
                 "shapes": shapes, "prev_shapes": prev_shapes},
            )
        except Exception as e:  # noqa: BLE001 — detection must survive capture failure
            logger.debug("storm incident capture failed: %s", e)


def install(storm_threshold: Optional[int] = None,
            storm_window_s: Optional[float] = None) -> bool:
    """Idempotent; returns True when the hooks are (now) installed.
    Requires jax to be importable — callers that must not trigger the
    import use :func:`maybe_install`."""
    global _installed, _metrics, _storm_threshold, _storm_window_s
    if storm_threshold is not None:
        _storm_threshold = int(storm_threshold)
    if storm_window_s is not None:
        _storm_window_s = float(storm_window_s)
    if _installed:
        return True
    try:
        import jax.monitoring as monitoring
    except Exception:  # noqa: BLE001 — no jax in this process
        return False
    with _lock:
        if _installed:
            return True
        _installed = True
    if _metrics is None:
        _metrics = _CompileMetrics()
    monitoring.register_event_duration_secs_listener(_on_duration)
    monitoring.register_event_listener(_on_event)
    pxla_logger = logging.getLogger("jax._src.interpreters.pxla")
    pxla_logger.addHandler(_PxlaHandler(prior_level=pxla_logger.getEffectiveLevel()))
    pxla_logger.setLevel(logging.DEBUG)
    pxla_logger.propagate = False
    return True


def maybe_install() -> bool:
    """install() only if jax is ALREADY imported (never triggers the
    multi-second TPU-runtime import from a control-plane process).
    Storm thresholds come from the cluster config the controller handed
    this process (per-init ``_system_config`` overrides apply), falling
    back to env/defaults when unconnected."""
    if _installed:
        return True
    if "jax" not in sys.modules:
        return False
    from ray_tpu.core import api

    core = api._global_worker
    if core is not None:
        threshold = core.config.get("compile_storm_threshold")
        window = core.config.get("compile_storm_window_s")
    else:
        from ray_tpu.config import get_config

        cfg = get_config()
        threshold = getattr(cfg, "compile_storm_threshold", None)
        window = getattr(cfg, "compile_storm_window_s", None)
    return install(storm_threshold=threshold, storm_window_s=window)


def pin_functions(names) -> dict:
    """Storm actuator target: mark ``names`` as shape-pinned in this
    process. Pinning changes no jax internals — it is advisory state the
    WORKLOAD consults via :func:`maybe_bucket` (pad a dynamic dim up to
    its power-of-2 bucket) or :func:`is_pinned` (choose a padded path).
    Returns the full pinned set so the actuator can audit it."""
    with _lock:
        for n in names or ():
            if isinstance(n, str) and n:
                _pinned.add(n)
        return {"pinned": sorted(_pinned)}


def is_pinned(name: str) -> bool:
    with _lock:
        return name in _pinned


def maybe_bucket(name: str, n: int) -> int:
    """Round ``n`` up to the next power of two IF the health plane pinned
    ``name`` (else return it unchanged). The storm-remediation contract:
    a recompile storm driven by a drifting dimension collapses to at most
    log2(max_n) compiles once the workload sizes through this."""
    if n <= 0 or not is_pinned(name):
        return n
    return 1 << (n - 1).bit_length()


def snapshot(max_functions: int = 20) -> dict:
    """Per-process compile stats for the state API / telemetry ship."""
    now = time.time()
    cutoff = now - _storm_window_s
    with _lock:
        funcs = {}
        for name, hist in _history.items():
            in_window = sum(1 for ts, _ in hist if ts >= cutoff)
            funcs[name] = {
                "count": len(hist),
                "window_count": in_window,
                "last_shapes": hist[-1][1] if hist else "",
            }
        top = dict(
            sorted(funcs.items(), key=lambda kv: -kv[1]["window_count"])[:max_functions]
        )
        return {
            "installed": _installed,
            "compiles": _totals["compiles"],
            "compile_seconds": round(_totals["compile_seconds"], 4),
            "cache_hits": _totals["cache_hits"],
            "cache_misses": _totals["cache_misses"],
            "storms_total": _totals["storms"],
            "storm_threshold": _storm_threshold,
            "storm_window_s": _storm_window_s,
            "active_storms": {
                name: dict(rec)
                for name, rec in _storms.items()
                if rec["last_ts"] >= cutoff
            },
            "pinned": sorted(_pinned),
            "functions": top,
        }


def _reset_for_tests():
    with _lock:
        _totals.update(compiles=0, compile_seconds=0.0, cache_hits=0,
                       cache_misses=0, storms=0)
        _history.clear()
        _storms.clear()
        _pinned.clear()
