"""On-demand distributed profiling: stack dumps, sampling CPU profiles,
attachable device traces, and incident auto-capture.

Reference: the dashboard reporter's py-spy endpoints (python/ray/dashboard/
modules/reporter/ — per-worker ``Stack Trace`` / ``CPU Flame Graph`` links)
and ``ray stack``. py-spy attaches to a pid from outside; here every
process profiles itself over its existing RPC channel, which works in
containers and needs no ptrace capability:

* **stack dumps** — :func:`dump_stacks` snapshots every thread
  (``sys._current_frames`` + thread names + held-lock annotations from the
  lockwatch watchdog). The controller fans ``dump_stacks`` out cluster-wide
  and :func:`merge_stack_dumps` deduplicates identical stacks across
  processes so a 100-worker dump reads as a handful of distinct states.
* **sampling CPU profiler** — :class:`CpuSampler` samples all threads at a
  bounded rate/duration, tags each sample with the task the executing
  thread is running (:func:`set_thread_task`, maintained by worker_main),
  and renders collapsed-stack text (:func:`collapsed_text`) or speedscope
  JSON (:func:`speedscope_json`). Busy/idle classification is leaf-frame
  based (a thread parked in ``wait``/``select``/``acquire`` is idle), and
  busy samples feed ``task_cpu_ms{name}`` through the metrics pipeline.
* **attachable device traces** — :func:`device_trace_start` /
  :func:`device_trace_stop` drive ``jax.profiler`` on an already-running
  process (no restart), writing into the same session ``profiles/`` root
  the runtime_env plugin uses so the existing list/fetch path applies.
* **incident auto-capture** — a continuous low-rate sampler
  (:class:`ContinuousSampler`, ``profiling_continuous_hz``) keeps a
  bounded ring of recent samples; detector hooks (lockwatch long-hold /
  order-cycle, recompile storms, serve SLO breaches) call
  :func:`incident` to flush stacks + the recent-sample ring + detector
  context into a bounded on-disk incident directory.

This module must import standalone (cheaply, no jax): workers, agents,
the controller, and drivers all load it at process start.
"""
from __future__ import annotations

import collections
import json
import logging
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("ray_tpu.profiling")

# ---------------------------------------------------------------------------
# Task attribution: executing thread -> current task/actor-method name.
# worker_main._run stamps this around every task execution so CPU samples
# (and stack dumps) can attribute threads to named work.
# ---------------------------------------------------------------------------
_task_tags: Dict[int, str] = {}


def set_thread_task(name: Optional[str]):
    """Tag THIS thread as executing ``name`` (None clears the tag)."""
    ident = threading.get_ident()
    if name:
        _task_tags[ident] = name
    else:
        _task_tags.pop(ident, None)


def thread_task_tags() -> Dict[int, str]:
    return dict(_task_tags)


# Leaf frames that mean "parked, not burning CPU" — the sampling profiler
# is a wall profiler (it sees blocked threads too, like py-spy --idle);
# busy/idle classification keeps task_cpu_ms honest.
_IDLE_LEAF_FUNCS = frozenset(
    {
        "wait", "wait_for", "sleep", "select", "poll", "epoll", "kevent",
        "accept", "accept4", "acquire", "join", "get", "park",
        "_recv_msg", "recv", "recv_into", "read", "readinto", "settrace",
        "channel_wait", "_wait_for_tstate_lock", "epoll_wait",
    }
)
_IDLE_LEAF_FILES = ("selectors.py", "threading.py", "queue.py", "ssl.py")


def _frame_stack(frame) -> Tuple[Tuple[str, int, str], ...]:
    """(file, line, func) tuples, LEAF FIRST (cheap f_back walk — no
    traceback machinery on the sampling hot path)."""
    out = []
    while frame is not None and len(out) < 128:
        code = frame.f_code
        out.append((code.co_filename, frame.f_lineno, code.co_name))
        frame = frame.f_back
    return tuple(out)


def _is_idle(frames: Tuple[Tuple[str, int, str], ...]) -> bool:
    if not frames:
        return True
    fname, _line, func = frames[0]
    if func in _IDLE_LEAF_FUNCS:
        return True
    return fname.endswith(_IDLE_LEAF_FILES)


def _frame_label(f: Tuple[str, int, str]) -> str:
    fname, line, func = f
    mod = os.path.basename(fname)
    if mod.endswith(".py"):
        mod = mod[:-3]
    return f"{mod}.{func}"


def process_label() -> str:
    """Human label for this process (mirrors tracing._process_name, but
    importable before a session exists)."""
    wid = os.environ.get("RAY_TPU_WORKER_ID", "")
    if wid:
        return f"worker-{wid[:8]}"
    argv = " ".join(sys.argv[:2])
    if "controller" in argv:
        return "controller"
    if "node_agent" in argv:
        return "node_agent"
    return f"driver-{os.getpid()}"


# ---------------------------------------------------------------------------
# Stack dumps
# ---------------------------------------------------------------------------
def dump_stacks() -> dict:
    """Structured snapshot of every thread in THIS process.

    Deliberately lock-free with respect to application state: it touches
    only ``sys._current_frames`` (GIL), the threading registry, and the
    lockwatch meta lock via a bounded-timeout acquire — so dumping a
    process that is deadlocked (or the controller mid-storm) always
    returns.
    """
    threads = {t.ident: t for t in threading.enumerate()}
    held = _lockwatch_held_snapshot()
    tags = thread_task_tags()
    rows = []
    for ident, frame in sys._current_frames().items():
        t = threads.get(ident)
        frames = _frame_stack(frame)
        rows.append(
            {
                "ident": ident,
                "name": t.name if t is not None else "?",
                "daemon": bool(t.daemon) if t is not None else None,
                "task": tags.get(ident),
                "idle": _is_idle(frames),
                # root-first for human reading (like traceback output)
                "frames": [
                    {"file": f, "line": ln, "func": fn}
                    for f, ln, fn in reversed(frames)
                ],
                "held_locks": held.get(ident, []),
            }
        )
    rows.sort(key=lambda r: r["name"])
    return {
        "process": process_label(),
        "pid": os.getpid(),
        "ts": time.time(),
        "threads": rows,
    }


def _lockwatch_held_snapshot() -> Dict[int, List[dict]]:
    try:
        from ray_tpu.util import lockwatch

        return lockwatch.held_snapshot()
    except Exception as e:  # noqa: BLE001 — dump must work without the watchdog
        logger.debug("lockwatch held snapshot unavailable: %s", e)
        return {}


def format_stacks(dump: dict) -> str:
    """One process's dump as text (``ray stack`` style)."""
    out = [f"process {dump.get('process', '?')} (pid {dump.get('pid', '?')})"]
    for t in dump.get("threads", ()):
        head = f"--- Thread {t['name']} (id {t['ident']})"
        if t.get("task"):
            head += f" [task {t['task']}]"
        if t.get("idle"):
            head += " [idle]"
        out.append(head + " ---")
        for lk in t.get("held_locks", ()):
            out.append(
                f"    holds {lk['lock']} (acquired {lk['acquired_at']}, "
                f"{lk['held_ms']:.0f} ms ago)"
            )
        for f in t.get("frames", ()):
            out.append(f"  {f['file']}:{f['line']} in {f['func']}")
    return "\n".join(out)


def merge_stack_dumps(dumps: Dict[str, Any]) -> str:
    """Cluster-wide merged report: threads with IDENTICAL stacks (across
    processes) collapse into one block listing every occurrence — the
    100-idle-workers case reads as one entry, and the one wedged actor
    stands out. ``dumps``: {process_name: dump dict | error string}."""
    groups: Dict[tuple, List[str]] = {}
    meta: Dict[tuple, dict] = {}
    errors: List[str] = []
    for proc, dump in sorted(dumps.items()):
        if not isinstance(dump, dict):
            errors.append(f"{proc}: {dump}")
            continue
        for t in dump.get("threads", ()):
            key = tuple((f["file"], f["func"]) for f in t.get("frames", ()))
            who = f"{proc} / {t['name']}"
            if t.get("task"):
                who += f" [task {t['task']}]"
            for lk in t.get("held_locks", ()):
                who += f" (holds {lk['lock']} {lk['held_ms']:.0f}ms)"
            groups.setdefault(key, []).append(who)
            if key not in meta:
                meta[key] = t
    out = []
    for key, whos in sorted(groups.items(), key=lambda kv: -len(kv[1])):
        t = meta[key]
        out.append(f"== {len(whos)} thread(s) ==")
        for who in whos[:20]:
            out.append(f"  {who}")
        if len(whos) > 20:
            out.append(f"  ... and {len(whos) - 20} more")
        for f in t.get("frames", ()):
            out.append(f"    {f['file']}:{f['line']} in {f['func']}")
        out.append("")
    for err in errors:
        out.append(f"!! unavailable: {err}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Sampling CPU profiler
# ---------------------------------------------------------------------------
_metrics = None


def _get_metrics():
    """Lazy metric singletons (this module imports before a session)."""
    global _metrics
    if _metrics is None:
        from ray_tpu.util.metrics import Counter, Histogram

        _metrics = {
            "samples": Counter(
                "profiling_samples_total",
                "CPU profiler samples taken in this process",
                ("mode",),
            ),
            "task_cpu": Histogram(
                "task_cpu_ms",
                "Sampled busy CPU time attributed to named tasks per "
                "profiling window",
                boundaries=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
                            5000, 15000, 60000),
                tag_keys=("name",),
            ),
            "incidents": Counter(
                "profiling_incidents_total",
                "Incident capture bundles written, by detector trigger",
                ("trigger",),
            ),
        }
    return _metrics


class CpuSampler:
    """Threading-based sampling profiler over ``sys._current_frames``.

    Bounded by construction: fixed rate, fixed max unique stacks, and the
    run loop exits at ``duration_s`` even if nobody calls :meth:`stop`.
    Aggregates in-memory (stack -> count); a 10 s @ 100 Hz profile of a
    50-thread process stays well under a megabyte.
    """

    MAX_UNIQUE_STACKS = 10000

    def __init__(self, hz: float = 100.0, duration_s: Optional[float] = None,
                 mode: str = "on_demand"):
        self.hz = max(1.0, min(float(hz), 1000.0))
        self.duration_s = duration_s
        self.mode = mode
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # (thread, task, (file, func) frames leaf-first)
        #   -> [count, busy_count, representative frames with lines]
        self.stacks: Dict[tuple, list] = {}
        self.task_busy: Dict[str, int] = {}
        self.samples_total = 0
        self.started_at = 0.0
        self.stopped_at = 0.0

    # -- control -------------------------------------------------------
    def start(self):
        self.started_at = time.time()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="cpu-sampler"
        )
        self._thread.start()
        return self

    def stop(self) -> dict:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self.stopped_at = time.time()
        self._flush_metrics()
        return self.result()

    def _run(self):
        interval = 1.0 / self.hz
        deadline = (
            time.monotonic() + self.duration_s if self.duration_s else None
        )
        my_ident = threading.get_ident()
        while not self._stop.is_set():
            if deadline is not None and time.monotonic() >= deadline:
                break
            t0 = time.monotonic()
            self._sample_once(my_ident)
            elapsed = time.monotonic() - t0
            self._stop.wait(max(0.001, interval - elapsed))

    def _sample_once(self, skip_ident: int):
        names = {t.ident: t.name for t in threading.enumerate()}
        tags = thread_task_tags()
        for ident, frame in sys._current_frames().items():
            if ident == skip_ident:
                continue
            frames = _frame_stack(frame)
            busy = not _is_idle(frames)
            task = tags.get(ident)
            # Aggregation key drops line numbers (the collapsed/speedscope
            # output is function-level anyway): a hot function sampled at
            # many lines must not fan out into thousands of unique stacks.
            key = (
                names.get(ident, "?"), task,
                tuple((f, fn) for f, _ln, fn in frames),
            )
            st = self.stacks.get(key)
            if st is None and len(self.stacks) < self.MAX_UNIQUE_STACKS:
                st = self.stacks[key] = [0, 0, frames]
            if st is not None:
                st[0] += 1
                if busy:
                    st[1] += 1
            # totals and task attribution count even past the unique-
            # stack cap — only the per-stack row is dropped
            if busy and task:
                self.task_busy[task] = self.task_busy.get(task, 0) + 1
            self.samples_total += 1

    # -- results -------------------------------------------------------
    def _flush_metrics(self):
        try:
            m = _get_metrics()
            if self.samples_total:
                m["samples"].inc(self.samples_total, {"mode": self.mode})
            ms_per = 1000.0 / self.hz
            for name, busy in self.task_busy.items():
                # task names are app-bounded; the registry cardinality cap
                # (metrics_max_series_per_metric) backstops misbehavers
                m["task_cpu"].observe(busy * ms_per, {"name": name})  # ray-tpu: lint-ignore[RTL004]
        except Exception as e:  # noqa: BLE001 — profiling must not kill the host process
            logger.debug("profiler metric flush failed: %s", e)

    def result(self) -> dict:
        ms_per = 1000.0 / self.hz
        rows = []
        for (tname, task, _key), (count, busy, frames) in sorted(
            self.stacks.items(), key=lambda kv: -kv[1][0]
        ):
            rows.append(
                {
                    "thread": tname,
                    "task": task,
                    "count": count,
                    "busy": busy,
                    # root-first labels, collapsed-stack ready
                    "frames": [_frame_label(f) for f in reversed(frames)],
                }
            )
        return {
            "process": process_label(),
            "pid": os.getpid(),
            "hz": self.hz,
            "duration_s": round(
                (self.stopped_at or time.time()) - self.started_at, 3
            ),
            "samples": self.samples_total,
            "ms_per_sample": ms_per,
            "task_cpu_ms": {
                k: round(v * ms_per, 1) for k, v in self.task_busy.items()
            },
            "stacks": rows,
        }


async def sample_async(duration_s: float, hz: float = 100.0) -> dict:
    """Profile THIS process for ``duration_s`` without blocking the
    caller's event loop (the sampler runs on its own thread; the handler
    just sleeps). Shared by the worker/agent RPC handlers and the
    controller's self-profile leg."""
    import asyncio

    duration_s = max(0.05, min(float(duration_s), 600.0))
    sampler = CpuSampler(hz=hz, duration_s=duration_s).start()
    await asyncio.sleep(duration_s)
    return sampler.stop()


def merge_cpu_results(results: Dict[str, Any]) -> dict:
    """Fan-out rollup: per-process results keyed by process name ->
    cluster-wide collapsed counts, task attribution, and totals."""
    collapsed: Dict[str, int] = {}
    task_cpu: Dict[str, float] = {}
    samples = 0
    procs: Dict[str, Any] = {}
    errors: Dict[str, str] = {}
    for proc, res in results.items():
        if not isinstance(res, dict):
            errors[proc] = str(res)
            continue
        procs[proc] = {
            "samples": res.get("samples", 0),
            "duration_s": res.get("duration_s"),
            "task_cpu_ms": res.get("task_cpu_ms", {}),
        }
        samples += res.get("samples", 0)
        for name, ms in res.get("task_cpu_ms", {}).items():
            task_cpu[name] = round(task_cpu.get(name, 0.0) + ms, 1)
        for row in res.get("stacks", ()):
            line = ";".join([proc] + row["frames"])
            collapsed[line] = collapsed.get(line, 0) + row["count"]
    return {
        "samples": samples,
        "task_cpu_ms": dict(
            sorted(task_cpu.items(), key=lambda kv: -kv[1])
        ),
        "collapsed": collapsed,
        "procs": procs,
        "errors": errors,
    }


def collapsed_text(merged: dict) -> str:
    """Brendan-Gregg collapsed-stack text (``flamegraph.pl`` /
    speedscope-importable): one ``frame;frame;... count`` line per stack."""
    return "\n".join(
        f"{stack} {count}"
        for stack, count in sorted(
            merged.get("collapsed", {}).items(), key=lambda kv: -kv[1]
        )
    )


def speedscope_json(merged: dict, name: str = "ray-tpu cpu profile",
                    ms_per_sample: float = 10.0) -> dict:
    """speedscope file-format JSON (sampled profile) from a merged
    result — one profile, each unique stack contributing one weighted
    sample (https://www.speedscope.app/file-format-schema.json)."""
    frame_index: Dict[str, int] = {}
    frames: List[dict] = []
    samples: List[List[int]] = []
    weights: List[float] = []
    for stack, count in merged.get("collapsed", {}).items():
        idxs = []
        for label in stack.split(";"):
            i = frame_index.get(label)
            if i is None:
                i = frame_index[label] = len(frames)
                frames.append({"name": label})
            idxs.append(i)
        samples.append(idxs)
        weights.append(count * ms_per_sample)
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "name": name,
        "exporter": "ray-tpu profile cpu",
        "shared": {"frames": frames},
        "profiles": [
            {
                "type": "sampled",
                "name": name,
                "unit": "milliseconds",
                "startValue": 0,
                "endValue": total,
                "samples": samples,
                "weights": weights,
            }
        ],
    }


# ---------------------------------------------------------------------------
# Continuous low-rate sampling + incident auto-capture
# ---------------------------------------------------------------------------
class ContinuousSampler:
    """Always-on low-rate sampler feeding a bounded ring of recent
    samples — the flight recorder for CPU time. Default OFF
    (``profiling_continuous_hz = 0``); at the recommended 5-20 Hz the
    measured overhead on the CPU micro-bench is well under the 3% budget
    (bench.py ``profiling_overhead_pct``)."""

    MAX_RING = 50000

    def __init__(self, hz: float, ring_s: float = 60.0):
        self.hz = max(0.1, min(float(hz), 100.0))
        self.ring_s = ring_s
        maxlen = min(self.MAX_RING, max(256, int(self.hz * ring_s * 8)))
        # (ts, thread_name, task, frames leaf-first, busy)
        self.ring: "collections.deque" = collections.deque(maxlen=maxlen)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._task_busy: Dict[str, int] = {}
        self._samples_since_flush = 0
        self._last_flush = time.monotonic()
        self._FLUSH_S = 10.0

    def start(self):
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="cpu-sampler-continuous"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self._flush_metrics(time.monotonic())

    def _run(self):
        interval = 1.0 / self.hz
        my_ident = threading.get_ident()
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self._sample_once(my_ident)
            except Exception as e:  # noqa: BLE001 — sampler must never die
                logger.debug("continuous sample failed: %s", e)
            if t0 - self._last_flush >= self._FLUSH_S:
                self._flush_metrics(t0)
            elapsed = time.monotonic() - t0
            self._stop.wait(max(0.005, interval - elapsed))

    def _sample_once(self, skip_ident: int):
        now = time.time()
        names = {t.ident: t.name for t in threading.enumerate()}
        tags = thread_task_tags()
        for ident, frame in sys._current_frames().items():
            if ident == skip_ident:
                continue
            frames = _frame_stack(frame)
            busy = not _is_idle(frames)
            task = tags.get(ident)
            self.ring.append((now, names.get(ident, "?"), task, frames, busy))
            if busy and task:
                self._task_busy[task] = self._task_busy.get(task, 0) + 1
            self._samples_since_flush += 1

    def _flush_metrics(self, now_m: float):
        self._last_flush = now_m
        busy, self._task_busy = self._task_busy, {}
        n, self._samples_since_flush = self._samples_since_flush, 0
        try:
            m = _get_metrics()
            if n:
                m["samples"].inc(n, {"mode": "continuous"})
            ms_per = 1000.0 / self.hz
            for name, count in busy.items():
                m["task_cpu"].observe(count * ms_per, {"name": name})  # ray-tpu: lint-ignore[RTL004] — app-bounded task names, registry cap backstops
        except Exception as e:  # noqa: BLE001 — profiling must not kill the host
            logger.debug("continuous metric flush failed: %s", e)

    def recent_collapsed(self, seconds: Optional[float] = None) -> str:
        """Aggregate the ring's newest ``seconds`` into collapsed text
        (the incident bundle's ``samples.collapsed``)."""
        cutoff = time.time() - (seconds or self.ring_s)
        counts: Dict[str, int] = {}
        for ts, tname, task, frames, _busy in list(self.ring):
            if ts < cutoff:
                continue
            label = f"{tname}[{task}]" if task else tname
            line = ";".join([label] + [_frame_label(f) for f in reversed(frames)])
            counts[line] = counts.get(line, 0) + 1
        return "\n".join(
            f"{line} {n}"
            for line, n in sorted(counts.items(), key=lambda kv: -kv[1])
        )


_continuous: Optional[ContinuousSampler] = None
_continuous_lock = threading.Lock()


def ensure_continuous(hz: Optional[float] = None,
                      ring_s: Optional[float] = None) -> Optional[ContinuousSampler]:
    """Start the process-wide continuous sampler if configured
    (``profiling_continuous_hz`` > 0). Idempotent; called from every
    process entry point alongside telemetry startup."""
    global _continuous
    if hz is None:
        hz = float(_config_value("profiling_continuous_hz", 0.0))
    if ring_s is None:
        ring_s = float(_config_value("profiling_ring_s", 60.0))
    if hz <= 0:
        return _continuous
    with _continuous_lock:
        if _continuous is None:
            _continuous = ContinuousSampler(hz, ring_s).start()
    return _continuous


def continuous_sampler() -> Optional[ContinuousSampler]:
    return _continuous


def _stop_continuous_for_tests():
    global _continuous
    with _continuous_lock:
        if _continuous is not None:
            _continuous.stop()
            _continuous = None


def _config_value(name: str, default):
    """Config lookup preferring the cluster config this process was
    handed at registration (per-init ``_system_config`` overrides apply),
    like compile_tracker.maybe_install."""
    try:
        from ray_tpu.core import api

        core = api._global_worker
        if core is not None:
            return core.config.get(name, default)
        from ray_tpu.config import get_config

        return getattr(get_config(), name, default)
    except Exception:  # noqa: BLE001 — config unavailable (odd embedders)
        return default


# ---------------------------------------------------------------------------
# Incident auto-capture
# ---------------------------------------------------------------------------
# Bounded trigger vocabulary — these become metric tags and directory
# name prefixes.
INCIDENT_TRIGGERS = (
    "lockwatch_long_hold",
    "lockwatch_cycle",
    "recompile_storm",
    "slo_breach",
    "memory_pressure",
    "memory_leak",
    "error_spike",
    "manual",
)

_incident_last: Dict[str, float] = {}
_incident_lock = threading.Lock()
# Flight-recorder tail provider: the controller registers a callable
# returning recent lifecycle events so ITS incident bundles carry the
# scheduler context (workers have no recorder).
_recorder_tail_provider = None


def set_recorder_tail_provider(fn):
    global _recorder_tail_provider
    _recorder_tail_provider = fn


def incidents_root(session_dir: Optional[str] = None) -> str:
    session_dir = session_dir or _session_dir()
    return os.path.join(session_dir, "incidents")


def _session_dir() -> str:
    sd = os.environ.get("RAY_TPU_SESSION_DIR")
    if sd:
        return sd
    try:
        from ray_tpu.core import api

        if api._global_worker is not None:
            return api._global_worker.session_dir
        if api._session_dir:
            return api._session_dir
    except Exception as e:  # noqa: BLE001 — no session in this process
        logger.debug("no session dir for incidents: %s", e)
    return ""


def incident(trigger: str, detail: Optional[dict] = None,
             extra_files: Optional[Dict[str, str]] = None) -> Optional[str]:
    """Write one incident capture bundle; returns its directory (or None
    when disabled/rate-limited/sessionless). Bundle contents:

    - ``meta.json``    — trigger, detail, process, pid, timestamps
    - ``stacks.txt``   — full formatted stack dump of this process
    - ``samples.collapsed`` — recent continuous-sampler ring (if running)
    - ``lifecycle_tail.json`` — flight-recorder tail (controller only)
    - any ``extra_files`` the detector supplies ({name: text} — e.g. the
      store-pressure trigger's ``memory.json`` autopsy)

    Bounded on disk: newest ``profiling_incident_keep`` bundles are kept
    per incidents dir; per-trigger writes are rate-limited to one per
    ``profiling_incident_min_interval_s``. Never raises.
    """
    try:
        if trigger not in INCIDENT_TRIGGERS:
            trigger = "manual"
        if not _config_value("profiling_incidents", True):
            return None
        session_dir = _session_dir()
        if not session_dir:
            return None
        min_interval = float(
            _config_value("profiling_incident_min_interval_s", 30.0)
        )
        now = time.time()
        with _incident_lock:
            if now - _incident_last.get(trigger, 0.0) < min_interval:
                return None
            _incident_last[trigger] = now
        root = incidents_root(session_dir)
        iid = f"{trigger}-{int(now * 1000)}-{os.getpid()}"
        d = os.path.join(root, iid)
        os.makedirs(d, exist_ok=True)
        meta = {
            "id": iid,
            "trigger": trigger,
            "detail": detail or {},
            "process": process_label(),
            "pid": os.getpid(),
            "ts": now,
        }
        with open(os.path.join(d, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1, default=str)
        with open(os.path.join(d, "stacks.txt"), "w") as f:
            f.write(format_stacks(dump_stacks()))
        cont = _continuous
        if cont is not None:
            samples = cont.recent_collapsed()
            if samples:
                with open(os.path.join(d, "samples.collapsed"), "w") as f:
                    f.write(samples)
        if _recorder_tail_provider is not None:
            try:
                tail = _recorder_tail_provider()
                with open(os.path.join(d, "lifecycle_tail.json"), "w") as f:
                    json.dump(tail, f, default=str)
            except Exception as e:  # noqa: BLE001 — tail is best-effort context
                logger.debug("recorder tail capture failed: %s", e)
        for name, text in (extra_files or {}).items():
            safe_name = os.path.basename(str(name)) or "extra.txt"
            try:
                with open(os.path.join(d, safe_name), "w") as f:
                    f.write(text)
            except OSError as e:
                logger.debug("incident extra file %s failed: %s", safe_name, e)
        _prune_incidents(root)
        try:
            _get_metrics()["incidents"].inc(1, {"trigger": trigger})
        except Exception as e:  # noqa: BLE001 — metrics may be unavailable
            logger.debug("incident metric failed: %s", e)
        logger.warning("incident captured: %s -> %s", trigger, d)
        return d
    except Exception as e:  # noqa: BLE001 — detectors must survive capture failure
        logger.debug("incident capture failed: %s", e)
        return None


def _prune_incidents(root: str):
    keep = int(_config_value("profiling_incident_keep", 20))
    try:
        entries = sorted(
            (e for e in os.listdir(root)
             if os.path.isdir(os.path.join(root, e)))
        )
    except OSError:
        return
    # ids embed epoch-ms, but prefixes differ — order by the embedded ts
    def _ts(e: str) -> int:
        parts = e.rsplit("-", 2)
        try:
            return int(parts[-2])
        except (ValueError, IndexError):
            return 0

    entries.sort(key=_ts)
    import shutil

    for e in entries[:-keep] if keep > 0 else entries:
        try:
            shutil.rmtree(os.path.join(root, e))
        except OSError as err:
            logger.debug("incident prune failed for %s: %s", e, err)


def list_incidents(session_dir: Optional[str] = None) -> List[dict]:
    """Rows: {id, trigger, ts, process, pid, path, files}."""
    root = incidents_root(session_dir)
    rows = []
    if not os.path.isdir(root):
        return rows
    for entry in sorted(os.listdir(root)):
        d = os.path.join(root, entry)
        if not os.path.isdir(d):
            continue
        row = {"id": entry, "path": d}
        meta_path = os.path.join(d, "meta.json")
        try:
            with open(meta_path) as f:
                row.update(json.load(f))
        except (OSError, ValueError) as e:
            logger.debug("unreadable incident meta %s: %s", meta_path, e)
        try:
            row["files"] = sorted(os.listdir(d))
        except OSError:
            row["files"] = []
        rows.append(row)
    rows.sort(key=lambda r: r.get("ts", 0))
    return rows


def get_incident(incident_id: str, session_dir: Optional[str] = None) -> dict:
    root = os.path.realpath(incidents_root(session_dir))
    d = os.path.realpath(os.path.join(root, incident_id))
    if os.path.commonpath([d, root]) != root:
        raise ValueError("incident path escapes the incidents dir")
    if not os.path.isdir(d):
        raise FileNotFoundError(f"no incident {incident_id!r}")
    row = {"id": incident_id, "path": d}
    try:
        with open(os.path.join(d, "meta.json")) as f:
            row.update(json.load(f))
    except (OSError, ValueError) as e:
        logger.debug("unreadable incident meta for %s: %s", incident_id, e)
    out = {}
    for name in sorted(os.listdir(d)):
        p = os.path.join(d, name)
        try:
            with open(p, errors="replace") as f:
                out[name] = f.read(1 << 20)
        except OSError as e:
            out[name] = f"<unreadable: {e}>"
    row["contents"] = out
    return row


def slo_breach_check(metric: str, value_ms: float):
    """Serve SLO hook: a TTFT observation past ``profiling_slo_ttft_ms``
    (0 = disabled) triggers an incident capture with the breach context.
    The capture itself (stack dump + ring aggregation + file writes)
    runs on a background thread — it must not stall the very request
    that was just flagged as too slow. The rate limiter is pre-checked
    here so a breach storm doesn't spawn a thread per request (and
    re-checked atomically inside :func:`incident`)."""
    threshold = float(_config_value("profiling_slo_ttft_ms", 0.0))
    if threshold <= 0 or value_ms <= threshold:
        return
    min_interval = float(_config_value("profiling_incident_min_interval_s", 30.0))
    if time.time() - _incident_last.get("slo_breach", 0.0) < min_interval:
        return
    threading.Thread(
        target=incident,
        args=("slo_breach",
              {"metric": metric, "value_ms": round(value_ms, 1),
               "threshold_ms": threshold}),
        daemon=True,
        name="incident-capture",
    ).start()


# ---------------------------------------------------------------------------
# Attachable device traces (jax.profiler on a live process)
# ---------------------------------------------------------------------------
_device_trace_lock = threading.Lock()
_device_trace: Optional[dict] = None  # {"dir", "capture", "t0"}


def device_trace_start(capture: str, base_dir: Optional[str] = None) -> dict:
    """Start a ``jax.profiler`` trace in THIS process (no restart —
    composes with the runtime_env plugin's capture dirs and the existing
    list/fetch path). One trace at a time per process."""
    global _device_trace
    safe = "".join(c if c.isalnum() or c in "._-" else "_" for c in capture)[:64]
    try:
        import jax
    except Exception as e:  # noqa: BLE001 — CPU-only / jax-less process
        return {"ok": False, "error": f"jax unavailable: {e}"}
    with _device_trace_lock:
        if _device_trace is not None:
            return {
                "ok": False,
                "error": f"trace already running ({_device_trace['capture']})",
            }
        from ray_tpu.runtime_env.jax_profiler import profiles_root

        out_dir = os.path.join(
            base_dir or profiles_root(_session_dir() or None),
            f"{safe}-pid{os.getpid()}",
        )
        os.makedirs(out_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(out_dir)
        except Exception as e:  # noqa: BLE001 — backend may not support tracing
            return {"ok": False, "error": f"start_trace failed: {e}"}
        _device_trace = {"dir": out_dir, "capture": safe, "t0": time.time()}
        return {"ok": True, "dir": out_dir}


def device_trace_stop() -> dict:
    """Stop the running trace and write the same ``profile.json`` meta
    the per-task runtime_env capture writes (so ``ray-tpu profile
    captures`` lists on-demand traces identically)."""
    global _device_trace
    with _device_trace_lock:
        if _device_trace is None:
            return {"ok": False, "error": "no trace running"}
        rec, _device_trace = _device_trace, None
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception as e:  # noqa: BLE001 — a failed stop still reports the dir
        return {"ok": False, "dir": rec["dir"], "error": f"stop_trace failed: {e}"}
    meta = {
        "name": rec["capture"],
        "kind": "ondemand",
        "captured_at": rec["t0"],
        "duration_s": round(time.time() - rec["t0"], 4),
        "pid": os.getpid(),
        "process": process_label(),
    }
    try:
        with open(os.path.join(rec["dir"], "profile.json"), "w") as f:
            json.dump(meta, f)
    except OSError as e:
        logger.debug("device trace meta write failed: %s", e)
    return {"ok": True, "dir": rec["dir"], **meta}


def device_trace_control(action: str, capture: str = "",
                         base_dir: Optional[str] = None) -> dict:
    if action == "start":
        return device_trace_start(capture or "ondemand", base_dir)
    if action == "stop":
        return device_trace_stop()
    return {"ok": False, "error": f"unknown action {action!r}"}


def collect_device_traces(session_dir: str) -> List[dict]:
    """Chrome-trace events from captured XLA device traces: every
    ``*.trace.json[.gz]`` under the session profiles root (the
    TensorBoard layout jax.profiler writes) parsed and re-labelled with
    an ``xla:<capture>`` pid so they merge into one ``ray-tpu timeline``
    perfetto load alongside host spans and lifecycle rows. XLA
    timestamps are capture-relative; the device rows sit on their own
    tracks rather than aligning with wall-clock host slices."""
    import gzip

    from ray_tpu.runtime_env.jax_profiler import profiles_root

    events: List[dict] = []
    root = profiles_root(session_dir)
    if not os.path.isdir(root):
        return events
    for base, _dirs, names in os.walk(root):
        for name in names:
            if not (name.endswith(".trace.json.gz")
                    or name.endswith(".trace.json")):
                continue
            path = os.path.join(base, name)
            capture = os.path.relpath(base, root).split(os.sep)[0]
            try:
                if name.endswith(".gz"):
                    with gzip.open(path, "rt", encoding="utf-8",
                                   errors="replace") as f:
                        payload = json.load(f)
                else:
                    with open(path, encoding="utf-8", errors="replace") as f:
                        payload = json.load(f)
            except (OSError, ValueError) as e:
                logger.debug("unreadable device trace %s: %s", path, e)
                continue
            for ev in payload.get("traceEvents", ()):
                if not isinstance(ev, dict):
                    continue
                ev = dict(ev)
                ev["pid"] = f"xla:{capture}:{ev.get('pid', 0)}"
                ev.setdefault("cat", "device")
                events.append(ev)
    return events
