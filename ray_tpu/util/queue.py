"""Distributed FIFO queue backed by an actor.

Reference: python/ray/util/queue.py (Queue over an _QueueActor).
"""
from __future__ import annotations

import queue as _stdqueue
from typing import Any, List, Optional

import ray_tpu


@ray_tpu.remote
class _QueueActor:
    """Methods run on the actor's thread pool (max_concurrency > 1), so a
    blocked get() must not starve puts — stdlib queue.Queue is the right
    thread-safe blocking primitive here."""

    def __init__(self, maxsize: int):
        self._q = _stdqueue.Queue(maxsize=maxsize if maxsize > 0 else 0)

    def put(self, item, timeout: Optional[float] = None):
        try:
            self._q.put(item, timeout=timeout)
            return True
        except _stdqueue.Full:
            return False

    def get(self, timeout: Optional[float] = None):
        try:
            return True, self._q.get(timeout=timeout)
        except _stdqueue.Empty:
            return False, None

    def put_nowait(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except _stdqueue.Full:
            return False

    def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except _stdqueue.Empty:
            return False, None

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()


class Empty(Exception):
    pass


class Full(Exception):
    pass


class Queue:
    """Sharable FIFO queue; pass the Queue object into tasks/actors freely."""

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 8)
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    def put(self, item: Any, timeout: Optional[float] = None):
        if not ray_tpu.get(self.actor.put.remote(item, timeout)):
            raise Full("queue full")

    def get(self, timeout: Optional[float] = None) -> Any:
        ok, value = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty("queue empty")
        return value

    def put_nowait(self, item: Any):
        if not ray_tpu.get(self.actor.put_nowait.remote(item)):
            raise Full("queue full")

    def get_nowait(self) -> Any:
        ok, value = ray_tpu.get(self.actor.get_nowait.remote())
        if not ok:
            raise Empty("queue empty")
        return value

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self):
        ray_tpu.kill(self.actor)

    def __reduce__(self):
        return (_rebuild_queue, (self.actor,))


def _rebuild_queue(actor):
    q = Queue.__new__(Queue)
    q.actor = actor
    return q
