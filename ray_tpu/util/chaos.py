"""Chaos / fault-injection utilities.

Reference: python/ray/_private/test_utils.py — ResourceKillerActor
(:1433), NodeKillerBase (:1500), WorkerKillerActor (:1597) — reusable
killer actors that randomly destroy cluster components while a workload
runs, and release/nightly_tests/setup_chaos.py which installs them for
chaos suites. Same shape here: killer actors driven by an interval loop,
started/stopped around a workload, reporting what they killed.
"""
from __future__ import annotations

import logging
import os
import random
import signal
import socket
import threading
import time
from typing import List, Optional

logger = logging.getLogger("ray_tpu.chaos")

import ray_tpu


class _KillerBase:
    """Interval loop calling ``_kill_one`` until stopped."""

    def __init__(self, kill_interval_s: float = 1.0, max_kills: int = 0, seed: int = 0):
        self._interval = kill_interval_s
        self._max = max_kills  # 0 = unlimited
        self._rng = random.Random(seed)
        self._killed: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run(self):
        """Start killing in the background (call via .remote())."""
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return True

    def _loop(self):
        while not self._stop.wait(self._interval):
            if self._max and len(self._killed) >= self._max:
                return
            try:
                victim = self._kill_one()
                if victim:
                    self._killed.append(victim)
            except Exception as e:  # noqa: BLE001 — chaos must not kill itself
                logger.debug("chaos kill attempt failed: %s", e)

    def stop_run(self) -> List[str]:
        """Stop and report the kill log."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        return list(self._killed)

    def get_total_killed(self) -> List[str]:
        return list(self._killed)

    def _kill_one(self) -> Optional[str]:
        raise NotImplementedError


@ray_tpu.remote(num_cpus=0)
class WorkerKillerActor(_KillerBase):
    """SIGKILLs random busy workers (reference: WorkerKillerActor —
    exercises task retry / actor restart paths)."""

    def _kill_one(self) -> Optional[str]:
        from ray_tpu.util import state as state_api

        me = os.getpid()
        host = socket.gethostname()
        victims = [
            w
            for w in state_api.list_workers()
            if w.get("state") in ("LEASED", "ACTOR")
            and w.get("pid")
            and w["pid"] != me
            # pids are only meaningful on this host (same rule the memory
            # monitor applies; a true multi-host chaos run needs a killer
            # per host).
            and w.get("hostname", host) == host
        ]
        if not victims:
            return None
        v = self._rng.choice(victims)
        try:
            os.kill(v["pid"], signal.SIGKILL)
        except ProcessLookupError:
            return None
        return f"worker:{v['worker_id'][:8]}:pid={v['pid']}"


@ray_tpu.remote(num_cpus=0)
class NodeKillerActor(_KillerBase):
    """SIGKILLs random non-head node agents (reference: NodeKillerBase —
    exercises node-death rescheduling, PG rescheduling, lineage
    reconstruction)."""

    def _kill_one(self) -> Optional[str]:
        from ray_tpu.util import state as state_api

        host = socket.gethostname()
        my_node = os.environ.get("RAY_TPU_NODE_ID", "")
        nodes = [
            n
            for n in state_api.list_nodes()
            if n.get("state") == "ALIVE"
            and not n.get("is_head")
            and n.get("agent_pid")
            and n.get("hostname", host) == host  # local pids only
            and n["node_id"] != my_node  # never saw off our own branch
        ]
        if not nodes:
            return None
        n = self._rng.choice(nodes)
        try:
            os.kill(n["agent_pid"], signal.SIGKILL)
        except ProcessLookupError:
            return None
        return f"node:{n['node_id'][:8]}"


def get_and_run_worker_killer(
    kill_interval_s: float = 1.0, max_kills: int = 0, seed: int = 0
):
    """Convenience mirroring setup_chaos.py's get_chaos_killer."""
    killer = WorkerKillerActor.remote(kill_interval_s, max_kills, seed)
    ray_tpu.get(killer.run.remote())
    return killer
