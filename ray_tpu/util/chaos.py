"""Chaos / fault-injection utilities.

Reference: python/ray/_private/test_utils.py — ResourceKillerActor
(:1433), NodeKillerBase (:1500), WorkerKillerActor (:1597) — reusable
killer actors that randomly destroy cluster components while a workload
runs, and release/nightly_tests/setup_chaos.py which installs them for
chaos suites. Same shape here: killer actors driven by an interval loop,
started/stopped around a workload, reporting what they killed.

Beyond the SIGKILL actors, this module owns the DETERMINISTIC side of
chaos: a seeded :class:`FaultSchedule` that the RPC layer consults on
every frame (reference analogue: the reference's chaos nightly tests
shape network faults with k8s traffic control — here the injection point
is the framework's own RPC peers, so drops/delays/errors/partitions are
exact and replayable). Install a plan programmatically
(:func:`install_fault_plan`) or via the ``RAY_TPU_FAULT_PLAN`` env var
(JSON, or ``@/path/to/plan.json``) which every process entry point
loads — spawned workers and agents inherit it. Decisions depend only on
the per-rule match counters and the plan's seed, never on wall-clock, so
two runs issuing the same RPC sequence inject the identical timeline
(verified by :func:`injection_log`).
"""
from __future__ import annotations

import fnmatch
import json
import logging
import os
import random
import signal
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger("ray_tpu.chaos")

import ray_tpu


class _KillerBase:
    """Interval loop calling ``_kill_one`` until stopped."""

    def __init__(self, kill_interval_s: float = 1.0, max_kills: int = 0, seed: int = 0):
        self._interval = kill_interval_s
        self._max = max_kills  # 0 = unlimited
        self._rng = random.Random(seed)
        self._killed: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run(self):
        """Start killing in the background (call via .remote())."""
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return True

    def _loop(self):
        while not self._stop.wait(self._interval):
            if self._max and len(self._killed) >= self._max:
                return
            try:
                victim = self._kill_one()
                if victim:
                    self._killed.append(victim)
            except Exception as e:  # noqa: BLE001 — chaos must not kill itself
                logger.debug("chaos kill attempt failed: %s", e)

    def stop_run(self) -> List[str]:
        """Stop and report the kill log."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        return list(self._killed)

    def get_total_killed(self) -> List[str]:
        return list(self._killed)

    def _kill_one(self) -> Optional[str]:
        raise NotImplementedError


@ray_tpu.remote(num_cpus=0)
class WorkerKillerActor(_KillerBase):
    """SIGKILLs random busy workers (reference: WorkerKillerActor —
    exercises task retry / actor restart paths)."""

    def _kill_one(self) -> Optional[str]:
        from ray_tpu.util import state as state_api

        me = os.getpid()
        host = socket.gethostname()
        victims = [
            w
            for w in state_api.list_workers()
            if w.get("state") in ("LEASED", "ACTOR")
            and w.get("pid")
            and w["pid"] != me
            # pids are only meaningful on this host (same rule the memory
            # monitor applies; a true multi-host chaos run needs a killer
            # per host).
            and w.get("hostname", host) == host
        ]
        if not victims:
            return None
        v = self._rng.choice(victims)
        try:
            os.kill(v["pid"], signal.SIGKILL)
        except ProcessLookupError:
            return None
        return f"worker:{v['worker_id'][:8]}:pid={v['pid']}"


@ray_tpu.remote(num_cpus=0)
class NodeKillerActor(_KillerBase):
    """SIGKILLs random non-head node agents (reference: NodeKillerBase —
    exercises node-death rescheduling, PG rescheduling, lineage
    reconstruction)."""

    def _kill_one(self) -> Optional[str]:
        from ray_tpu.util import state as state_api

        host = socket.gethostname()
        my_node = os.environ.get("RAY_TPU_NODE_ID", "")
        nodes = [
            n
            for n in state_api.list_nodes()
            if n.get("state") == "ALIVE"
            and not n.get("is_head")
            and n.get("agent_pid")
            and n.get("hostname", host) == host  # local pids only
            and n["node_id"] != my_node  # never saw off our own branch
        ]
        if not nodes:
            return None
        n = self._rng.choice(nodes)
        try:
            os.kill(n["agent_pid"], signal.SIGKILL)
        except ProcessLookupError:
            return None
        return f"node:{n['node_id'][:8]}"


def get_and_run_worker_killer(
    kill_interval_s: float = 1.0, max_kills: int = 0, seed: int = 0
):
    """Convenience mirroring setup_chaos.py's get_chaos_killer."""
    killer = WorkerKillerActor.remote(kill_interval_s, max_kills, seed)
    ray_tpu.get(killer.run.remote())
    return killer


# ===========================================================================
# Deterministic RPC-level fault injection
# ===========================================================================

class InjectedFaultError(ConnectionError):
    """An error deliberately injected by a :class:`FaultSchedule` rule.

    Subclasses ConnectionError so the injected failure walks the same
    recovery paths a real transport fault would (reconnect/backoff/
    gang-repair), not a user-error path."""

    def __init__(self, detail: str = "injected fault"):
        self.detail = detail
        super().__init__(detail)

    def __reduce__(self):
        return (InjectedFaultError, (self.detail,))


@dataclass
class FaultRule:
    """One injection rule. Matches RPC frames by (method glob, direction,
    peer-label substring); fires ``after`` skipped matches, at most
    ``count`` times (0 = unlimited), with seeded ``probability``.

    Actions: ``delay`` (delay_ms before the frame proceeds), ``drop``
    (the frame silently vanishes — a dropped request leaves the caller
    waiting on its timeout, exactly like a lost packet), ``error``
    (request fails fast with :class:`InjectedFaultError`). A one-way
    partition is a ``drop`` rule with ``method="*"`` scoped to one
    direction/peer; agent-level slow-node throttling is a ``delay`` rule
    with ``method="*"`` installed on that node's processes."""

    method: str = "*"
    direction: str = "both"  # "in" (frames we receive) | "out" | "both"
    peer: str = ""  # substring of the connection label ("" = any)
    action: str = "delay"  # "delay" | "drop" | "error"
    delay_ms: float = 0.0
    error: str = "injected fault"
    after: int = 0
    count: int = 0
    probability: float = 1.0
    # runtime state (not part of the plan)
    _matched: int = field(default=0, repr=False, compare=False)
    _fired: int = field(default=0, repr=False, compare=False)


class FaultSchedule:
    """A seeded, replayable injection plan the RPC layer consults.

    Decisions are a pure function of (seed, per-rule match counters):
    two processes issuing the same RPC sequence against the same plan
    inject the identical timeline. The bounded :meth:`log` records every
    injection (seq, method, direction, peer, rule index, action) for
    replay verification."""

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self.seed = seed
        self.rules = list(rules)
        self._rngs = [random.Random(f"{seed}:{i}") for i in range(len(self.rules))]
        self._lock = threading.Lock()
        self._seq = 0
        import collections

        self._log: "collections.deque[dict]" = collections.deque(maxlen=10000)

    @classmethod
    def from_plan(cls, plan: Dict[str, Any]) -> "FaultSchedule":
        rules = [
            FaultRule(**{k: v for k, v in r.items() if not k.startswith("_")})
            for r in plan.get("rules", [])
        ]
        return cls(rules, seed=int(plan.get("seed", 0)))

    @classmethod
    def from_json(cls, raw: str) -> "FaultSchedule":
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        return cls.from_plan(json.loads(raw))

    def intercept(self, method: str, direction: str, label: str = "") -> Optional[dict]:
        """First matching rule's action for this frame, or None. Applies
        after/count/probability bookkeeping under the lock."""
        with self._lock:
            for i, rule in enumerate(self.rules):
                if rule.direction not in ("both", direction):
                    continue
                if rule.peer and rule.peer not in (label or ""):
                    continue
                if not fnmatch.fnmatchcase(method, rule.method):
                    continue
                rule._matched += 1
                if rule._matched <= rule.after:
                    continue
                if rule.count and rule._fired >= rule.count:
                    continue
                if rule.probability < 1.0 and self._rngs[i].random() >= rule.probability:
                    continue
                rule._fired += 1
                self._seq += 1
                entry = {
                    "seq": self._seq,
                    "method": method,
                    "direction": direction,
                    "peer": label,
                    "rule": i,
                    "action": rule.action,
                }
                self._log.append(entry)
                if rule.action == "delay":
                    return {"action": "delay", "delay_s": rule.delay_ms / 1000.0}
                if rule.action == "drop":
                    return {"action": "drop"}
                return {
                    "action": "error",
                    "error": InjectedFaultError(
                        f"{rule.error} (rule {i}: {rule.method} {direction})"
                    ),
                }
        return None

    def log(self) -> List[dict]:
        with self._lock:
            return list(self._log)


_install_lock = threading.Lock()
_env_loaded = False


def install_fault_plan(plan) -> Optional[FaultSchedule]:
    """Install a fault plan in THIS process (None clears). Accepts a
    FaultSchedule, a plan dict ({"seed": .., "rules": [..]}), or a JSON
    string / ``@path``. Returns the active schedule."""
    from ray_tpu.utils import rpc

    if plan is None:
        sched = None
    elif isinstance(plan, FaultSchedule):
        sched = plan
    elif isinstance(plan, dict):
        sched = FaultSchedule.from_plan(plan)
    else:
        sched = FaultSchedule.from_json(str(plan))
    rpc.set_fault_schedule(sched)
    if sched is not None:
        logger.warning(
            "fault plan installed: %d rule(s), seed %d (pid %d)",
            len(sched.rules), sched.seed, os.getpid(),
        )
    return sched


def active_fault_schedule() -> Optional[FaultSchedule]:
    from ray_tpu.utils import rpc

    return rpc.get_fault_schedule()


def injection_log() -> List[dict]:
    """This process's injection timeline (empty when no plan active)."""
    sched = active_fault_schedule()
    return sched.log() if sched is not None else []


def install_fault_plan_from_env() -> Optional[FaultSchedule]:
    """Load ``RAY_TPU_FAULT_PLAN`` once per process (entry points call
    this; spawned workers/agents inherit the env var)."""
    global _env_loaded
    with _install_lock:
        if _env_loaded:
            return active_fault_schedule()
        _env_loaded = True
        raw = os.environ.get("RAY_TPU_FAULT_PLAN", "")
        if not raw:
            return None
        try:
            return install_fault_plan(raw)
        except Exception as e:  # noqa: BLE001 — a bad plan must not kill the process
            logger.error("RAY_TPU_FAULT_PLAN unparseable: %s", e)
            return None


def install_plan_on_node(node_id_hex: str, plan: Optional[dict]) -> bool:
    """Install (or clear, plan=None) a fault plan on a RUNNING node
    agent — the runtime path for agent-level slow-node throttling:
    ``install_plan_on_node(nid, {"rules": [{"method": "*",
    "direction": "in", "action": "delay", "delay_ms": 200}]})``."""
    from ray_tpu.core.api import _require_worker

    return _require_worker()._call(
        "chaos_install", node_id_hex, json.dumps(plan) if plan else ""
    )
