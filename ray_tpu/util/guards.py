"""Guard annotations: which lock protects which map.

The concurrency-correctness vocabulary (round 19). PR 17 multiplied the
control plane's shared mutable state — sharded controller hot maps,
TopicBus subscriber registries, agent-side resource mirrors, batched
lease windows — and the existing tooling (RTL001–RTL008, lockwatch)
can say *that* a lock was held too long or acquired out of order, but
not *which* lock protects which structure. These annotations close that
gap, in the spirit of Clang's ``GUARDED_BY`` thread-safety attributes
and the TSan discipline the Ray reference leans on:

* ``self._tasks = GuardedDict("_lock", owner=self, name="tasks")`` —
  a dict whose every access must hold ``self._lock``;
* ``self._subs = GuardedDict(OWNER_THREAD, owner=self, name="subs")`` —
  single-writer state owned by one thread (the asyncio-loop discipline
  every controller map follows: no locks, loop-only mutation);
* ``@guarded_by("_lock")`` on a method — the method is only ever called
  with ``self._lock`` already held (callers acquire), so its accesses
  to ``"_lock"``-guarded state are sanctioned;
* :func:`snapshot` / :func:`cycle_snapshot` — sanctioned unguarded
  reads: an atomic shallow copy (list()/dict() under the GIL) taken for
  iteration outside the lock, the idiom the controller's ``_CENSUS_CHUNK``
  census cycle and the lint allow-list both recognize.

Two consumers:

* **static** — lint rules RTL009–RTL011 (``tools/lint/guard_rules.py``)
  AST-check every read/write of an annotated attribute lexically;
* **dynamic** — the ConcSan runtime witness
  (``tools/sanitizer/runtime.py``) records the held-lock set at every
  access when ``RAY_TPU_CONCSAN=1`` and applies the Eraser lockset
  algorithm on top of the declared guard.

Cost discipline: with ConcSan off (the default), ``GuardedDict`` /
``GuardedSet`` are plain dict/set subclasses with **no overridden
accessors** — every operation stays a C-speed builtin call. The checked
variants are only selected at construction when the sanitizer is
enabled, so production and the normal test suite pay nothing.

Both containers degrade to their plain builtin across pickling (the
RPC layer and the GCS journal are pickle-based): the guard annotation
is a property of the *owning process's* instance, never of the wire
form.
"""
from __future__ import annotations

import functools
import weakref
from typing import Any, List, Optional, Set

# Sentinel guard: single-writer state owned by one thread (the asyncio
# event-loop discipline of the controller/agent). The runtime witness
# binds the owner thread on first access and allows exactly one
# ownership transfer (constructor thread -> loop thread handoff).
OWNER_THREAD = "@owner-thread"


class GuardMeta:
    """Per-container annotation record, read by the ConcSan runtime."""

    __slots__ = (
        "guard", "attr", "owner_ref", "owner_cls",
        # Eraser state, mutated only by tools/sanitizer/runtime.py:
        "state", "owner_thread", "transferred", "threads_seen",
        "lockset", "reported",
    )

    def __init__(self, guard: str, attr: str, owner: Any = None):
        self.guard = guard
        self.attr = attr
        self.owner_ref = weakref.ref(owner) if owner is not None else None
        self.owner_cls = type(owner).__name__ if owner is not None else ""
        self.state = "virgin"  # virgin|exclusive|shared_read|shared_mod
        self.owner_thread: Optional[int] = None
        self.transferred = False
        self.threads_seen: Set[int] = set()
        self.lockset: Optional[frozenset] = None
        self.reported: Set[str] = set()  # finding kinds already emitted

    def describe(self) -> str:
        owner = self.owner_cls or "?"
        return f"{owner}.{self.attr or '?'} (guarded_by {self.guard})"


# Resolved lazily so importing guards never drags the sanitizer in on
# the production path; the sanitizer installs itself here on enable().
_runtime = None


def _sanitizer():
    global _runtime
    if _runtime is None:
        from ray_tpu.tools.sanitizer import runtime as _rt

        _runtime = _rt
    return _runtime


def concsan_enabled() -> bool:
    """Is the runtime witness on for THIS process? (env or explicit)."""
    import os

    if _runtime is not None:
        return _runtime.enabled()
    # Cheap pre-import check: don't import the sanitizer package just to
    # learn it is off.
    if os.environ.get("RAY_TPU_CONCSAN", "") != "1":
        return False
    return _sanitizer().enabled()


def guarded_by(guard: str):
    """Declare that a method is only called with ``self.<guard>`` held
    (or, for :data:`OWNER_THREAD`, only from the owning thread).

    Static: RTL009 treats the method body as holding the named lock.
    Dynamic: with ConcSan enabled at import, the method is wrapped to
    verify the contract on entry; otherwise the declaration is free
    (attribute stamp only — no wrapper on the call path).
    """

    def deco(fn):
        fn.__guarded_by__ = guard
        if not concsan_enabled():
            return fn

        @functools.wraps(fn)
        def checked(self, *args, **kw):
            _sanitizer().note_method_entry(self, guard, fn.__qualname__)
            return fn(self, *args, **kw)

        checked.__guarded_by__ = guard
        return checked

    return deco


def _plain_copy(container) -> Any:
    if isinstance(container, dict):
        return dict(container)
    if isinstance(container, (set, frozenset)):
        return set(container)
    return list(container)


def snapshot(container) -> Any:
    """Sanctioned unguarded read: one atomic shallow copy (GIL) of a
    guarded container, for iteration/inspection outside the lock.
    Dict -> dict, set -> set, anything else -> list."""
    if concsan_enabled():
        with _sanitizer().sanctioned():
            return _plain_copy(container)
    return _plain_copy(container)


def cycle_snapshot(container) -> List:
    """Sanctioned unguarded read for chunked cycle iteration (the
    controller's ``_CENSUS_CHUNK`` census pattern): an atomic key/member
    list the caller may walk across many ticks while the live structure
    keeps mutating."""
    if concsan_enabled():
        with _sanitizer().sanctioned():
            return list(container)
    return list(container)


class GuardedDict(dict):
    """A dict annotated with the lock (or owner thread) that guards it.

    Construction chooses the class: the plain variant (this class — no
    overridden accessors, zero overhead) normally, the checked variant
    when the ConcSan witness is enabled in this process.
    """

    __slots__ = ("__guard_meta__",)

    def __new__(cls, guard: str = OWNER_THREAD, *args, **kw):
        if cls is GuardedDict and concsan_enabled():
            cls = _CheckedGuardedDict
        return super().__new__(cls)

    def __init__(self, guard: str = OWNER_THREAD, *args,
                 owner: Any = None, name: str = "", **kw):
        super().__init__(*args, **kw)
        self.__guard_meta__ = GuardMeta(guard, name, owner)

    def __reduce__(self):
        # Wire/journal form is a plain dict: the annotation belongs to
        # the owning process's instance, and the RPC peer's pickle must
        # not need this class (or its guard) to exist.
        return (dict, (dict(self),))


class GuardedSet(set):
    """Set sibling of :class:`GuardedDict`."""

    __slots__ = ("__guard_meta__",)

    def __new__(cls, guard: str = OWNER_THREAD, *args, **kw):
        if cls is GuardedSet and concsan_enabled():
            cls = _CheckedGuardedSet
        return super().__new__(cls)

    def __init__(self, guard: str = OWNER_THREAD, *args,
                 owner: Any = None, name: str = "", **kw):
        super().__init__(*args, **kw)
        self.__guard_meta__ = GuardMeta(guard, name, owner)

    def __reduce__(self):
        return (set, (set(self),))


# ---------------------------------------------------------------------------
# Checked variants — selected only when the sanitizer is enabled.

def _note(container, op: str):
    _sanitizer().note_access(container.__guard_meta__, op)


def _rd(name):
    base = getattr(dict, name)

    def method(self, *a, **kw):
        _note(self, "read")
        return base(self, *a, **kw)

    method.__name__ = name
    return method


def _wr(name, base_cls=dict):
    base = getattr(base_cls, name)

    def method(self, *a, **kw):
        _note(self, "write")
        return base(self, *a, **kw)

    method.__name__ = name
    return method


class _CheckedGuardedDict(GuardedDict):
    __slots__ = ()

    for _m in ("__getitem__", "__contains__", "__iter__", "__len__",
               "get", "keys", "values", "items", "copy", "__eq__"):
        locals()[_m] = _rd(_m)
    for _m in ("__setitem__", "__delitem__", "pop", "popitem", "clear",
               "update", "setdefault"):
        locals()[_m] = _wr(_m)
    del _m
    __hash__ = None  # dicts are unhashable; keep that true here


def _srd(name):
    base = getattr(set, name)

    def method(self, *a, **kw):
        _note(self, "read")
        return base(self, *a, **kw)

    method.__name__ = name
    return method


class _CheckedGuardedSet(GuardedSet):
    __slots__ = ()

    for _m in ("__contains__", "__iter__", "__len__", "__eq__",
               "isdisjoint", "issubset", "issuperset", "copy"):
        locals()[_m] = _srd(_m)
    for _m in ("add", "discard", "remove", "pop", "clear", "update",
               "difference_update", "intersection_update",
               "symmetric_difference_update"):
        locals()[_m] = _wr(_m, set)
    del _m
    __hash__ = None  # sets are unhashable; keep that true here
