"""Application metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py (Cython Metric over the C++
OpenCensus stats, src/ray/stats/metric.h) and the per-node metrics agent
(python/ray/_private/metrics_agent.py:119) that proxies to Prometheus.

Rebuild shape: metrics record locally (lock-free per-process dicts) and a
daemon thread flushes deltas to the controller every
``metrics_report_interval_ms``; the controller aggregates and serves both a
JSON snapshot (state API) and the Prometheus text exposition on its HTTP
observability port (reference: dashboard metrics module + `ray metrics`).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_lock = threading.Lock()
_registry: List["Metric"] = []
_flusher_started = False


def _tags_key(tags: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((tags or {}).items()))


class Metric:
    """Base class (reference: util/metrics.py Metric)."""

    TYPE = "untyped"

    def __init__(self, name: str, description: str = "", tag_keys: Sequence[str] = ()):
        if not name:
            raise ValueError("metric name is required")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        with _lock:
            _registry.append(self)
        _ensure_flusher()

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]):
        if self._default_tags:
            out = dict(self._default_tags)
            out.update(tags or {})
            return out
        return tags

    # -- flush protocol -----------------------------------------------------
    def _drain(self) -> List[tuple]:
        """Return (name, type, desc, tags, payload) records and reset deltas."""
        raise NotImplementedError


class Counter(Metric):
    TYPE = "counter"

    def __init__(self, name, description="", tag_keys=()):
        self._deltas: Dict[tuple, float] = {}
        super().__init__(name, description, tag_keys)

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("Counter.inc() requires a non-negative value")
        key = _tags_key(self._merged(tags))
        with _lock:
            self._deltas[key] = self._deltas.get(key, 0.0) + value

    def _drain(self):
        with _lock:
            out, self._deltas = self._deltas, {}
        return [(self.name, self.TYPE, self.description, k, v) for k, v in out.items()]


class Gauge(Metric):
    TYPE = "gauge"

    def __init__(self, name, description="", tag_keys=()):
        self._values: Dict[tuple, float] = {}
        super().__init__(name, description, tag_keys)

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with _lock:
            self._values[_tags_key(self._merged(tags))] = float(value)

    def _drain(self):
        with _lock:
            out = dict(self._values)
        return [(self.name, self.TYPE, self.description, k, v) for k, v in out.items()]


class Histogram(Metric):
    TYPE = "histogram"

    def __init__(self, name, description="", boundaries: Sequence[float] = (), tag_keys=()):
        if not boundaries:
            raise ValueError("Histogram requires boundaries")
        self.boundaries = sorted(float(b) for b in boundaries)
        self._state: Dict[tuple, list] = {}  # tags -> [bucket_counts..., sum, count]
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _tags_key(self._merged(tags))
        with _lock:
            st = self._state.get(key)
            if st is None:
                st = self._state[key] = [0] * (len(self.boundaries) + 1) + [0.0, 0]
            i = 0
            while i < len(self.boundaries) and value > self.boundaries[i]:
                i += 1
            st[i] += 1
            st[-2] += value
            st[-1] += 1

    def _drain(self):
        with _lock:
            out, self._state = self._state, {}
        return [
            (self.name, self.TYPE, self.description, k, {"boundaries": self.boundaries, "state": v})
            for k, v in out.items()
        ]


_unflushed: List[tuple] = []  # drained records a failed report must not lose


def _flush_once() -> bool:
    global _unflushed
    from ray_tpu.core import api

    core = api._global_worker
    if core is None:
        return False
    with _lock:
        metrics = list(_registry)
        records, _unflushed = _unflushed, []
    for m in metrics:
        records.extend(m._drain())
    if records:
        try:
            core._call("metrics_report", records)
        except Exception:
            # Re-queue so counter deltas survive transient controller
            # hiccups (bounded: keep the newest ~10k records).
            with _lock:
                _unflushed = (records + _unflushed)[-10000:]
            return False
    return True


def _ensure_flusher():
    global _flusher_started
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True

    def loop():
        from ray_tpu.config import get_config

        interval = get_config().metrics_report_interval_ms / 1000.0
        while True:
            time.sleep(interval)
            _flush_once()

    threading.Thread(target=loop, daemon=True, name="metrics-flush").start()


def flush():
    """Force a synchronous flush (tests / process exit)."""
    _flush_once()


# ---------------------------------------------------------------------------
def prometheus_text(snapshot: Dict) -> str:
    """Render a controller metrics snapshot in Prometheus exposition format."""
    lines = []
    for name, entry in sorted(snapshot.items()):
        mtype, desc, series = entry["type"], entry["description"], entry["series"]
        if desc:
            lines.append(f"# HELP {name} {desc}")
        lines.append(f"# TYPE {name} {mtype}")
        for tags, value in series:
            label = (
                "{" + ",".join(f'{k}="{v}"' for k, v in tags) + "}" if tags else ""
            )
            if mtype == "histogram":
                bounds = value["boundaries"]
                st = value["state"]
                cum = 0
                for i, b in enumerate(bounds):
                    cum += st[i]
                    ltags = dict(tags)
                    ltags["le"] = str(b)
                    lab = "{" + ",".join(f'{k}="{v}"' for k, v in sorted(ltags.items())) + "}"
                    lines.append(f"{name}_bucket{lab} {cum}")
                cum += st[len(bounds)]
                inf = dict(tags)
                inf["le"] = "+Inf"
                lab = "{" + ",".join(f'{k}="{v}"' for k, v in sorted(inf.items())) + "}"
                lines.append(f"{name}_bucket{lab} {cum}")
                lines.append(f"{name}_sum{label} {st[-2]}")
                lines.append(f"{name}_count{label} {st[-1]}")
            else:
                lines.append(f"{name}{label} {value}")
    return "\n".join(lines) + "\n"
