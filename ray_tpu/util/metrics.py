"""Application metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py (Cython Metric over the C++
OpenCensus stats, src/ray/stats/metric.h) and the per-node metrics agent
(python/ray/_private/metrics_agent.py:119) that proxies to Prometheus.

Rebuild shape: metrics record locally (lock-free per-process dicts) and a
daemon thread flushes deltas to the controller every
``metrics_report_interval_ms``; the controller aggregates and serves both a
JSON snapshot (state API) and the Prometheus text exposition on its HTTP
observability port (reference: dashboard metrics module + `ray metrics`).
"""
from __future__ import annotations

import concurrent.futures as _futures
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_lock = threading.Lock()
_registry: List["Metric"] = []
_flusher_started = False
# Dropped-series accounting (cardinality cap): metric name -> drop count
# since the last drain. Synthesized into ``metrics_series_dropped_total``
# records at flush time — NOT a Metric instance, so the counter itself
# can never recurse into the cap.
_dropped_series: Dict[str, float] = {}


def _series_cap() -> int:
    """Per-metric cap on distinct label sets (config
    ``metrics_max_series_per_metric``). Prefers the cluster config the
    controller handed this process at registration (so per-init
    ``_system_config`` overrides reach the recording side), falling back
    to env/defaults. Read lazily so library imports don't force config
    initialization."""
    try:
        from ray_tpu.core import api

        core = api._global_worker
        if core is not None:
            return int(core.config.get("metrics_max_series_per_metric", 200))
        from ray_tpu.config import get_config

        return int(get_config().metrics_max_series_per_metric)
    except Exception:  # noqa: BLE001 — config unavailable (odd embedders)
        return 200


def _tags_key(tags: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((tags or {}).items()))


class Metric:
    """Base class (reference: util/metrics.py Metric)."""

    TYPE = "untyped"

    def __init__(self, name: str, description: str = "", tag_keys: Sequence[str] = (),
                 max_series: Optional[int] = None):
        if not name:
            raise ValueError("metric name is required")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        # Cardinality bound: label sets ever admitted by this metric. A
        # NEW label set past the cap is dropped (and counted) — a
        # per-request/per-task tag can't blow up the registry, the
        # controller aggregation, or the Prometheus exposition.
        self._seen_keys: set = set()
        self._max_series = max_series
        with _lock:
            _registry.append(self)
        _ensure_flusher()

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]):
        if self._default_tags:
            out = dict(self._default_tags)
            out.update(tags or {})
            return out
        return tags

    def _cap(self) -> int:
        """Resolve the series cap OUTSIDE _lock: _series_cap may import
        (api/config), and running Python's import machinery under the
        metrics lock would serialize every recording thread behind it —
        and risk a _lock→import-lock inversion against a thread
        constructing a Metric at module import time."""
        return self._max_series if self._max_series is not None else _series_cap()

    def _admit_locked(self, key: tuple, cap: int) -> bool:
        """Caller holds _lock. False = series dropped (over the cap)."""
        if key in self._seen_keys:
            return True
        if len(self._seen_keys) >= cap:
            _dropped_series[self.name] = _dropped_series.get(self.name, 0.0) + 1.0
            return False
        self._seen_keys.add(key)
        return True

    # -- flush protocol -----------------------------------------------------
    def _drain(self) -> List[tuple]:
        """Return (name, type, desc, tags, payload) records and reset deltas."""
        raise NotImplementedError


class Counter(Metric):
    TYPE = "counter"

    def __init__(self, name, description="", tag_keys=(), max_series=None):
        self._deltas: Dict[tuple, float] = {}
        super().__init__(name, description, tag_keys, max_series)

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("Counter.inc() requires a non-negative value")
        key = _tags_key(self._merged(tags))
        cap = self._cap()
        with _lock:
            if not self._admit_locked(key, cap):
                return
            self._deltas[key] = self._deltas.get(key, 0.0) + value

    def _drain(self):
        with _lock:
            out, self._deltas = self._deltas, {}
        return [(self.name, self.TYPE, self.description, k, v) for k, v in out.items()]


class Gauge(Metric):
    TYPE = "gauge"

    def __init__(self, name, description="", tag_keys=(), max_series=None):
        self._values: Dict[tuple, float] = {}
        super().__init__(name, description, tag_keys, max_series)

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _tags_key(self._merged(tags))
        cap = self._cap()
        with _lock:
            if not self._admit_locked(key, cap):
                return
            self._values[key] = float(value)

    def _drain(self):
        with _lock:
            out = dict(self._values)
        return [(self.name, self.TYPE, self.description, k, v) for k, v in out.items()]


class Histogram(Metric):
    TYPE = "histogram"

    def __init__(self, name, description="", boundaries: Sequence[float] = (), tag_keys=(),
                 max_series=None):
        if not boundaries:
            raise ValueError("Histogram requires boundaries")
        self.boundaries = sorted(float(b) for b in boundaries)
        self._state: Dict[tuple, list] = {}  # tags -> [bucket_counts..., sum, count]
        super().__init__(name, description, tag_keys, max_series)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        self.observe_many((value,), tags)

    def observe_many(self, values: Sequence[float], tags: Optional[Dict[str, str]] = None):
        """Bulk observe: one tags-key/cap resolution and one lock
        acquisition for the whole batch — the flush path for hot-loop
        recorders (e.g. the lifecycle flight recorder) that must not pay
        per-event metric overhead."""
        if not values:
            return
        key = _tags_key(self._merged(tags))
        cap = self._cap()
        with _lock:
            if not self._admit_locked(key, cap):
                return
            st = self._state.get(key)
            if st is None:
                st = self._state[key] = [0] * (len(self.boundaries) + 1) + [0.0, 0]
            bounds = self.boundaries
            nb = len(bounds)
            for value in values:
                i = 0
                while i < nb and value > bounds[i]:
                    i += 1
                st[i] += 1
                st[-2] += value
                st[-1] += 1

    def _drain(self):
        with _lock:
            out, self._state = self._state, {}
        return [
            (self.name, self.TYPE, self.description, k, {"boundaries": self.boundaries, "state": v})
            for k, v in out.items()
        ]


_unflushed: List[tuple] = []  # drained records a failed report must not lose


def drain_records() -> List[tuple]:
    """Drain every registered metric (plus dropped-series accounting and
    any re-queued unflushed records) into report records. Used by
    _flush_once AND by processes without a CoreWorker — the node agent
    ships these over its own controller connection."""
    global _unflushed
    with _lock:
        metrics = list(_registry)
        records, _unflushed = _unflushed, []
        dropped = dict(_dropped_series)
        _dropped_series.clear()
    for m in metrics:
        records.extend(m._drain())
    for name, n in dropped.items():
        records.append(
            (
                "metrics_series_dropped_total",
                "counter",
                "Metric series dropped by the per-metric label-cardinality cap",
                (("metric", name),),
                n,
            )
        )
    return records


def requeue_records(records: List[tuple]):
    """Put drained records back so a failed report isn't lost (bounded:
    oldest records are trimmed first — the just-drained batch is the
    newest and goes at the tail)."""
    global _unflushed
    with _lock:
        _unflushed = (_unflushed + records)[-10000:]


def _flush_once() -> bool:
    from ray_tpu.core import api

    core = api._global_worker
    if core is None:
        return False
    records = drain_records()
    if records:
        try:
            # Bounded wait: this runs on the ONE process-wide flusher
            # thread — an unbounded call wedged on a cluster mid-shutdown
            # (stopped loop, half-dead peer) would silently kill metric
            # delivery for every LATER cluster this process connects to.
            core._call("metrics_report", records, timeout=5)
        except (TimeoutError, _futures.TimeoutError):
            # The in-flight RPC is NOT cancelled by the client-side wait
            # expiring — a stalled-but-alive controller may still apply
            # it, so re-sending would double-count deltas. Drop instead:
            # undercounting one window beats inflating counters.
            return False
        except BaseException:  # noqa: BLE001 — incl. loop-shutdown errors
            # Connection-level failure: the report did not land. Re-queue
            # so counter deltas survive transient controller hiccups
            # (bounded: keep the newest ~10k records).
            requeue_records(records)
            return False
    return True


def _ensure_flusher():
    global _flusher_started
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True

    def loop():
        from ray_tpu.config import get_config

        interval = get_config().metrics_report_interval_ms / 1000.0
        while True:
            time.sleep(interval)
            _flush_once()

    threading.Thread(target=loop, daemon=True, name="metrics-flush").start()


def flush():
    """Force a synchronous flush (tests / process exit)."""
    _flush_once()


# ---------------------------------------------------------------------------
def summarize_samples(samples) -> Dict[str, float]:
    """Percentile summary of a bounded sample ring (nearest-rank): the
    shared shape for dwell-time and latency rollups in the state API and
    the envelope harness ({samples, mean, p50, p95, p99, max})."""
    vals = sorted(float(v) for v in samples)
    if not vals:
        return {}
    last = len(vals) - 1

    def pct(q: float) -> float:
        return vals[min(last, int(q * last + 0.5))]

    return {
        "samples": len(vals),
        "mean": round(sum(vals) / len(vals), 3),
        "p50": round(pct(0.5), 3),
        "p95": round(pct(0.95), 3),
        "p99": round(pct(0.99), 3),
        "max": round(vals[-1], 3),
    }


# ---------------------------------------------------------------------------
def prometheus_text(snapshot: Dict) -> str:
    """Render a controller metrics snapshot in Prometheus exposition format."""
    lines = []
    for name, entry in sorted(snapshot.items()):
        mtype, desc, series = entry["type"], entry["description"], entry["series"]
        if desc:
            lines.append(f"# HELP {name} {desc}")
        lines.append(f"# TYPE {name} {mtype}")
        for tags, value in series:
            label = (
                "{" + ",".join(f'{k}="{v}"' for k, v in tags) + "}" if tags else ""
            )
            if mtype == "histogram":
                bounds = value["boundaries"]
                st = value["state"]
                cum = 0
                for i, b in enumerate(bounds):
                    cum += st[i]
                    ltags = dict(tags)
                    ltags["le"] = str(b)
                    lab = "{" + ",".join(f'{k}="{v}"' for k, v in sorted(ltags.items())) + "}"
                    lines.append(f"{name}_bucket{lab} {cum}")
                cum += st[len(bounds)]
                inf = dict(tags)
                inf["le"] = "+Inf"
                lab = "{" + ",".join(f'{k}="{v}"' for k, v in sorted(inf.items())) + "}"
                lines.append(f"{name}_bucket{lab} {cum}")
                lines.append(f"{name}_sum{label} {st[-2]}")
                lines.append(f"{name}_count{label} {st[-1]}")
            else:
                lines.append(f"{name}{label} {value}")
    return "\n".join(lines) + "\n"
