"""Generic actor worker group + the TrainWorker actor.

Reference: python/ray/train/_internal/worker_group.py — ``RayTrainWorker``
:19-35 (an actor that executes arbitrary functions), ``execute/
execute_single(_async)`` :233-316, add/remove workers :318-361; rank sort
by node :363.
"""
from __future__ import annotations

import logging
import os
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("ray_tpu.train")

import ray_tpu
from ray_tpu.train.session import TrainContext, _TrainSession, _set_session


class TrainWorker:
    """Actor hosting one training rank. ``run_train_fn`` occupies one actor
    thread for the whole training loop; ``next_result``/``execute`` run on
    the other threads (max_concurrency > 1)."""

    def __init__(self):
        self._session: Optional[_TrainSession] = None
        self._thread: Optional[threading.Thread] = None

    # -- generic execution (reference worker_group.py:19 __execute) -------
    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def node_info(self) -> dict:
        from ray_tpu.runtime_context import get_runtime_context

        return {"node_id": get_runtime_context().get_node_id(), "pid": os.getpid()}

    # -- training lifecycle ----------------------------------------------
    def setup_session(
        self,
        ctx: TrainContext,
        group_name: str,
        latest_checkpoint: Optional[str],
        env_vars: Optional[Dict[str, str]] = None,
        jax_distributed: bool = False,
        dataset_shards: Optional[Dict[str, Any]] = None,
        data_context: Optional[Dict[str, Any]] = None,
        checkpoint_async: bool = False,
        ckpt_index_start: int = 0,
    ):
        from ray_tpu import collective

        for k, v in (env_vars or {}).items():
            os.environ[k] = v
        if data_context:
            from ray_tpu.data.context import DataContext

            DataContext.apply_overrides(data_context)
        self._session = _TrainSession(
            ctx, group_name, latest_checkpoint,
            checkpoint_async=checkpoint_async,
            ckpt_index_start=ckpt_index_start,
        )
        self._session.dataset_shards = dict(dataset_shards or {})
        _set_session(self._session)
        if jax_distributed:
            # One JAX runtime across the gang: rendezvous via controller
            # KV, then jax.distributed.initialize (multi-host SPMD).
            from ray_tpu.train.jax_rendezvous import setup_jax_distributed

            setup_jax_distributed(ctx.world_rank, ctx.world_size, group_name)
            self._jax_distributed = True
        # Join the rank-sync collective group for report() barriers.
        collective.init_collective_group(
            ctx.world_size, ctx.world_rank, "host", group_name
        )
        return True

    def run_train_fn(self, train_fn: Callable, config: Optional[dict]):
        """Runs the user loop to completion; reports stream via the session."""
        from ray_tpu.train.session import _call_train_fn

        session = self._session
        assert session is not None, "setup_session must run first"
        try:
            _call_train_fn(train_fn, config)
            # A loop that RETURNED must mean its checkpoints are durable:
            # drain pending async uploads before declaring success.
            session.finish_checkpoints()
        except BaseException as e:  # noqa: BLE001 — surfaced to the driver
            session.error = e
            session.finished.set()
            raise
        finally:
            # The executor kills this actor soon after the loop returns;
            # push the final step-metric deltas out before that.
            try:
                from ray_tpu.util.metrics import flush

                flush()
            except Exception as e:  # noqa: BLE001 — telemetry only
                logger.debug("final train-metric flush failed: %s", e)
        session.finished.set()
        return True

    def next_result(self):
        assert self._session is not None
        return self._session.next_result()

    def abort_run(self, reason: str = "gang repair"):
        """Break the (possibly barrier-blocked) training loop out NOW,
        keeping this actor warm for the repaired gang. Idempotent; safe
        when no loop is running."""
        session = self._session
        if session is None:
            return False
        session.abort(reason)
        return True

    def teardown(self):
        """Dismantle the session (and its collective/jax runtime
        memberships). The ACTOR survives — repair-in-place calls
        setup_session again on the warm process instead of respawning."""
        from ray_tpu import collective

        if getattr(self, "_jax_distributed", False):
            from ray_tpu.train.jax_rendezvous import shutdown_jax_distributed

            shutdown_jax_distributed()
            self._jax_distributed = False
        if self._session is not None:
            try:
                self._session.finish_checkpoints(timeout=30.0)
            except Exception as e:  # noqa: BLE001 — teardown is best-effort
                logger.warning("checkpoint drain at teardown failed: %s", e)
            try:
                collective.destroy_collective_group(self._session.group_name)
            except Exception:
                pass
            _set_session(None)
            self._session = None
        return True


@dataclass
class WorkerMetadata:
    actor: Any
    node_id: str
    pid: int
    world_rank: int = -1
    local_rank: int = -1
    node_rank: int = -1
    # PG bundle this worker was spawned into (stable across the rank
    # re-sort; a replacement reuses the dead worker's bundle).
    bundle_index: int = -1


class WorkerGroup:
    """Creates and addresses a gang of TrainWorker actors (reference:
    worker_group.py:102 start)."""

    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Dict[str, float],
        placement_group=None,
        max_concurrency: int = 4,
    ):
        self.num_workers = num_workers
        self.workers: List[WorkerMetadata] = []
        self._remote_cls = ray_tpu.remote(TrainWorker)
        self._pg = placement_group
        self._opts: Dict[str, Any] = {
            "max_concurrency": max_concurrency,
            "num_cpus": resources_per_worker.get("CPU", 1),
        }
        extra = {k: v for k, v in resources_per_worker.items() if k != "CPU"}
        if extra:
            self._opts["resources"] = extra
        handles = [self._spawn(i) for i in range(num_workers)]
        infos = ray_tpu.get([h.node_info.remote() for h in handles])
        self.workers = [
            WorkerMetadata(actor=h, node_id=info["node_id"], pid=info["pid"],
                           bundle_index=b)
            for b, (h, info) in enumerate(zip(handles, infos))
        ]
        self._assign_ranks()

    def _spawn(self, bundle_index: int):
        """One TrainWorker actor handle (not yet ready) on this group's
        options — bundle-pinned when the group is PG-placed."""
        o = dict(self._opts)
        if self._pg is not None:
            from ray_tpu.util.scheduling_strategies import (
                PlacementGroupSchedulingStrategy,
            )

            o["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                placement_group=self._pg,
                placement_group_bundle_index=bundle_index,
            )
        return self._remote_cls.options(**o).remote()

    # -- elastic repair (backend_executor.repair) ------------------------
    def probe(self, timeout: float = 5.0) -> List[bool]:
        """Liveness per current worker: ping each actor, False for any
        whose ping errors or misses the deadline (SIGKILLed host: the
        ping ref resolves with ActorDiedError ~immediately)."""
        refs = [w.actor.node_info.remote() for w in self.workers]
        ray_tpu.wait(refs, num_returns=len(refs), timeout=timeout)
        alive = []
        for r in refs:
            try:
                ray_tpu.get(r, timeout=0.1)
                alive.append(True)
            except Exception:  # noqa: BLE001 — dead/hung either way
                alive.append(False)
        return alive

    def replace(self, indices: List[int], grace_s: float) -> bool:
        """Spawn replacement workers for the members at list positions
        ``indices`` (reusing each dead member's PG bundle) and wait up to
        ``grace_s`` for ALL to come up. On success the group keeps its
        world size (rejoin); on timeout the spawns are killed and the
        group is untouched (caller decides re-mesh vs rebuild)."""
        spawned = {i: self._spawn(self.workers[i].bundle_index) for i in indices}
        refs = {i: h.node_info.remote() for i, h in spawned.items()}
        ready, _ = ray_tpu.wait(
            list(refs.values()), num_returns=len(refs), timeout=grace_s
        )
        infos = {}
        try:
            if len(ready) < len(refs):
                raise TimeoutError("replacement workers not placeable in time")
            infos = {i: ray_tpu.get(r, timeout=5) for i, r in refs.items()}
        except Exception:  # noqa: BLE001 — timeout, or a replacement died arriving
            for h in spawned.values():
                try:
                    ray_tpu.kill(h)
                # best-effort kill of an abandoned spawn; it may not exist
                # yet  # ray-tpu: lint-ignore[RTL006]
                except Exception:  # noqa: BLE001
                    pass
            return False
        for i, h in spawned.items():
            self.workers[i] = WorkerMetadata(
                actor=h, node_id=infos[i]["node_id"], pid=infos[i]["pid"],
                bundle_index=self.workers[i].bundle_index,
            )
        self._assign_ranks()
        return True

    def shrink(self, dead_indices: List[int]):
        """Drop dead members and re-rank the survivors (elastic
        re-mesh). The caller has checked the floor (min_workers).
        The dead members' PG bundles are RETIRED, not left rescheduling:
        an orphan bundle would otherwise commit (and reserve resources
        forever) the moment cluster capacity returns."""
        dead = set(dead_indices)
        if self._pg is not None:
            bundles = [
                self.workers[i].bundle_index for i in dead
                if self.workers[i].bundle_index >= 0
            ]
            if bundles:
                from ray_tpu.core.api import _require_worker

                try:
                    _require_worker().pg_shrink(self._pg.id, bundles)
                except Exception as e:  # noqa: BLE001 — repair continues
                    logger.warning("pg_shrink failed: %s", e)
        self.workers = [w for i, w in enumerate(self.workers) if i not in dead]
        self.num_workers = len(self.workers)
        for w in self.workers:
            w.world_rank = -1
        self._assign_ranks()

    def _assign_ranks(self):
        """Ranks sorted so co-located workers get contiguous ranks
        (reference: backend_executor.py:369 + worker_group.py:363)."""
        order = sorted(range(len(self.workers)), key=lambda i: (self.workers[i].node_id, i))
        node_rank_map: Dict[str, int] = {}
        local_counter: Dict[str, int] = {}
        for rank, idx in enumerate(order):
            w = self.workers[idx]
            if w.node_id not in node_rank_map:
                node_rank_map[w.node_id] = len(node_rank_map)
                local_counter[w.node_id] = 0
            w.world_rank = rank
            w.node_rank = node_rank_map[w.node_id]
            w.local_rank = local_counter[w.node_id]
            local_counter[w.node_id] += 1
        self.workers.sort(key=lambda w: w.world_rank)

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.actor.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(self.workers[rank].actor.execute.remote(fn, *args, **kwargs))

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w.actor)
            except Exception:
                pass
        self.workers = []

    def __len__(self):
        return len(self.workers)
