"""Generic actor worker group + the TrainWorker actor.

Reference: python/ray/train/_internal/worker_group.py — ``RayTrainWorker``
:19-35 (an actor that executes arbitrary functions), ``execute/
execute_single(_async)`` :233-316, add/remove workers :318-361; rank sort
by node :363.
"""
from __future__ import annotations

import logging
import os
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("ray_tpu.train")

import ray_tpu
from ray_tpu.train.session import TrainContext, _TrainSession, _set_session


class TrainWorker:
    """Actor hosting one training rank. ``run_train_fn`` occupies one actor
    thread for the whole training loop; ``next_result``/``execute`` run on
    the other threads (max_concurrency > 1)."""

    def __init__(self):
        self._session: Optional[_TrainSession] = None
        self._thread: Optional[threading.Thread] = None

    # -- generic execution (reference worker_group.py:19 __execute) -------
    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def node_info(self) -> dict:
        from ray_tpu.runtime_context import get_runtime_context

        return {"node_id": get_runtime_context().get_node_id(), "pid": os.getpid()}

    # -- training lifecycle ----------------------------------------------
    def setup_session(
        self,
        ctx: TrainContext,
        group_name: str,
        latest_checkpoint: Optional[str],
        env_vars: Optional[Dict[str, str]] = None,
        jax_distributed: bool = False,
        dataset_shards: Optional[Dict[str, Any]] = None,
        data_context: Optional[Dict[str, Any]] = None,
    ):
        from ray_tpu import collective

        for k, v in (env_vars or {}).items():
            os.environ[k] = v
        if data_context:
            from ray_tpu.data.context import DataContext

            DataContext.apply_overrides(data_context)
        self._session = _TrainSession(ctx, group_name, latest_checkpoint)
        self._session.dataset_shards = dict(dataset_shards or {})
        _set_session(self._session)
        if jax_distributed:
            # One JAX runtime across the gang: rendezvous via controller
            # KV, then jax.distributed.initialize (multi-host SPMD).
            from ray_tpu.train.jax_rendezvous import setup_jax_distributed

            setup_jax_distributed(ctx.world_rank, ctx.world_size, group_name)
            self._jax_distributed = True
        # Join the rank-sync collective group for report() barriers.
        collective.init_collective_group(
            ctx.world_size, ctx.world_rank, "host", group_name
        )
        return True

    def run_train_fn(self, train_fn: Callable, config: Optional[dict]):
        """Runs the user loop to completion; reports stream via the session."""
        from ray_tpu.train.session import _call_train_fn

        session = self._session
        assert session is not None, "setup_session must run first"
        try:
            _call_train_fn(train_fn, config)
        except BaseException as e:  # noqa: BLE001 — surfaced to the driver
            session.error = e
            session.finished.set()
            raise
        finally:
            # The executor kills this actor soon after the loop returns;
            # push the final step-metric deltas out before that.
            try:
                from ray_tpu.util.metrics import flush

                flush()
            except Exception as e:  # noqa: BLE001 — telemetry only
                logger.debug("final train-metric flush failed: %s", e)
        session.finished.set()
        return True

    def next_result(self):
        assert self._session is not None
        return self._session.next_result()

    def teardown(self):
        from ray_tpu import collective

        if getattr(self, "_jax_distributed", False):
            from ray_tpu.train.jax_rendezvous import shutdown_jax_distributed

            shutdown_jax_distributed()
        if self._session is not None:
            try:
                collective.destroy_collective_group(self._session.group_name)
            except Exception:
                pass
            _set_session(None)
            self._session = None
        return True


@dataclass
class WorkerMetadata:
    actor: Any
    node_id: str
    pid: int
    world_rank: int = -1
    local_rank: int = -1
    node_rank: int = -1


class WorkerGroup:
    """Creates and addresses a gang of TrainWorker actors (reference:
    worker_group.py:102 start)."""

    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Dict[str, float],
        placement_group=None,
        max_concurrency: int = 4,
    ):
        self.num_workers = num_workers
        self.workers: List[WorkerMetadata] = []
        remote_cls = ray_tpu.remote(TrainWorker)
        opts: Dict[str, Any] = {
            "max_concurrency": max_concurrency,
            "num_cpus": resources_per_worker.get("CPU", 1),
        }
        extra = {k: v for k, v in resources_per_worker.items() if k != "CPU"}
        if extra:
            opts["resources"] = extra
        handles = []
        for i in range(num_workers):
            o = dict(opts)
            if placement_group is not None:
                from ray_tpu.util.scheduling_strategies import (
                    PlacementGroupSchedulingStrategy,
                )

                o["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group=placement_group, placement_group_bundle_index=i
                )
            handles.append(remote_cls.options(**o).remote())
        infos = ray_tpu.get([h.node_info.remote() for h in handles])
        self.workers = [
            WorkerMetadata(actor=h, node_id=i["node_id"], pid=i["pid"])
            for h, i in zip(handles, infos)
        ]
        self._assign_ranks()

    def _assign_ranks(self):
        """Ranks sorted so co-located workers get contiguous ranks
        (reference: backend_executor.py:369 + worker_group.py:363)."""
        order = sorted(range(len(self.workers)), key=lambda i: (self.workers[i].node_id, i))
        node_rank_map: Dict[str, int] = {}
        local_counter: Dict[str, int] = {}
        for rank, idx in enumerate(order):
            w = self.workers[idx]
            if w.node_id not in node_rank_map:
                node_rank_map[w.node_id] = len(node_rank_map)
                local_counter[w.node_id] = 0
            w.world_rank = rank
            w.node_rank = node_rank_map[w.node_id]
            w.local_rank = local_counter[w.node_id]
            local_counter[w.node_id] += 1
        self.workers.sort(key=lambda w: w.world_rank)

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.actor.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(self.workers[rank].actor.execute.remote(fn, *args, **kwargs))

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w.actor)
            except Exception:
                pass
        self.workers = []

    def __len__(self):
        return len(self.workers)
