"""JaxTrainer / DataParallelTrainer: the driver-side training loop.

Reference: python/ray/train/data_parallel_trainer.py:428 (training_loop
driving BackendExecutor + TrainingIterator, train/trainer.py:36) and
base_trainer.py:567 (fit). The reference routes fit() through a 1-trial
Tune run; here the trainer drives the executor directly and ray_tpu.tune
reuses the trainer (same layering, fewer hops — Tune-on-Train rather than
Train-on-Tune).

SPMD note (SURVEY.md §7 hard parts): on a TPU pod each worker is one host
of the slice; the gang is placed STRICT_PACK/SPREAD via the scaling
config's placement strategy, and a worker failure fails the step for the
whole mesh. Recovery (FailureConfig.max_failures budget) is ELASTIC:
surviving workers stay warm while the executor repairs in place — a
replacement rejoins at the same world size within
FailureConfig.elastic_grace_s, or the gang re-meshes down to
ScalingConfig.min_workers and resumes from the last COMPLETE checkpoint
at the smaller data-parallel width (backend_executor.restart).
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.backend_executor import (
    TRAINABLE_FAILURES,
    BackendExecutor,
    TrainingFailedError,
)
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig

logger = logging.getLogger("ray_tpu.train")


@dataclass
class Result:
    """Reference: ray.train.Result (train/v2/result.py shape)."""

    metrics: Optional[dict]
    checkpoint: Optional[Checkpoint]
    path: str
    error: Optional[BaseException] = None
    metrics_history: List[dict] = field(default_factory=list)
    # One entry per gang recovery this run absorbed: {mode: rejoin |
    # remesh | rebuild | none, detect_ms, repair_ms, resume_ms,
    # world_size, dead_ranks, ts} (backend_executor.recovery_log).
    recoveries: List[dict] = field(default_factory=list)

    @property
    def best_checkpoints(self):
        return [(self.checkpoint, self.metrics)] if self.checkpoint else []


class DataParallelTrainer:
    """Runs ``train_loop_per_worker`` on N gang-scheduled workers.

    The loop calls ``ray_tpu.train.report(metrics, checkpoint=...)``; rank
    sync + checkpoint persistence + top-k retention happen here.
    """

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self._train_fn = train_loop_per_worker
        self._config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._resume_from = resume_from_checkpoint
        # name -> ray_tpu.data.Dataset; each fit() attempt splits every
        # dataset num_workers ways behind a ShardCoordinator actor and the
        # loop pulls its split via train.get_dataset_shard(name) (the
        # pipelined ingest path — reference: DataParallelTrainer datasets).
        self._datasets: Dict[str, Any] = dict(datasets or {})

    def _make_shard_actors(self, num_splits: int) -> Dict[str, Any]:
        if not self._datasets:
            return {}
        from ray_tpu.data.shard import create_shard_coordinator

        # num_splits follows the EXECUTOR's current world size, not the
        # configured one — an elastic re-mesh resumes at fewer ranks and
        # every dataset must re-split to the new width.
        return {
            name: create_shard_coordinator(ds, num_splits)
            for name, ds in self._datasets.items()
        }

    def _stop_shard_actors(self):
        import ray_tpu

        for name, actor in getattr(self, "_shard_actors", {}).items():
            try:
                ray_tpu.kill(actor)
            except Exception as e:  # noqa: BLE001 — best-effort teardown
                logger.debug("shard coordinator %s kill failed: %s", name, e)
        self._shard_actors = {}

    def fit(self) -> Result:
        storage = self.run_config.resolve_storage()
        ckpt_cfg: CheckpointConfig = self.run_config.checkpoint_config
        manager = CheckpointManager.restore_state(
            storage,
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attr=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
        )
        if self._resume_from is not None and manager.latest is None:
            manager.register(self._resume_from, {}, -1)

        failure_cfg: FailureConfig = self.run_config.failure_config
        experiment_name = self.run_config.name or "train_run"
        executor = BackendExecutor(
            self.scaling_config,
            experiment_name=experiment_name,
            storage_path=storage,
            max_failures=failure_cfg.max_failures,
            elastic_grace_s=failure_cfg.elastic_grace_s,
            checkpoint_async=ckpt_cfg.async_upload,
        )

        last_metrics: Optional[dict] = None
        history: List[dict] = []
        error: Optional[BaseException] = None
        try:
            executor.start()
            while True:
                # manager.latest only yields COMPLETE checkpoints: an
                # async upload torn by the very death we are recovering
                # from is skipped, never resumed into.
                latest = manager.latest.checkpoint.path if manager.latest else None
                # Fresh shard coordinators per attempt, split to the
                # executor's CURRENT width (a re-mesh resumes narrower):
                # a gang restart replays the datasets from the beginning
                # (streams are single-pass; recovery restarts the epoch).
                self._stop_shard_actors()
                from ray_tpu.train.session import train_metrics

                tmetrics = train_metrics()
                run_tag = {"run": experiment_name}
                run_refs = None
                # setup_sessions/start_training sit INSIDE the try: a
                # gang member dying mid-repair (double fault) must
                # consume a retry like any other failure, not escape
                # fit() as a raw exception.
                try:
                    self._shard_actors = self._make_shard_actors(
                        executor.world_size
                    )
                    executor.setup_sessions(
                        latest, dataset_shards=self._shard_actors,
                        ckpt_index_start=manager.next_index,
                    )
                    run_refs = executor.start_training(
                        self._train_fn, self._config
                    )
                    while True:
                        t_wait = time.monotonic()
                        results = executor.next_results(run_refs)
                        tmetrics.driver_wait_ms.observe(
                            (time.monotonic() - t_wait) * 1000.0, run_tag
                        )
                        if results is None:
                            break
                        rank0 = results[0]
                        last_metrics = rank0["metrics"]
                        history.append(rank0["metrics"])
                        if rank0["checkpoint"]:
                            manager.register(
                                Checkpoint(rank0["checkpoint"]),
                                rank0["metrics"],
                                rank0["ckpt_index"],
                            )
                    # Drain the run refs so loop errors surface.
                    import ray_tpu

                    ray_tpu.get(run_refs)
                    break  # clean finish
                except TRAINABLE_FAILURES as e:
                    logger.warning("training failed: %s", e)
                    if executor.can_retry():
                        manager.sync_from_storage()
                        executor.restart(run_refs=run_refs)
                        continue
                    lf = executor.last_failure
                    where = (
                        f" (last failure: rank {lf.rank} on node "
                        f"{lf.node[:12] or '?'}: {lf.reason})"
                        if lf is not None else ""
                    )
                    error = TrainingFailedError(
                        f"training failed after {executor.failures} "
                        f"failure(s); root cause: {e!r}{where}"
                    )
                    error.__cause__ = e
                    break
        finally:
            executor.shutdown()
            self._stop_shard_actors()

        best = manager.best
        return Result(
            metrics=last_metrics,
            checkpoint=best.checkpoint if best else None,
            path=storage,
            error=error,
            metrics_history=history,
            recoveries=list(executor.recovery_log),
        )


class JaxTrainer(DataParallelTrainer):
    """TPU-flavored DataParallelTrainer (reference analogue: TorchTrainer
    via train/torch/config.py; the XLA backend precedent is
    train/torch/xla/config.py TorchXLAConfig).

    The per-worker loop builds its mesh from ray_tpu.parallel (MeshPlan →
    jax.sharding.Mesh); on a multi-host slice each worker is one host
    process and jax.distributed-style rendezvous happens through the train
    collective group's KV namespace.
    """
