"""TorchTrainer — torch.distributed data-parallel training on the gang.

Reference: python/ray/train/torch/config.py (_TorchBackend picks
MASTER_ADDR/PORT from worker 0 and calls dist.init_process_group on every
worker, :153/:66) and train/torch/train_loop_utils.py (prepare_model :162
DDP wrap, get_devices :115). CPU/gloo is the supported fabric here —
torch-on-TPU is out of scope (the TPU path is JaxTrainer); TorchTrainer
exists for capability parity and for CPU-side torch workloads riding the
same gang scheduler, checkpointing, and report() machinery.

Rendezvous: rank 0 publishes host:port through the cluster KV (the same
role the reference gives worker 0's env vars), everyone else polls —
exactly the Rendezvous shape of nccl_collective_group.py:29.
"""
from __future__ import annotations

import socket
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.session import get_context
from ray_tpu.train.trainer import DataParallelTrainer


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _rendezvous_key() -> bytes:
    # Keyed by the gang's unique group name (fresh per (re)start), not the
    # experiment name — an elastic restart must not read the previous
    # incarnation's stale rank-0 address.
    from ray_tpu.train.session import _get_session

    return f"torch_dist/{_get_session().group_name}".encode()


def init_torch_process_group(timeout_s: float = 60.0) -> bool:
    """Gloo process-group init inside a train worker; returns False when
    world_size == 1 (no group needed)."""
    import datetime

    import torch.distributed as dist

    from ray_tpu.collective.host_group import _multi_host
    from ray_tpu.experimental import internal_kv

    ctx = get_context()
    if ctx.get_world_size() <= 1:
        return False
    key = _rendezvous_key()
    if ctx.get_world_rank() == 0:
        # Same address policy as the collective host group: only advertise
        # the resolved hostname across hosts (it is often 127.0.1.1 via
        # /etc/hosts); single-host gangs rendezvous on loopback.
        addr = (
            socket.gethostbyname(socket.gethostname())
            if _multi_host()
            else "127.0.0.1"
        )
        port = _free_port()
        internal_kv._internal_kv_put(key, f"{addr}:{port}".encode())
        master = f"{addr}:{port}"
    else:
        deadline = time.time() + timeout_s
        master = None
        while time.time() < deadline:
            v = internal_kv._internal_kv_get(key)
            if v:
                master = v.decode()
                break
            time.sleep(0.05)
        if master is None:
            raise TimeoutError("torch rendezvous: rank-0 address never appeared")
    dist.init_process_group(
        backend="gloo",
        init_method=f"tcp://{master}",
        rank=ctx.get_world_rank(),
        world_size=ctx.get_world_size(),
        # Bound the store handshake too — otherwise a dead peer stalls the
        # gang for torch's 30-minute default, far past the elastic-restart
        # budget.
        timeout=datetime.timedelta(seconds=timeout_s),
    )
    if ctx.get_world_rank() == 0:
        # init returning on rank 0 means every rank has joined the store;
        # the advertised address is no longer needed.
        internal_kv._internal_kv_del(key)
    return True


def prepare_model(model):
    """DDP-wrap when a process group is live (reference:
    train_loop_utils.py:162 prepare_model)."""
    import torch.distributed as dist

    if dist.is_available() and dist.is_initialized() and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


def get_device():
    """Reference: train_loop_utils.py:115 get_devices — CPU fabric here."""
    import torch

    return torch.device("cpu")


class _EpochSteppingLoader:
    """DataLoader proxy that calls ``sampler.set_epoch`` on every
    ``__iter__`` so shuffled loaders reshuffle each epoch (the reference's
    prepare_data_loader does the same inside its wrapper)."""

    def __init__(self, loader, sampler):
        self._loader = loader
        self._sampler = sampler
        self._epoch = 0

    def __iter__(self):
        self._sampler.set_epoch(self._epoch)
        self._epoch += 1
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)

    def __getattr__(self, name):
        return getattr(self._loader, name)


def prepare_data_loader(data_loader):
    """Shard a DataLoader across ranks with DistributedSampler (reference:
    train_loop_utils.py prepare_data_loader). Preserves the original
    loader's shuffle semantics and worker/pinning configuration; loaders
    this can't shard faithfully (IterableDataset, custom batch_sampler)
    are returned unchanged."""
    import logging

    import torch.distributed as dist
    import torch.utils.data as tud

    if not (dist.is_available() and dist.is_initialized() and dist.get_world_size() > 1):
        return data_loader
    if isinstance(data_loader.dataset, tud.IterableDataset) or data_loader.batch_size is None:
        logging.getLogger("ray_tpu.train").warning(
            "prepare_data_loader: cannot shard an IterableDataset or a "
            "batch_sampler loader; returning it unsharded"
        )
        return data_loader
    shuffle = isinstance(data_loader.sampler, tud.RandomSampler)
    sampler = tud.distributed.DistributedSampler(
        data_loader.dataset, shuffle=shuffle, drop_last=data_loader.drop_last
    )
    loader = tud.DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        pin_memory=data_loader.pin_memory,
        drop_last=data_loader.drop_last,
        worker_init_fn=data_loader.worker_init_fn,
        generator=data_loader.generator,
        persistent_workers=getattr(data_loader, "persistent_workers", False),
    )
    return _EpochSteppingLoader(loader, sampler)


class TorchTrainer(DataParallelTrainer):
    """DataParallelTrainer whose workers run inside an initialized gloo
    process group (reference: TorchTrainer + _TorchBackend.on_start)."""

    def __init__(self, train_loop_per_worker: Callable, **kw):
        def bootstrap(config: Optional[Dict[str, Any]] = None):
            import torch.distributed as dist

            from ray_tpu.train.session import _call_train_fn

            inited = init_torch_process_group()
            try:
                _call_train_fn(train_loop_per_worker, config)
            finally:
                if inited and dist.is_initialized():
                    dist.destroy_process_group()

        super().__init__(bootstrap, **kw)
