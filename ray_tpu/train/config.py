"""Train configuration dataclasses.

Reference: python/ray/air/config.py — ``ScalingConfig`` :102,
``FailureConfig`` :394, ``CheckpointConfig`` :444, ``RunConfig`` :593.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class ScalingConfig:
    """How many workers and what each one holds.

    ``use_tpu`` replaces the reference's ``use_gpu``; ``topology`` lets the
    TPU scheduler gang-place workers onto one ICI slice (STRICT_PACK).
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # e.g. "v5p-16": informs slice-aware placement; None = any chips.
    topology: Optional[str] = None
    # Multi-host SPMD: every worker is one host process of a single JAX
    # runtime — ranks rendezvous through the controller KV and call
    # jax.distributed.initialize before the training loop, so
    # jax.devices() spans the gang (reference precedent:
    # train/torch/xla/config.py env-var rendezvous + init_process_group).
    use_jax_distributed: bool = False
    # Extra env vars applied in each worker BEFORE jax initializes
    # (platform pinning, XLA flags).
    worker_env: Optional[Dict[str, str]] = None

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            res = dict(self.resources_per_worker)
            if self.use_tpu:
                res.setdefault("TPU", 1)
            return res
        res = {"CPU": 1.0}
        if self.use_tpu:
            res["TPU"] = 1.0
        return res

    def bundles(self):
        return [self.worker_resources() for _ in range(self.num_workers)]


@dataclass
class FailureConfig:
    """max_failures: worker-group restarts before giving up (-1 = infinite)."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Top-k checkpoint retention (reference:
    train/_internal/checkpoint_manager.py:43)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)

    def resolve_storage(self) -> str:
        from ray_tpu.utils import cloudfs

        base = self.storage_path or os.path.expanduser("~/ray_tpu_results")
        name = self.name or "train_run"
        # storage_path may be a cloud URI (gs://bucket/runs) — cloudfs.join
        # keeps the scheme intact (reference: storage.py:352 pyarrow.fs).
        return cloudfs.join(base, name)
