"""Train configuration dataclasses.

Reference: python/ray/air/config.py — ``ScalingConfig`` :102,
``FailureConfig`` :394, ``CheckpointConfig`` :444, ``RunConfig`` :593.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class ScalingConfig:
    """How many workers and what each one holds.

    ``use_tpu`` replaces the reference's ``use_gpu``; ``topology`` lets the
    TPU scheduler gang-place workers onto one ICI slice (STRICT_PACK).
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # Elastic floor: when a worker's host dies and no replacement becomes
    # placeable within FailureConfig.elastic_grace_s, the gang RE-MESHES
    # to the surviving count (resuming from checkpoint at the smaller
    # data-parallel width) as long as it stays >= min_workers. None (the
    # default) pins the world shape: recovery always waits for a
    # replacement (rejoin) and a gang below num_workers is a failure.
    min_workers: Optional[int] = None
    # e.g. "v5p-16": informs slice-aware placement; None = any chips.
    topology: Optional[str] = None
    # Multi-host SPMD: every worker is one host process of a single JAX
    # runtime — ranks rendezvous through the controller KV and call
    # jax.distributed.initialize before the training loop, so
    # jax.devices() spans the gang (reference precedent:
    # train/torch/xla/config.py env-var rendezvous + init_process_group).
    use_jax_distributed: bool = False
    # Extra env vars applied in each worker BEFORE jax initializes
    # (platform pinning, XLA flags).
    worker_env: Optional[Dict[str, str]] = None

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            res = dict(self.resources_per_worker)
            if self.use_tpu:
                res.setdefault("TPU", 1)
            return res
        res = {"CPU": 1.0}
        if self.use_tpu:
            res["TPU"] = 1.0
        return res

    def bundles(self):
        return [self.worker_resources() for _ in range(self.num_workers)]


@dataclass
class FailureConfig:
    """max_failures: gang recoveries before giving up (-1 = infinite).

    Since the elastic-recovery rework a "failure" no longer implies a
    tear-down-and-rebuild: surviving workers are kept warm and the group
    repairs in place (replacement rejoin at the same world size, or
    re-mesh to the surviving count when ScalingConfig.min_workers
    allows). ``elastic_grace_s`` bounds how long a repair waits for a
    replacement worker before falling back to re-mesh (or, without an
    elastic floor, keeps waiting until the grace expires and the repair
    degrades to a full gang rebuild)."""

    max_failures: int = 0
    elastic_grace_s: float = 10.0


@dataclass
class CheckpointConfig:
    """Top-k checkpoint retention (reference:
    train/_internal/checkpoint_manager.py:43).

    ``async_upload=True`` makes ``train.report(checkpoint=...)``
    non-blocking: the step pays only for a local host-side snapshot of
    the checkpoint directory; persistence into run storage happens on a
    per-rank writer thread with crash-consistent commit markers (the
    ``.complete`` marker is written by rank 0's writer only after every
    rank's upload landed, so a death mid-upload can never leave a torn
    "latest" — CheckpointManager.latest/resume only trust complete
    checkpoints)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    async_upload: bool = False

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)

    def resolve_storage(self) -> str:
        from ray_tpu.utils import cloudfs

        base = self.storage_path or os.path.expanduser("~/ray_tpu_results")
        name = self.name or "train_run"
        # storage_path may be a cloud URI (gs://bucket/runs) — cloudfs.join
        # keeps the scheme intact (reference: storage.py:352 pyarrow.fs).
        return cloudfs.join(base, name)
