"""Checkpoint object + top-k retention manager.

Reference: python/ray/train/_checkpoint.py (Checkpoint = directory handle)
and train/_internal/checkpoint_manager.py:43,80 (_CheckpointManager).
Storage paths resolve through ray_tpu.utils.cloudfs (reference:
train/_internal/storage.py:352 uses pyarrow.fs the same way), so
``storage_path="gs://bucket/run"`` works wherever a local path does.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
from contextlib import contextmanager
from typing import List, Optional

from ray_tpu.utils import cloudfs


class Checkpoint:
    """A handle to a directory of checkpoint data (local or cloud URI)."""

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(cloudfs.normalize(path))

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or tempfile.mkdtemp(prefix="rt_ckpt_")
        if cloudfs.normalize(dest) != cloudfs.normalize(self.path):
            cloudfs.copy_dir(self.path, dest)
        return dest

    @contextmanager
    def as_directory(self):
        """A LOCAL directory view (downloads cloud checkpoints)."""
        local, is_tmp = cloudfs.as_local_dir(self.path)
        try:
            yield local
        finally:
            if is_tmp:
                shutil.rmtree(local, ignore_errors=True)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"


class ReportedCheckpoint:
    def __init__(self, checkpoint: Checkpoint, metrics: dict, index: int):
        self.checkpoint = checkpoint
        self.metrics = metrics
        self.index = index


class CheckpointManager:
    """Keeps the top-k checkpoints by score under ``root`` (reference:
    checkpoint_manager.py:80 register_checkpoint)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None,
                 score_attr: Optional[str] = None, score_order: str = "max"):
        self.root = root
        self.num_to_keep = num_to_keep
        self.score_attr = score_attr
        self.score_order = score_order
        self._kept: List[ReportedCheckpoint] = []
        cloudfs.makedirs(root)

    @property
    def latest(self) -> Optional[ReportedCheckpoint]:
        return self._kept[-1] if self._kept else None

    @property
    def best(self) -> Optional[ReportedCheckpoint]:
        if not self._kept:
            return None
        if not self.score_attr:
            return self._kept[-1]
        scored = [c for c in self._kept if self.score_attr in c.metrics]
        if not scored:
            return self._kept[-1]
        return max(
            scored,
            key=lambda c: c.metrics[self.score_attr] * (1 if self.score_order == "max" else -1),
        )

    def register(self, checkpoint: Checkpoint, metrics: dict, index: int) -> ReportedCheckpoint:
        rc = ReportedCheckpoint(checkpoint, metrics, index)
        self._kept.append(rc)
        cloudfs.write_text(
            cloudfs.join(self.root, "checkpoints.json"),
            json.dumps(
                [{"path": c.checkpoint.path, "metrics": c.metrics, "index": c.index}
                 for c in self._kept]
            ),
        )
        self._evict()
        return rc

    def _evict(self):
        if self.num_to_keep is None or len(self._kept) <= self.num_to_keep:
            return
        # Never evict the most recent (resume anchor); evict worst/oldest.
        candidates = self._kept[:-1]
        if self.score_attr:
            candidates = sorted(
                candidates,
                key=lambda c: c.metrics.get(
                    self.score_attr, float("-inf") if self.score_order == "max" else float("inf")
                ),
                reverse=(self.score_order == "min"),
            )
        while len(self._kept) > self.num_to_keep and candidates:
            victim = candidates.pop(0)
            self._kept.remove(victim)
            cloudfs.delete(victim.checkpoint.path)

    def sync_from_storage(self):
        """Register checkpoints that were fully persisted (``.complete``
        marker — all ranks past the report barrier) but whose report the
        driver never consumed because the gang died first."""
        known = {c.checkpoint.path for c in self._kept}
        found = []
        for entry in sorted(cloudfs.listdir(self.root)):
            path = cloudfs.join(self.root, entry)
            if (
                entry.startswith("checkpoint_")
                and cloudfs.isdir(path)
                and cloudfs.exists(cloudfs.join(path, ".complete"))
                and path not in known
            ):
                try:
                    index = int(entry.split("_")[-1])
                except ValueError:
                    continue
                found.append((index, path))
        for index, path in sorted(found):
            self.register(Checkpoint(path), {}, index)

    @classmethod
    def restore_state(cls, root: str, **kwargs) -> "CheckpointManager":
        mgr = cls(root, **kwargs)
        state_file = cloudfs.join(root, "checkpoints.json")
        if cloudfs.exists(state_file):
            for entry in json.loads(cloudfs.read_text(state_file)):
                if cloudfs.exists(entry["path"]):
                    mgr._kept.append(
                        ReportedCheckpoint(
                            Checkpoint(entry["path"]), entry["metrics"], entry["index"]
                        )
                    )
        return mgr
