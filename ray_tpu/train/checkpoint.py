"""Checkpoint object + top-k retention manager.

Reference: python/ray/train/_checkpoint.py (Checkpoint = directory handle)
and train/_internal/checkpoint_manager.py:43,80 (_CheckpointManager).
Storage is a filesystem path (local or mounted GCS/NFS — the reference uses
pyarrow.fs; local-path semantics are the common denominator here, and orbax
handles cloud URIs natively on the TPU path).
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
from contextlib import contextmanager
from typing import List, Optional


class Checkpoint:
    """A handle to a directory of checkpoint data."""

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path))

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or tempfile.mkdtemp(prefix="rt_ckpt_")
        if os.path.abspath(dest) != os.path.abspath(self.path):
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextmanager
    def as_directory(self):
        yield self.path

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"


class ReportedCheckpoint:
    def __init__(self, checkpoint: Checkpoint, metrics: dict, index: int):
        self.checkpoint = checkpoint
        self.metrics = metrics
        self.index = index


class CheckpointManager:
    """Keeps the top-k checkpoints by score under ``root`` (reference:
    checkpoint_manager.py:80 register_checkpoint)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None,
                 score_attr: Optional[str] = None, score_order: str = "max"):
        self.root = root
        self.num_to_keep = num_to_keep
        self.score_attr = score_attr
        self.score_order = score_order
        self._kept: List[ReportedCheckpoint] = []
        os.makedirs(root, exist_ok=True)

    @property
    def latest(self) -> Optional[ReportedCheckpoint]:
        return self._kept[-1] if self._kept else None

    @property
    def best(self) -> Optional[ReportedCheckpoint]:
        if not self._kept:
            return None
        if not self.score_attr:
            return self._kept[-1]
        scored = [c for c in self._kept if self.score_attr in c.metrics]
        if not scored:
            return self._kept[-1]
        return max(
            scored,
            key=lambda c: c.metrics[self.score_attr] * (1 if self.score_order == "max" else -1),
        )

    def register(self, checkpoint: Checkpoint, metrics: dict, index: int) -> ReportedCheckpoint:
        rc = ReportedCheckpoint(checkpoint, metrics, index)
        self._kept.append(rc)
        with open(os.path.join(self.root, "checkpoints.json"), "w") as f:
            json.dump(
                [{"path": c.checkpoint.path, "metrics": c.metrics, "index": c.index}
                 for c in self._kept],
                f,
            )
        self._evict()
        return rc

    def _evict(self):
        if self.num_to_keep is None or len(self._kept) <= self.num_to_keep:
            return
        # Never evict the most recent (resume anchor); evict worst/oldest.
        candidates = self._kept[:-1]
        if self.score_attr:
            candidates = sorted(
                candidates,
                key=lambda c: c.metrics.get(
                    self.score_attr, float("-inf") if self.score_order == "max" else float("inf")
                ),
                reverse=(self.score_order == "min"),
            )
        while len(self._kept) > self.num_to_keep and candidates:
            victim = candidates.pop(0)
            self._kept.remove(victim)
            shutil.rmtree(victim.checkpoint.path, ignore_errors=True)

    def sync_from_storage(self):
        """Register checkpoints that were fully persisted (``.complete``
        marker — all ranks past the report barrier) but whose report the
        driver never consumed because the gang died first."""
        known = {c.checkpoint.path for c in self._kept}
        found = []
        for entry in sorted(os.listdir(self.root)):
            path = os.path.join(self.root, entry)
            if (
                entry.startswith("checkpoint_")
                and os.path.isdir(path)
                and os.path.exists(os.path.join(path, ".complete"))
                and path not in known
            ):
                try:
                    index = int(entry.split("_")[-1])
                except ValueError:
                    continue
                found.append((index, path))
        for index, path in sorted(found):
            self.register(Checkpoint(path), {}, index)

    @classmethod
    def restore_state(cls, root: str, **kwargs) -> "CheckpointManager":
        mgr = cls(root, **kwargs)
        state_file = os.path.join(root, "checkpoints.json")
        if os.path.exists(state_file):
            with open(state_file) as f:
                for entry in json.load(f):
                    if os.path.exists(entry["path"]):
                        mgr._kept.append(
                            ReportedCheckpoint(
                                Checkpoint(entry["path"]), entry["metrics"], entry["index"]
                            )
                        )
        return mgr
