"""Checkpoint object + top-k retention manager + async upload writer.

Reference: python/ray/train/_checkpoint.py (Checkpoint = directory handle)
and train/_internal/checkpoint_manager.py:43,80 (_CheckpointManager).
Storage paths resolve through ray_tpu.utils.cloudfs (reference:
train/_internal/storage.py:352 uses pyarrow.fs the same way), so
``storage_path="gs://bucket/run"`` works wherever a local path does.

Crash consistency contract (async uploads): a checkpoint directory is
DURABLE only once it carries a ``.complete`` marker, written by rank 0's
writer after every rank's ``.rank_<k>.uploaded`` marker landed. Every
resume path (:attr:`CheckpointManager.latest`,
:meth:`CheckpointManager.sync_from_storage`) trusts only complete
checkpoints — a death mid-upload leaves a torn directory that is simply
never resumed from, never a corrupt "latest".
"""
from __future__ import annotations

import json
import logging
import os
import queue
import shutil
import tempfile
import threading
from contextlib import contextmanager
from typing import Callable, List, Optional

from ray_tpu.utils import cloudfs

logger = logging.getLogger("ray_tpu.train")

COMPLETE_MARKER = ".complete"


def rank_marker(rank: int) -> str:
    return f".rank_{rank:04d}.uploaded"


class Checkpoint:
    """A handle to a directory of checkpoint data (local or cloud URI)."""

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(cloudfs.normalize(path))

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or tempfile.mkdtemp(prefix="rt_ckpt_")
        if cloudfs.normalize(dest) != cloudfs.normalize(self.path):
            cloudfs.copy_dir(self.path, dest)
        return dest

    @contextmanager
    def as_directory(self):
        """A LOCAL directory view (downloads cloud checkpoints)."""
        local, is_tmp = cloudfs.as_local_dir(self.path)
        try:
            yield local
        finally:
            if is_tmp:
                shutil.rmtree(local, ignore_errors=True)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"


class ReportedCheckpoint:
    def __init__(self, checkpoint: Checkpoint, metrics: dict, index: int):
        self.checkpoint = checkpoint
        self.metrics = metrics
        self.index = index


class CheckpointManager:
    """Keeps the top-k checkpoints by score under ``root`` (reference:
    checkpoint_manager.py:80 register_checkpoint)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None,
                 score_attr: Optional[str] = None, score_order: str = "max"):
        self.root = root
        self.num_to_keep = num_to_keep
        self.score_attr = score_attr
        self.score_order = score_order
        self._kept: List[ReportedCheckpoint] = []
        cloudfs.makedirs(root)

        # Positive completeness checks are cached (complete never
        # un-happens); pending async uploads re-check on each read.
        self._verified: set = set()

    def _is_complete(self, rc: ReportedCheckpoint) -> bool:
        path = rc.checkpoint.path
        if path in self._verified:
            return True
        base = path.rstrip("/").rsplit("/", 1)[-1]
        if not base.startswith("checkpoint_"):
            # External checkpoint (resume_from_checkpoint) — not written
            # by a session, no marker convention; trust the caller.
            self._verified.add(path)
            return True
        if cloudfs.exists(cloudfs.join(path, COMPLETE_MARKER)):
            self._verified.add(path)
            return True
        return False

    @property
    def latest(self) -> Optional[ReportedCheckpoint]:
        """Newest COMPLETE checkpoint (the resume anchor). Registered
        checkpoints whose async upload has not committed yet — or whose
        writer died mid-upload — are skipped, never resumed from."""
        for rc in reversed(self._kept):
            if self._is_complete(rc):
                return rc
        return None

    @property
    def next_index(self) -> int:
        """First unused checkpoint index: a repaired/restarted session
        continues numbering here so a new incarnation can never write
        into a directory an earlier one already touched. Scans the
        ON-DISK directories too, not just registered checkpoints: a torn
        async upload (rank markers present, no ``.complete``) is never
        registered, and reusing its index would let the new incarnation's
        rank 0 count the STALE rank markers toward its commit and mark a
        mixed-incarnation checkpoint complete."""
        newest = max((c.index for c in self._kept), default=-1)
        try:
            for entry in cloudfs.listdir(self.root):
                if entry.startswith("checkpoint_"):
                    try:
                        newest = max(newest, int(entry.split("_")[-1]))
                    except ValueError:
                        continue
        except Exception as e:  # noqa: BLE001 — storage listing is advisory
            logger.debug("next_index storage scan failed: %s", e)
        return newest + 1

    @property
    def best(self) -> Optional[ReportedCheckpoint]:
        if not self._kept:
            return None
        if not self.score_attr:
            return self._kept[-1]
        scored = [c for c in self._kept if self.score_attr in c.metrics]
        if not scored:
            return self._kept[-1]
        return max(
            scored,
            key=lambda c: c.metrics[self.score_attr] * (1 if self.score_order == "max" else -1),
        )

    def register(self, checkpoint: Checkpoint, metrics: dict, index: int) -> ReportedCheckpoint:
        rc = ReportedCheckpoint(checkpoint, metrics, index)
        self._kept.append(rc)
        cloudfs.write_text(
            cloudfs.join(self.root, "checkpoints.json"),
            json.dumps(
                [{"path": c.checkpoint.path, "metrics": c.metrics, "index": c.index}
                 for c in self._kept]
            ),
        )
        self._evict()
        return rc

    def _evict(self):
        if self.num_to_keep is None or len(self._kept) <= self.num_to_keep:
            return
        # Never evict the most recent NOR the newest complete one (the
        # resume anchor — with async uploads they can be different
        # checkpoints), and never evict ANY not-yet-complete entry: its
        # writers may still be uploading, and deleting under them would
        # recreate the directory piecemeal and let rank 0 commit a torn
        # checkpoint. Incomplete entries either commit (evictable later)
        # or stay torn and untrusted — harmless either way.
        protected = {id(self._kept[-1])}
        anchor = self.latest
        if anchor is not None:
            protected.add(id(anchor))
        candidates = [
            c for c in self._kept
            if id(c) not in protected and self._is_complete(c)
        ]
        if self.score_attr:
            candidates = sorted(
                candidates,
                key=lambda c: c.metrics.get(
                    self.score_attr, float("-inf") if self.score_order == "max" else float("inf")
                ),
                reverse=(self.score_order == "min"),
            )
        while len(self._kept) > self.num_to_keep and candidates:
            victim = candidates.pop(0)
            self._kept.remove(victim)
            cloudfs.delete(victim.checkpoint.path)

    def sync_from_storage(self):
        """Register checkpoints that were fully persisted (``.complete``
        marker — all ranks past the report barrier) but whose report the
        driver never consumed because the gang died first."""
        known = {c.checkpoint.path for c in self._kept}
        found = []
        for entry in sorted(cloudfs.listdir(self.root)):
            path = cloudfs.join(self.root, entry)
            if (
                entry.startswith("checkpoint_")
                and cloudfs.isdir(path)
                and cloudfs.exists(cloudfs.join(path, COMPLETE_MARKER))
                and path not in known
            ):
                try:
                    index = int(entry.split("_")[-1])
                except ValueError:
                    continue
                found.append((index, path))
        for index, path in sorted(found):
            self.register(Checkpoint(path), {}, index)

    @classmethod
    def restore_state(cls, root: str, **kwargs) -> "CheckpointManager":
        mgr = cls(root, **kwargs)
        state_file = cloudfs.join(root, "checkpoints.json")
        if cloudfs.exists(state_file):
            for entry in json.loads(cloudfs.read_text(state_file)):
                if cloudfs.exists(entry["path"]):
                    mgr._kept.append(
                        ReportedCheckpoint(
                            Checkpoint(entry["path"]), entry["metrics"], entry["index"]
                        )
                    )
        return mgr


class WriterKilled(BaseException):
    """Raised by a test fault hook to simulate the writer thread dying
    at an exact point (BaseException so user-code except clauses in the
    hook path can't swallow it)."""


class CheckpointWriter:
    """Per-rank background uploader for non-blocking checkpoints.

    ``train.report(checkpoint=..)`` hands this thread a (staging_dir,
    dest) job; the step itself blocks only for the local host-side
    snapshot. The writer uploads the rank's files into the shared dest,
    commits the per-rank marker, and — on rank 0 — waits for every
    rank's marker before atomically committing ``.complete`` (the only
    thing resume paths trust) and enqueueing nothing further until the
    next report. Reference analogue: orbax's async checkpointing commit
    protocol (commit_success file after all hosts' writes).

    ``fault_hook(point, dest)`` is the deterministic chaos seam: tests
    raise :class:`WriterKilled` at seeded points ("before_upload",
    "mid_upload", "before_rank_marker", "before_complete") to prove a
    death anywhere mid-upload never yields a trusted torn checkpoint.
    """

    _POINTS = ("before_upload", "mid_upload", "before_rank_marker",
               "before_complete")

    def __init__(self, world_rank: int, world_size: int,
                 fault_hook: Optional[Callable[[str, str], None]] = None,
                 complete_timeout_s: float = 120.0):
        self.world_rank = world_rank
        self.world_size = world_size
        self.fault_hook = fault_hook
        self.complete_timeout_s = complete_timeout_s
        self.error: Optional[BaseException] = None
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def _hook(self, point: str, dest: str):
        if self.fault_hook is not None:
            self.fault_hook(point, dest)

    def submit(self, staging_dir: str, dest: str):
        """Enqueue one upload job. Raises a previous job's error (the
        loop must learn persistence is failing, not silently lose
        durability)."""
        self.check()
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"ckpt-writer-r{self.world_rank}",
                )
                self._thread.start()
        self._q.put((staging_dir, dest))

    def check(self):
        if self.error is not None:
            err, self.error = self.error, None
            raise RuntimeError(
                f"async checkpoint upload failed: {err!r}"
            ) from err

    def _run(self):
        while True:
            # writer thread parks for the next snapshot by design  # ray-tpu: lint-ignore[RTL008]
            job = self._q.get()
            if job is None:
                # Sentinel counts toward unfinished_tasks like any job —
                # settle it or every later drain() sees a phantom pending
                # upload on a dead thread.
                self._q.task_done()
                return
            staging, dest = job
            try:
                self._upload(staging, dest)
            except WriterKilled as e:
                # Simulated writer death: the thread is gone mid-job, the
                # torn dest has no .complete and never will.
                self.error = e
                return
            except BaseException as e:  # noqa: BLE001 — surfaced on next submit
                self.error = e
            finally:
                self._q.task_done()

    def _upload(self, staging: str, dest: str):
        self._hook("before_upload", dest)
        cloudfs.makedirs(dest)
        # Per-file copy with a deterministic mid-upload fault point after
        # the first file — "mid_upload" means dest holds a PARTIAL rank
        # shard when the writer dies there.
        entries = sorted(os.listdir(staging))
        for i, entry in enumerate(entries):
            src = os.path.join(staging, entry)
            if os.path.isdir(src):
                cloudfs.copy_dir(src, cloudfs.join(dest, entry))
            else:
                with open(src, "rb") as f:
                    cloudfs.write_bytes(cloudfs.join(dest, entry), f.read())
            if i == 0:
                self._hook("mid_upload", dest)
        self._hook("before_rank_marker", dest)
        cloudfs.touch(cloudfs.join(dest, rank_marker(self.world_rank)))
        if self.world_rank == 0:
            self._commit_complete(dest)
        shutil.rmtree(staging, ignore_errors=True)

    def _commit_complete(self, dest: str):
        """Rank 0: wait for every rank's upload marker, then commit."""
        import time

        deadline = time.monotonic() + self.complete_timeout_s
        while True:
            markers = [
                e for e in cloudfs.listdir(dest)
                if e.startswith(".rank_") and e.endswith(".uploaded")
            ]
            if len(markers) >= self.world_size:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"checkpoint {dest}: only {len(markers)}/"
                    f"{self.world_size} rank uploads landed within "
                    f"{self.complete_timeout_s}s — leaving it uncommitted"
                )
            time.sleep(0.05)
        self._hook("before_complete", dest)
        cloudfs.touch(cloudfs.join(dest, COMPLETE_MARKER))

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until queued uploads finish (session teardown / clean
        fit() exit). True when the queue emptied in time."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._q.all_tasks_done:
                if self._q.unfinished_tasks == 0:
                    return True
            if self._thread is None or not self._thread.is_alive():
                # writer died (fault or error): whatever is queued will
                # never upload — report drained-with-error
                return self.error is None and self._q.unfinished_tasks == 0
            time.sleep(0.02)
        return False

    def stop(self):
        self._q.put(None)
