"""Multi-host JAX runtime rendezvous through the controller KV.

Reference precedent: python/ray/train/torch/xla/config.py:67-75,120 —
the XLA backend picks rank 0's address via env-var rendezvous and every
worker calls ``init_process_group("xla")``. Same shape here: rank 0
claims a coordinator port and publishes it under the gang's KV key;
every rank (including 0) then calls ``jax.distributed.initialize`` so
``jax.devices()`` spans all host processes and ``pjit`` programs run
SPMD across them (ICI/DCN collectives on real pods; gloo on the CPU
simulation used in tests).
"""
from __future__ import annotations

import logging
import os
import socket
import time
from typing import Optional

logger = logging.getLogger("ray_tpu.train")

_KV_NS = "jax_rendezvous"


def _host_ip() -> str:
    from ray_tpu.utils.net import host_ip

    return host_ip()


def _free_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def setup_jax_distributed(
    world_rank: int,
    world_size: int,
    group_name: str,
    timeout_s: float = 60.0,
) -> str:
    """Initialize the cross-host JAX runtime for this gang. Returns the
    coordinator address. Call before any other jax use in the process."""
    from ray_tpu.experimental import internal_kv

    key = f"coordinator:{group_name}".encode()
    if world_rank == 0:
        addr = f"{_host_ip()}:{_free_port()}"
        internal_kv._internal_kv_put(key, addr.encode(), namespace=_KV_NS)
    else:
        deadline = time.monotonic() + timeout_s
        while True:
            raw = internal_kv._internal_kv_get(key, namespace=_KV_NS)
            if raw:
                addr = raw.decode()
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"rank {world_rank}: no coordinator published for "
                    f"{group_name} within {timeout_s}s"
                )
            time.sleep(0.05)
    import jax

    # The host image may pin a platform via sitecustomize before env vars
    # are honored; re-assert the requested platform pre-initialize.
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        jax.config.update("jax_platforms", platforms)
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=world_size,
        process_id=world_rank,
    )
    logger.info(
        "jax.distributed up: rank %d/%d via %s (%d global devices)",
        world_rank, world_size, addr, len(jax.devices()),
    )
    return addr


def shutdown_jax_distributed() -> None:
    try:
        import jax

        jax.distributed.shutdown()
    except Exception:  # noqa: BLE001 — never initialized / already down
        pass
