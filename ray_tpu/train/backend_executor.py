"""BackendExecutor: PG + worker group + rank env + training drive loop.

Reference: python/ray/train/_internal/backend_executor.py — PG creation
:219, worker start :135, accelerator-visibility sharing :299
(``_share_resource_ids`` — CUDA/TPU env vars), rank assignment :369,
``start_training`` :451, health-check + ``_restart`` :759 (elastic retry).
"""
from __future__ import annotations

import logging
import uuid
from typing import Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, ActorError, TaskError, WorkerCrashedError
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.util.placement_group import placement_group, remove_placement_group

logger = logging.getLogger("ray_tpu.train")

TRAINABLE_FAILURES = (ActorDiedError, ActorError, WorkerCrashedError, TaskError)


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(
        self,
        scaling: ScalingConfig,
        experiment_name: str,
        storage_path: str,
        max_failures: int = 0,
    ):
        self.scaling = scaling
        self.experiment_name = experiment_name
        self.storage_path = storage_path
        self.max_failures = max_failures
        self.pg = None
        self.worker_group: Optional[WorkerGroup] = None
        self._failures = 0

    # -- lifecycle --------------------------------------------------------
    def start(self):
        self.pg = placement_group(
            self.scaling.bundles(), strategy=self.scaling.placement_strategy
        )
        if not self.pg.wait(timeout_seconds=60):
            raise TrainingFailedError(
                f"placement group for {self.scaling.num_workers} workers "
                f"({self.scaling.worker_resources()}) not placeable"
            )
        self.worker_group = WorkerGroup(
            self.scaling.num_workers,
            self.scaling.worker_resources(),
            placement_group=self.pg,
        )

    def setup_sessions(self, latest_checkpoint: Optional[str],
                       dataset_shards: Optional[Dict] = None):
        assert self.worker_group is not None
        group_name = f"__train__{uuid.uuid4().hex[:8]}"
        self._group_name = group_name
        tpu_per_worker = self.scaling.worker_resources().get("TPU", 0)
        refs = []
        for w in self.worker_group.workers:
            ctx = TrainContext(
                world_size=len(self.worker_group),
                world_rank=w.world_rank,
                local_rank=w.local_rank,
                node_rank=w.node_rank,
                experiment_name=self.experiment_name,
                storage_path=self.storage_path,
            )
            env = dict(self.scaling.worker_env or {})
            env.update(self._visibility_env(w, tpu_per_worker))
            # Each rank gets its split index of every shard coordinator
            # (rank == split keeps shard assignment stable across ranks).
            shards = {
                name: (actor, w.world_rank)
                for name, actor in (dataset_shards or {}).items()
            }
            data_context = None
            if shards:
                from ray_tpu.data.context import DataContext

                # Ship the driver's ingest knobs — DataContext is
                # process-local and would otherwise silently reset to
                # defaults inside the train workers.
                data_context = DataContext.get_current().to_dict()
            refs.append(
                w.actor.setup_session.remote(
                    ctx, group_name, latest_checkpoint, env,
                    jax_distributed=self.scaling.use_jax_distributed,
                    dataset_shards=shards or None,
                    data_context=data_context,
                )
            )
        ray_tpu.get(refs)

    def _visibility_env(self, w, tpu_per_worker) -> Dict[str, str]:
        """Chip isolation for co-located workers (reference:
        accelerators/tpu.py:155-195 TPU_VISIBLE_CHIPS + backend_executor.py
        :299 _share_resource_ids)."""
        if not tpu_per_worker:
            return {}
        n = int(tpu_per_worker)
        start = w.local_rank * n
        chips = ",".join(str(c) for c in range(start, start + n))
        return {
            "TPU_VISIBLE_CHIPS": chips,
            "TPU_CHIPS_PER_PROCESS_BOUNDS": f"1,{n},1",
        }

    def start_training(self, train_fn: Callable, config: Optional[dict]) -> List:
        assert self.worker_group is not None
        return [
            w.actor.run_train_fn.remote(train_fn, config)
            for w in self.worker_group.workers
        ]

    def next_results(self, run_refs: Optional[List] = None) -> Optional[List[dict]]:
        """One result per rank, or None when all loops finished.

        ``run_refs`` (the run_train_fn return refs) are watched while
        waiting: a training loop that dies before its first report —
        including failing to even deserialize the train fn — must surface
        as an error, not leave next_result() blocked forever."""
        assert self.worker_group is not None
        result_refs = [
            w.actor.next_result.remote() for w in self.worker_group.workers
        ]
        if run_refs:
            result_set = set(result_refs)
            pending_run = list(run_refs)
            while True:
                ready, _ = ray_tpu.wait(
                    result_refs + pending_run,
                    num_returns=len(result_refs),
                    timeout=5.0,
                )
                if sum(1 for r in ready if r in result_set) == len(result_refs):
                    break
                for r in ready:
                    if r not in result_set:
                        # raises the loop's error if it failed; a clean
                        # finish resolves next_result() to None shortly.
                        # Seen run refs leave the wait set — otherwise a
                        # finished loop would satisfy the quota instantly
                        # and turn this into a zero-delay spin.
                        ray_tpu.get(r)
                        pending_run.remove(r)
        results = ray_tpu.get(result_refs)
        done = [r is None for r in results]
        if all(done):
            return None
        if any(done):
            raise TrainingFailedError(
                "ranks reported unevenly: some training loops finished while "
                "others are still calling report()"
            )
        return results

    def can_retry(self) -> bool:
        self._failures += 1
        return self.max_failures < 0 or self._failures <= self.max_failures

    def restart(self):
        """Tear down the gang and rebuild it (reference: _restart :759)."""
        logger.warning("restarting worker group (failure %d)", self._failures)
        self.shutdown_workers()
        self.start()

    def shutdown_workers(self):
        if self.worker_group is not None:
            self.worker_group.shutdown()
            self.worker_group = None
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None

    def shutdown(self):
        if self.worker_group is not None:
            for w in self.worker_group.workers:
                try:
                    ray_tpu.get(w.actor.teardown.remote(), timeout=5)
                except Exception:
                    pass
        self.shutdown_workers()
