"""BackendExecutor: PG + worker group + rank env + training drive loop.

Reference: python/ray/train/_internal/backend_executor.py — PG creation
:219, worker start :135, accelerator-visibility sharing :299
(``_share_resource_ids`` — CUDA/TPU env vars), rank assignment :369,
``start_training`` :451, health-check + ``_restart`` :759 (elastic retry).

Fault tolerance (this repo's elastic extension of :759):

* **fast detection** — the executor subscribes to the controller's
  lifecycle DEATH_CHANNEL (core/lifecycle.py): a SIGKILLed worker or
  host pushes a death event in ~the TCP connection-loss latency, so
  ``next_results`` raises :class:`GangMemberDiedError` within its next
  poll slice (~1s) instead of waiting out a blocked collective or RPC
  timeout.
* **repair-in-place** — ``restart()`` keeps surviving ``TrainWorker``
  actors WARM: their loops are broken out of any barrier via
  ``abort_run`` and their sessions torn down, but the processes (and
  their warm imports/JITs) survive. Dead ranks are either replaced
  within ``elastic_grace_s`` (rejoin at the same world size — the next
  ``setup_sessions`` re-runs the jax rendezvous with the same shape) or,
  when ``ScalingConfig.min_workers`` allows, the gang RE-MESHES to the
  surviving count and resumes from checkpoint at the smaller
  data-parallel width. Only when neither is possible does it fall back
  to the legacy tear-down-and-rebuild.
"""
from __future__ import annotations

import logging
import time
import uuid
from typing import Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, ActorError, TaskError, WorkerCrashedError
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.util.placement_group import placement_group, remove_placement_group

logger = logging.getLogger("ray_tpu.train")


class TrainingFailedError(RuntimeError):
    pass


class GangMemberDiedError(ActorError):
    """A gang member (or its host) died mid-training — detected via the
    lifecycle death channel, not an RPC timeout."""

    def __init__(self, rank: int = -1, node: str = "", reason: str = "",
                 detect_ms: float = -1.0):
        self.rank = rank
        self.node = node
        self.reason = reason
        self.detect_ms = detect_ms
        super().__init__(
            f"train worker rank {rank} on node {node[:12]} died: {reason} "
            f"(detected in {detect_ms:.0f}ms)"
        )

    def __reduce__(self):
        return (GangMemberDiedError,
                (self.rank, self.node, self.reason, self.detect_ms))


# GangMemberDiedError is covered via its ActorError base.
TRAINABLE_FAILURES = (
    ActorDiedError, ActorError, WorkerCrashedError, TaskError,
)


# ---------------------------------------------------------------------------
# Driver-side recovery metrics (flushed by the driver's metric flusher
# like train_driver_wait_ms; surfaced by state.summarize_train()).
# ---------------------------------------------------------------------------
_RECOVER_MS_BOUNDARIES = (
    10, 50, 100, 250, 500, 1000, 2500, 5000, 15000, 60000, 300000,
)
_recovery_metrics = None


def recovery_metrics():
    global _recovery_metrics
    if _recovery_metrics is None:
        from ray_tpu.util.metrics import Counter, Histogram

        class _M:
            def __init__(self):
                self.recoveries = Counter(
                    "train_recoveries_total",
                    "Gang recoveries by mode (rejoin/remesh/rebuild)",
                    ("run", "mode"),
                )
                self.deaths = Counter(
                    "train_worker_deaths_total",
                    "Train gang member deaths observed by the executor",
                    ("run",),
                )
                self.detect_ms = Histogram(
                    "train_detect_ms",
                    "Failure detection latency (death to executor raise)",
                    _RECOVER_MS_BOUNDARIES, ("run",),
                )
                self.repair_ms = Histogram(
                    "train_repair_ms",
                    "Gang repair latency (abort + replace/shrink), by mode",
                    _RECOVER_MS_BOUNDARIES, ("run", "mode"),
                )
                self.resume_ms = Histogram(
                    "train_resume_ms",
                    "Post-repair resume latency (session setup + rendezvous)",
                    _RECOVER_MS_BOUNDARIES, ("run",),
                )

        _recovery_metrics = _M()
    return _recovery_metrics


class BackendExecutor:
    def __init__(
        self,
        scaling: ScalingConfig,
        experiment_name: str,
        storage_path: str,
        max_failures: int = 0,
        elastic_grace_s: float = 10.0,
        checkpoint_async: bool = False,
    ):
        self.scaling = scaling
        self.experiment_name = experiment_name
        self.storage_path = storage_path
        self.max_failures = max_failures
        self.elastic_grace_s = elastic_grace_s
        self.checkpoint_async = checkpoint_async
        self.pg = None
        self.worker_group: Optional[WorkerGroup] = None
        self._failures = 0
        # Fast failure detection (lifecycle death events).
        self._death_sub = None
        self._seen_deaths: set = set()
        self.last_failure: Optional[GangMemberDiedError] = None
        # One dict per recovery: {mode, detect_ms, repair_ms, resume_ms,
        # world_size, ts} — the chaos bench and tests read this.
        self.recovery_log: List[dict] = []

    # -- introspection ----------------------------------------------------
    @property
    def failures(self) -> int:
        """Gang failures absorbed so far (public face of the retry
        counter the TrainingFailedError message reports)."""
        return self._failures

    @property
    def world_size(self) -> int:
        """CURRENT gang width — shrinks after an elastic re-mesh."""
        if self.worker_group is not None:
            return len(self.worker_group)
        return self.scaling.num_workers

    # -- lifecycle --------------------------------------------------------
    def start(self):
        self.pg = placement_group(
            self.scaling.bundles(), strategy=self.scaling.placement_strategy
        )
        if not self.pg.wait(timeout_seconds=60):
            raise TrainingFailedError(
                f"placement group for {self.scaling.num_workers} workers "
                f"({self.scaling.worker_resources()}) not placeable"
            )
        self.worker_group = WorkerGroup(
            self.scaling.num_workers,
            self.scaling.worker_resources(),
            placement_group=self.pg,
        )
        self._subscribe_deaths()

    def _subscribe_deaths(self):
        if self._death_sub is not None:
            return
        try:
            from ray_tpu.core.lifecycle import DEATH_CHANNEL
            from ray_tpu.experimental import pubsub

            self._death_sub = pubsub.subscribe(DEATH_CHANNEL)
        except Exception as e:  # noqa: BLE001 — detection degrades to RPC errors
            logger.warning("death-event subscription unavailable: %s", e)

    def _gang_identity(self):
        """(actor ids, node ids) of the CURRENT gang, for death-event
        filtering."""
        actors, nodes = set(), set()
        for w in self.worker_group.workers if self.worker_group else ():
            actors.add(w.actor._actor_id.hex())
            nodes.add(w.node_id)
        return actors, nodes

    def check_deaths(self) -> Optional[GangMemberDiedError]:
        """Drain the death channel; return an error for the first event
        that names a CURRENT gang member (or its node). Dedups by entity
        so the worker-death + actor-death pair of one kill counts once."""
        if self._death_sub is None or self.worker_group is None:
            return None
        import queue as _q

        actors, nodes = self._gang_identity()
        hit = None
        while True:
            try:
                msg = self._death_sub.get_nowait()
            except _q.Empty:
                break
            if not isinstance(msg, dict):
                continue
            kind, eid = msg.get("kind"), msg.get("id", "")
            key = msg.get("actor") or eid
            victim_rank, victim_node = -1, ""
            if kind == "node" and eid in nodes and msg.get("state") == "DEAD":
                victim_node = eid
                for w in self.worker_group.workers:
                    if w.node_id == eid:
                        victim_rank = w.world_rank
                        break
            elif kind == "actor" and eid in actors:
                key = eid
            elif kind == "worker" and msg.get("actor") in actors:
                key = msg.get("actor")
            else:
                continue
            if key in self._seen_deaths:
                continue
            self._seen_deaths.add(key)
            if victim_rank < 0:
                for w in self.worker_group.workers:
                    if w.actor._actor_id.hex() == key:
                        victim_rank, victim_node = w.world_rank, w.node_id
                        break
            # Cross-process wall clocks (controller stamped ts, we read
            # now): on multi-host deployments NTP skew biases this by the
            # host offset (clamped at 0). Precise cross-host detection
            # latency needs a clock-sync estimate — single-host (tests,
            # bench) is exact.
            detect_ms = max(0.0, (time.time() - float(msg.get("ts", 0)))) * 1000.0
            err = GangMemberDiedError(
                rank=victim_rank,
                node=victim_node or msg.get("node", ""),
                reason=msg.get("reason", msg.get("state", "died")),
                detect_ms=detect_ms,
            )
            if hit is None:
                hit = err
        return hit

    def setup_sessions(self, latest_checkpoint: Optional[str],
                       dataset_shards: Optional[Dict] = None,
                       ckpt_index_start: int = 0):
        assert self.worker_group is not None
        t0 = time.monotonic()
        group_name = f"__train__{uuid.uuid4().hex[:8]}"
        self._group_name = group_name
        tpu_per_worker = self.scaling.worker_resources().get("TPU", 0)
        refs = []
        for w in self.worker_group.workers:
            ctx = TrainContext(
                world_size=len(self.worker_group),
                world_rank=w.world_rank,
                local_rank=w.local_rank,
                node_rank=w.node_rank,
                experiment_name=self.experiment_name,
                storage_path=self.storage_path,
            )
            env = dict(self.scaling.worker_env or {})
            env.update(self._visibility_env(w, tpu_per_worker))
            # Each rank gets its split index of every shard coordinator
            # (rank == split keeps shard assignment stable across ranks).
            shards = {
                name: (actor, w.world_rank)
                for name, actor in (dataset_shards or {}).items()
            }
            data_context = None
            if shards:
                from ray_tpu.data.context import DataContext

                # Ship the driver's ingest knobs — DataContext is
                # process-local and would otherwise silently reset to
                # defaults inside the train workers.
                data_context = DataContext.get_current().to_dict()
            refs.append(
                w.actor.setup_session.remote(
                    ctx, group_name, latest_checkpoint, env,
                    jax_distributed=self.scaling.use_jax_distributed,
                    dataset_shards=shards or None,
                    data_context=data_context,
                    checkpoint_async=self.checkpoint_async,
                    ckpt_index_start=ckpt_index_start,
                )
            )
        ray_tpu.get(refs)
        resume_ms = (time.monotonic() - t0) * 1000.0
        if self.recovery_log and "resume_ms" not in self.recovery_log[-1]:
            self.recovery_log[-1]["resume_ms"] = round(resume_ms, 1)
            recovery_metrics().resume_ms.observe(
                resume_ms, {"run": self.experiment_name}
            )

    def _visibility_env(self, w, tpu_per_worker) -> Dict[str, str]:
        """Chip isolation for co-located workers (reference:
        accelerators/tpu.py:155-195 TPU_VISIBLE_CHIPS + backend_executor.py
        :299 _share_resource_ids)."""
        if not tpu_per_worker:
            return {}
        n = int(tpu_per_worker)
        start = w.local_rank * n
        chips = ",".join(str(c) for c in range(start, start + n))
        return {
            "TPU_VISIBLE_CHIPS": chips,
            "TPU_CHIPS_PER_PROCESS_BOUNDS": f"1,{n},1",
        }

    def start_training(self, train_fn: Callable, config: Optional[dict]) -> List:
        assert self.worker_group is not None
        return [
            w.actor.run_train_fn.remote(train_fn, config)
            for w in self.worker_group.workers
        ]

    def next_results(self, run_refs: Optional[List] = None) -> Optional[List[dict]]:
        """One result per rank, or None when all loops finished.

        Three failure-surfacing paths race, fastest wins: the lifecycle
        death channel (a killed worker/host raises GangMemberDiedError
        within one poll slice), the ``run_refs`` (a loop that dies
        before its first report — including failing to even deserialize
        the train fn — surfaces its error), and the result refs
        themselves."""
        assert self.worker_group is not None
        death = self.check_deaths()
        if death is not None:
            self._note_detection(death)
            raise death
        result_refs = [
            w.actor.next_result.remote() for w in self.worker_group.workers
        ]
        result_set = set(result_refs)
        pending_run = list(run_refs or [])
        while True:
            ready, _ = ray_tpu.wait(
                result_refs + pending_run,
                num_returns=len(result_refs),
                timeout=0.5,
            )
            death = self.check_deaths()
            if death is not None:
                self._note_detection(death)
                raise death
            if sum(1 for r in ready if r in result_set) == len(result_refs):
                break
            for r in ready:
                if r not in result_set:
                    # raises the loop's error if it failed; a clean
                    # finish resolves next_result() to None shortly.
                    # Seen run refs leave the wait set — otherwise a
                    # finished loop would satisfy the quota instantly
                    # and turn this into a zero-delay spin.
                    ray_tpu.get(r)
                    pending_run.remove(r)
        results = ray_tpu.get(result_refs)
        done = [r is None for r in results]
        if all(done):
            return None
        if any(done):
            raise TrainingFailedError(
                "ranks reported unevenly: some training loops finished while "
                "others are still calling report()"
            )
        return results

    def _note_detection(self, err: GangMemberDiedError):
        self.last_failure = err
        m = recovery_metrics()
        tags = {"run": self.experiment_name}
        m.deaths.inc(1, tags)
        if err.detect_ms >= 0:
            m.detect_ms.observe(err.detect_ms, tags)

    def can_retry(self) -> bool:
        self._failures += 1
        return self.max_failures < 0 or self._failures <= self.max_failures

    # -- repair -----------------------------------------------------------
    def restart(self, run_refs: Optional[List] = None):
        """Repair the gang in place (reference `_restart` :759 rebuilt
        from zero; here surviving workers stay warm). Steps: break every
        survivor out of its barrier (abort_run), wait for the old loops
        to unwind, probe liveness, tear down surviving sessions, then
        rejoin (replacements within ``elastic_grace_s``) / re-mesh
        (``min_workers`` floor) / rebuild."""
        assert self.worker_group is not None
        t0 = time.monotonic()
        wg = self.worker_group
        # 1. Abort every loop (dead members' calls just error) so
        # survivors unwind out of collective barriers NOW.
        abort_refs = [
            w.actor.abort_run.remote("gang repair") for w in wg.workers
        ]
        ray_tpu.wait(abort_refs, num_returns=len(abort_refs), timeout=5.0)
        if run_refs:
            # Old loop threads must have EXITED before sessions are
            # rebuilt — a straggler calling report() later would land in
            # the fresh session and skew its rank pacing. Bounded: a
            # loop ignoring the abort forfeits the wait.
            ray_tpu.wait(list(run_refs), num_returns=len(run_refs), timeout=15.0)
        # 2. Who is actually alive?
        alive = wg.probe(timeout=5.0)
        dead_idx = [i for i, a in enumerate(alive) if not a]
        if dead_idx and self.last_failure is None:
            # The failure surfaced through the direct transport (a
            # closed caller→actor connection fails refs even faster than
            # the death channel); the lifecycle event carries the
            # authoritative death timestamp — wait briefly for it so
            # detect_ms is still recorded.
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                death = self.check_deaths()
                if death is not None:
                    self._note_detection(death)
                    break
                time.sleep(0.05)
        # 3. Surviving sessions: normal teardown (collective + jax
        # runtime membership die with the OLD group name; the actor and
        # its warm imports survive for the next setup_sessions).
        td = [
            wg.workers[i].actor.teardown.remote()
            for i, a in enumerate(alive) if a
        ]
        ray_tpu.wait(td, num_returns=len(td), timeout=30.0)
        mode = "none"
        if dead_idx:
            survivors = len(wg) - len(dead_idx)
            min_workers = self.scaling.min_workers
            if survivors > 0 and wg.replace(dead_idx, self.elastic_grace_s):
                mode = "rejoin"
            elif (
                min_workers is not None
                and 0 < min_workers <= survivors < len(wg)
            ):
                wg.shrink(dead_idx)
                mode = "remesh"
                logger.warning(
                    "elastic re-mesh: %d -> %d workers (floor %d)",
                    self.scaling.num_workers, len(wg), min_workers,
                )
            else:
                # No replacement in time and no (viable) elastic floor:
                # the legacy full rebuild. This is also the 0-survivors
                # path.
                mode = "rebuild"
                self.shutdown_workers()
                self.start()
        repair_ms = (time.monotonic() - t0) * 1000.0
        # Consume the detection: a later recovery whose failure surfaced
        # only through the transport must re-wait for ITS death event
        # above, not inherit this one's stale detect_ms.
        detect, self.last_failure = self.last_failure, None
        entry = {
            "mode": mode,
            "repair_ms": round(repair_ms, 1),
            "world_size": self.world_size,
            "dead_ranks": dead_idx,
            "ts": time.time(),
        }
        if detect is not None and detect.detect_ms >= 0:
            entry["detect_ms"] = round(detect.detect_ms, 1)
        self.recovery_log.append(entry)
        m = recovery_metrics()
        m.recoveries.inc(1, {"run": self.experiment_name, "mode": mode})
        m.repair_ms.observe(repair_ms, {"run": self.experiment_name, "mode": mode})
        logger.warning(
            "gang repair #%d: mode=%s dead=%s world=%d (%.0fms)",
            self._failures, mode, dead_idx, self.world_size, repair_ms,
        )

    def shutdown_workers(self):
        if self.worker_group is not None:
            self.worker_group.shutdown()
            self.worker_group = None
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None

    def shutdown(self):
        if self._death_sub is not None:
            try:
                self._death_sub.close()
            except Exception:
                pass
            self._death_sub = None
        if self.worker_group is not None:
            for w in self.worker_group.workers:
                try:
                    ray_tpu.get(w.actor.teardown.remote(), timeout=5)
                except Exception:
                    pass
        self.shutdown_workers()
