"""ray_tpu.train — distributed (data/model-parallel) training.

Reference: python/ray/train/ (§2.4 of SURVEY.md).
"""
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
    timed,
)
from ray_tpu.train.trainer import DataParallelTrainer, JaxTrainer, Result
from ray_tpu.train.torch import TorchTrainer
from ray_tpu.train.worker_group import TrainWorker, WorkerGroup
from ray_tpu.train.backend_executor import (
    BackendExecutor,
    GangMemberDiedError,
    TrainingFailedError,
)

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "FailureConfig",
    "RunConfig",
    "ScalingConfig",
    "report",
    "get_context",
    "get_checkpoint",
    "get_dataset_shard",
    "timed",
    "DataParallelTrainer",
    "JaxTrainer",
    "TorchTrainer",
    "Result",
    "TrainWorker",
    "WorkerGroup",
    "BackendExecutor",
    "TrainingFailedError",
    "GangMemberDiedError",
]
