"""Per-worker training session: ranks, report(), checkpoint access.

Reference: python/ray/train/_internal/session.py:111 (_TrainSession) and
:403 (``ray.train.report`` — synchronizes ranks, ships results to the
driver via a queue), train/context.py:26 (TrainContext).

The session lives inside each TrainWorker actor. ``report`` barriers the
ranks over the worker group's collective group, persists the checkpoint
directory into run storage, then hands the result to the driver through a
bounded queue (the driver paces training exactly like the reference's
TrainingIterator).
"""
from __future__ import annotations

import os
import queue
import shutil
import threading
from dataclasses import dataclass
from typing import Optional

from ray_tpu.train.checkpoint import Checkpoint

_session: Optional["_TrainSession"] = None


@dataclass
class TrainContext:
    world_size: int
    world_rank: int
    local_rank: int
    node_rank: int
    experiment_name: str
    storage_path: str

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name


class _TrainSession:
    def __init__(self, ctx: TrainContext, group_name: str, latest_checkpoint: Optional[str]):
        self.ctx = ctx
        self.group_name = group_name
        self.result_queue: queue.Queue = queue.Queue(maxsize=1)
        self.ckpt_seq = 0
        self.latest_checkpoint = latest_checkpoint
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None

    # -- worker-side API --------------------------------------------------
    def report(self, metrics: dict, checkpoint: Optional[Checkpoint] = None):
        from ray_tpu import collective

        persisted = None
        if checkpoint is not None:
            from ray_tpu.utils import cloudfs

            dest = cloudfs.join(
                self.ctx.storage_path, f"checkpoint_{self.ckpt_seq:06d}"
            )
            cloudfs.makedirs(dest)
            # Every rank copies its files into the shared checkpoint dir
            # (sharded checkpoints: orbax writes disjoint per-host files;
            # reference: storage.py:508 persist_current_checkpoint —
            # cloudfs uploads when storage_path is a gs://-style URI).
            if cloudfs.normalize(checkpoint.path) != cloudfs.normalize(dest):
                cloudfs.copy_dir(checkpoint.path, dest)
            persisted = dest
        self.ckpt_seq += 1
        # Rank synchronization barrier (reference session.py:403 semantics).
        collective.barrier(self.group_name)
        if persisted is not None:
            # Past the barrier every rank has persisted its shard; the marker
            # makes the checkpoint discoverable on restart even if the driver
            # never consumes this report (rank death races the queue).
            if self.ctx.world_rank == 0:
                from ray_tpu.utils import cloudfs

                cloudfs.touch(cloudfs.join(persisted, ".complete"))
            self.latest_checkpoint = persisted
        # Block until the driver consumed the previous result — keeps
        # training paced with the driver loop.
        self.result_queue.put(
            {
                "metrics": metrics,
                "checkpoint": persisted,
                "ckpt_index": self.ckpt_seq - 1,
            }
        )

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return Checkpoint(self.latest_checkpoint) if self.latest_checkpoint else None

    # -- driver-facing (via actor method) ---------------------------------
    def next_result(self, timeout: Optional[float] = None):
        """Blocks (up to ``timeout``) for the next report; returns None when
        the loop is done; raises TimeoutError when the bound expires."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            try:
                return self.result_queue.get(timeout=0.2)
            except queue.Empty:
                if self.finished.is_set() and self.result_queue.empty():
                    if self.error is not None:
                        raise self.error
                    return None
                if deadline is not None and _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no train result within {timeout}s (worker still running)"
                    )


def _set_session(session: Optional[_TrainSession]):
    global _session
    _session = session


def _get_session() -> _TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session active — this API must be called inside a "
            "train_loop_per_worker launched by a Trainer"
        )
    return _session


def _call_train_fn(train_fn, config: Optional[dict]):
    """The loop-arity convention (loop(config) vs loop()), in one place —
    used by TrainWorker.run_train_fn and trainer wrappers alike."""
    import inspect

    if len(inspect.signature(train_fn).parameters) >= 1:
        return train_fn(config if config is not None else {})
    return train_fn()


# ---------------------------------------------------------------------------
# Public API (reference: ray.train.report / get_context / get_checkpoint)
# ---------------------------------------------------------------------------
def report(metrics: dict, checkpoint: Optional[Checkpoint] = None):
    _get_session().report(metrics, checkpoint)


def get_context() -> TrainContext:
    return _get_session().ctx


def get_checkpoint() -> Optional[Checkpoint]:
    return _get_session().get_checkpoint()
