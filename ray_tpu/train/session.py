"""Per-worker training session: ranks, report(), checkpoint access.

Reference: python/ray/train/_internal/session.py:111 (_TrainSession) and
:403 (``ray.train.report`` — synchronizes ranks, ships results to the
driver via a queue), train/context.py:26 (TrainContext).

The session lives inside each TrainWorker actor. ``report`` barriers the
ranks over the worker group's collective group, persists the checkpoint
directory into run storage, then hands the result to the driver through a
bounded queue (the driver paces training exactly like the reference's
TrainingIterator).
"""
from __future__ import annotations

import contextlib
import os
import queue
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint, CheckpointWriter

_session: Optional["_TrainSession"] = None


class TrainingAborted(RuntimeError):
    """The driver aborted this rank's training loop (gang repair after a
    peer death): not a user-code failure — the executor restarts the
    loop from checkpoint on the repaired gang."""


# Test seam: fault hook threaded into every session's CheckpointWriter
# (see checkpoint.CheckpointWriter docstring).
_ckpt_fault_hook = None


def set_checkpoint_fault_hook(hook):
    global _ckpt_fault_hook
    _ckpt_fault_hook = hook

# ---------------------------------------------------------------------------
# Step telemetry (reference: the reference's train ProgressTracker /
# per-worker metrics; here histograms in the app-metric registry tagged
# {run, rank} so the Grafana train row gets quantile panels for free).
# ---------------------------------------------------------------------------
_STEP_MS_BOUNDARIES = (
    1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 15000, 60000, 300000,
)
_metrics_lock = threading.Lock()
_train_metrics = None
_phase_hists: Dict[str, object] = {}


class _TrainMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        rr = ("run", "rank")
        self.step_wall_ms = Histogram(
            "train_step_wall_ms",
            "Wall time between consecutive train.report() calls (one step)",
            _STEP_MS_BOUNDARIES, rr,
        )
        self.report_ms = Histogram(
            "train_report_ms",
            "Time inside train.report(): rank barrier + checkpoint persist + "
            "driver queue",
            _STEP_MS_BOUNDARIES, rr,
        )
        self.reports = Counter(
            "train_reports_total", "train.report() calls (steps reported)", rr
        )
        self.steps_per_s = Gauge(
            "train_steps_per_s", "Reported-step throughput per worker", rr
        )
        self.driver_wait_ms = Histogram(
            "train_driver_wait_ms",
            "Driver time blocked waiting for the next rank-0 result",
            _STEP_MS_BOUNDARIES, ("run",),
        )


def train_metrics() -> _TrainMetrics:
    global _train_metrics
    if _train_metrics is None:
        with _metrics_lock:
            if _train_metrics is None:
                _train_metrics = _TrainMetrics()
    return _train_metrics


def _ctx_tags(ctx: "TrainContext") -> Dict[str, str]:
    return {"run": ctx.experiment_name, "rank": str(ctx.world_rank)}


def _session_tags() -> Dict[str, str]:
    if _session is None:
        return {"run": "_no_session", "rank": "-"}
    return _ctx_tags(_session.ctx)


def _phase_histogram(phase: str):
    """One histogram per timed phase (``train_step_<phase>_ms``),
    registered on first use — e.g. data_wait / compile."""
    with _metrics_lock:
        h = _phase_hists.get(phase)
        if h is None:
            from ray_tpu.util.metrics import Histogram

            h = _phase_hists[phase] = Histogram(
                f"train_step_{phase}_ms",
                f"Time attributed to the '{phase}' phase of a train step",
                _STEP_MS_BOUNDARIES, ("run", "rank"),
            )
        return h


@contextlib.contextmanager
def timed(phase: str):
    """Attribute a chunk of the current step to ``phase`` — e.g.
    ``with train.timed("data_wait"): batch = next(it)`` or
    ``with train.timed("compile"): step_fn = jax.jit(...).lower(...).compile()``.
    Records ``train_step_<phase>_ms`` tagged {run, rank}."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        _phase_histogram(phase).observe(
            (time.monotonic() - t0) * 1000.0, _session_tags()
        )


@dataclass
class TrainContext:
    world_size: int
    world_rank: int
    local_rank: int
    node_rank: int
    experiment_name: str
    storage_path: str

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name


class _TrainSession:
    def __init__(self, ctx: TrainContext, group_name: str, latest_checkpoint: Optional[str],
                 checkpoint_async: bool = False, ckpt_index_start: int = 0):
        self.ctx = ctx
        self.group_name = group_name
        self.result_queue: queue.Queue = queue.Queue(maxsize=1)
        # Numbering continues where the previous incarnation left off so
        # a repaired gang can never write into (tear) a directory an
        # earlier incarnation already committed.
        self.ckpt_seq = ckpt_index_start
        self.latest_checkpoint = latest_checkpoint
        self.checkpoint_async = checkpoint_async
        self._ckpt_writer: Optional[CheckpointWriter] = None
        # Driver-initiated abort (gang repair): breaks this rank's loop
        # out of report()/barrier waits with TrainingAborted.
        self.aborted = threading.Event()
        self.abort_reason = ""
        # name -> (ShardCoordinator actor handle, split index) for the
        # trainer's ``datasets`` (see get_dataset_shard).
        self.dataset_shards: Dict[str, tuple] = {}
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        self._steps_reported = 0
        # Step-timing marks: wall time between report() calls is the
        # step; time inside report() (barrier + persist + queue) is
        # accounted separately so sync overhead is visible on its own.
        self._step_start = time.monotonic()
        self._first_report = self._step_start

    # -- worker-side API --------------------------------------------------
    def abort(self, reason: str = "gang repair"):
        """Driver-initiated abort (via TrainWorker.abort_run): unblocks
        report()'s barrier and result-queue waits so the loop thread
        unwinds with TrainingAborted while the ACTOR stays warm."""
        self.abort_reason = reason
        self.aborted.set()
        from ray_tpu import collective

        collective.abort_collective_group(self.group_name)

    def _check_abort(self):
        if self.aborted.is_set():
            raise TrainingAborted(self.abort_reason or "aborted")

    def _writer(self) -> CheckpointWriter:
        if self._ckpt_writer is None:
            self._ckpt_writer = CheckpointWriter(
                self.ctx.world_rank, self.ctx.world_size,
                fault_hook=_ckpt_fault_hook,
            )
        return self._ckpt_writer

    def finish_checkpoints(self, timeout: float = 120.0):
        """Drain pending async uploads (clean loop exit / teardown): a
        fit() that returned must mean the last checkpoint is durable."""
        w = self._ckpt_writer
        if w is None:
            return
        drained = w.drain(timeout)
        # Park the writer thread for good either way — repair-in-place
        # keeps this actor warm, and the NEXT incarnation builds its own
        # writer; without stop() every recovery would leak one thread.
        w.stop()
        self._ckpt_writer = None
        if not drained:
            raise RuntimeError(
                f"async checkpoint uploads still pending after {timeout}s"
            )
        w.check()

    def report(self, metrics: dict, checkpoint: Optional[Checkpoint] = None):
        from ray_tpu import collective

        t_report = time.monotonic()
        m = train_metrics()
        tags = _ctx_tags(self.ctx)
        m.step_wall_ms.observe((t_report - self._step_start) * 1000.0, tags)
        m.reports.inc(1, tags)
        self._check_abort()
        persisted = None
        staging = None
        if checkpoint is not None:
            from ray_tpu.utils import cloudfs

            dest = cloudfs.join(
                self.ctx.storage_path, f"checkpoint_{self.ckpt_seq:06d}"
            )
            if self.checkpoint_async:
                # Non-blocking persistence: the step pays only for a
                # local host-side snapshot; upload + keep-K + commit
                # markers run on the writer thread (still surfaces
                # upload errors — on the NEXT report, via submit()).
                import tempfile

                staging = tempfile.mkdtemp(
                    prefix=f"rt_ckpt_stage_r{self.ctx.world_rank}_"
                )
                cloudfs.copy_dir(checkpoint.path, staging)
            else:
                cloudfs.makedirs(dest)
                # Every rank copies its files into the shared checkpoint
                # dir (sharded checkpoints: orbax writes disjoint
                # per-host files; reference: storage.py:508
                # persist_current_checkpoint — cloudfs uploads when
                # storage_path is a gs://-style URI).
                if cloudfs.normalize(checkpoint.path) != cloudfs.normalize(dest):
                    cloudfs.copy_dir(checkpoint.path, dest)
            persisted = dest
        self.ckpt_seq += 1
        # Rank synchronization barrier (reference session.py:403
        # semantics). A peer death mid-barrier surfaces as
        # ConnectionError; when the driver aborted us first, classify as
        # the abort (repair), not a transport failure.
        try:
            collective.barrier(self.group_name)
        except BaseException as e:
            # The writer never saw this snapshot — without the cleanup a
            # gang repair would leak one model-sized staging dir per
            # surviving rank per recovery.
            if staging is not None:
                import shutil

                shutil.rmtree(staging, ignore_errors=True)
            if isinstance(e, ConnectionError):
                self._check_abort()
            raise
        if persisted is not None:
            if self.checkpoint_async:
                # Past the barrier every rank has SNAPSHOTTED (not yet
                # uploaded): hand the upload to the writer; rank 0's
                # writer commits .complete only after every rank's
                # upload marker lands (checkpoint.CheckpointWriter).
                try:
                    self._writer().submit(staging, persisted)
                except BaseException:
                    # submit() surfaces a PREVIOUS upload's error before
                    # enqueueing — this snapshot was never handed off, so
                    # nothing else will ever delete it.
                    import shutil

                    shutil.rmtree(staging, ignore_errors=True)
                    raise
            elif self.ctx.world_rank == 0:
                # Sync path: past the barrier every rank has persisted;
                # the marker makes the checkpoint discoverable on
                # restart even if the driver never consumes this report
                # (rank death races the queue).
                from ray_tpu.utils import cloudfs

                from ray_tpu.train.checkpoint import COMPLETE_MARKER

                cloudfs.touch(cloudfs.join(persisted, COMPLETE_MARKER))
            self.latest_checkpoint = persisted
        # Block until the driver consumed the previous result — keeps
        # training paced with the driver loop (abort-aware: the driver
        # stops consuming during a gang repair).
        item = {
            "metrics": metrics,
            "checkpoint": persisted,
            "ckpt_index": self.ckpt_seq - 1,
        }
        while True:
            self._check_abort()
            try:
                self.result_queue.put(item, timeout=0.2)
                break
            except queue.Full:
                continue
        now = time.monotonic()
        m.report_ms.observe((now - t_report) * 1000.0, tags)
        self._steps_reported += 1
        elapsed = now - self._first_report
        if elapsed > 0:
            m.steps_per_s.set(self._steps_reported / elapsed, tags)
        self._step_start = now

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return Checkpoint(self.latest_checkpoint) if self.latest_checkpoint else None

    # -- driver-facing (via actor method) ---------------------------------
    def next_result(self, timeout: Optional[float] = None):
        """Blocks (up to ``timeout``) for the next report; returns None when
        the loop is done; raises TimeoutError when the bound expires."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            try:
                return self.result_queue.get(timeout=0.2)
            except queue.Empty:
                if self.finished.is_set() and self.result_queue.empty():
                    if self.error is not None:
                        raise self.error
                    return None
                if deadline is not None and _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no train result within {timeout}s (worker still running)"
                    )


def _set_session(session: Optional[_TrainSession]):
    global _session
    _session = session


def _get_session() -> _TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session active — this API must be called inside a "
            "train_loop_per_worker launched by a Trainer"
        )
    return _session


def _call_train_fn(train_fn, config: Optional[dict]):
    """The loop-arity convention (loop(config) vs loop()), in one place —
    used by TrainWorker.run_train_fn and trainer wrappers alike."""
    import inspect

    if len(inspect.signature(train_fn).parameters) >= 1:
        return train_fn(config if config is not None else {})
    return train_fn()


# ---------------------------------------------------------------------------
# Public API (reference: ray.train.report / get_context / get_checkpoint)
# ---------------------------------------------------------------------------
def report(metrics: dict, checkpoint: Optional[Checkpoint] = None):
    _get_session().report(metrics, checkpoint)


def get_context() -> TrainContext:
    return _get_session().ctx


def get_checkpoint() -> Optional[Checkpoint]:
    return _get_session().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    """This rank's shard of a trainer ``datasets`` entry, as a pipelined
    :class:`ray_tpu.data.DataIterator` (reference:
    ``ray.train.get_dataset_shard``). Block prefetch, zero-copy decode,
    background rebatch and device prefetch are on by default — see
    ``ray_tpu.data.context.DataContext`` for the knobs. The stream is one
    pass over the dataset per ``fit()``."""
    sess = _get_session()
    spec = sess.dataset_shards.get(name)
    if spec is None:
        raise KeyError(
            f"no dataset shard {name!r} — pass datasets={{{name!r}: ds}} "
            "to the Trainer"
        )
    from ray_tpu.data.shard import shard_iterator

    actor, split = spec
    return shard_iterator(actor, split)
