"""Sharded train-state checkpointing via orbax.

Reference: train/_internal/storage.py persists whole checkpoint
directories through pyarrow.fs — adequate for torch state dicts, but a
TPU mesh's train state is an array tree sharded across hosts. Orbax
writes each host's shards in parallel and reassembles on restore under
*any* target sharding, which is what makes topology-changing resume
(e.g. fsdp=8 → fsdp=4×tp=2, or elastic re-mesh after gang restart —
backend_executor._restart) possible. This wraps it in the framework's
checkpoint shapes.
"""
from __future__ import annotations

from typing import Any, Optional

import jax

from ray_tpu.utils import cloudfs


def save_sharded(path: str, state: Any, *, force: bool = True) -> str:
    """Write a (possibly sharded) pytree of jax.Arrays to ``path``.

    ``path`` may be a cloud URI (`gs://bucket/ckpt`) — orbax/tensorstore
    handle those natively, and on a real TPU pod a bucket is the only
    durable target (reference: storage.py:352 pyarrow.fs resolution).
    cloudfs.normalize abspaths ONLY local paths; URIs pass through.

    Every process in a multi-host mesh must call this with the same
    ``path``; each writes only the shards it owns."""
    import orbax.checkpoint as ocp

    path = cloudfs.normalize(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state, force=force)
    ckptr.wait_until_finished()
    return path


_async_ckptr = None


def save_sharded_async(path: str, state: Any, *, force: bool = True) -> str:
    """Snapshot-to-host-then-background-write: returns as soon as the
    device→host copy is done (the only part the train step must block
    for); serialization + upload continue on orbax's writer threads.
    Orbax serializes saves on the same checkpointer, so back-to-back
    calls self-pace; call :func:`wait_for_async_saves` before relying on
    durability (``session.report`` instead routes its own per-rank
    commit markers through train.checkpoint.CheckpointWriter — this
    function is the direct-orbax analogue for loops that checkpoint to
    cloud storage themselves)."""
    global _async_ckptr
    import orbax.checkpoint as ocp

    path = cloudfs.normalize(path)
    if _async_ckptr is None:
        _async_ckptr = ocp.StandardCheckpointer()  # AsyncCheckpointer subclass
    _async_ckptr.save(path, state, force=force)
    return path


def wait_for_async_saves() -> None:
    """Block until every :func:`save_sharded_async` write committed."""
    if _async_ckptr is not None:
        _async_ckptr.wait_until_finished()


def restore_sharded(path: str, template: Any) -> Any:
    """Restore into the shardings carried by ``template``.

    ``template`` is a pytree of jax.Arrays or jax.ShapeDtypeStruct with
    `.sharding` set — pass arrays laid out for the NEW topology to
    reshard an old checkpoint on load."""
    import orbax.checkpoint as ocp

    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
        template,
    )
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(cloudfs.normalize(path), abstract)


def _replicated_scalar(value: int, like_tree: Any):
    """A step counter as a globally-replicated array on the same mesh as
    ``like_tree``'s arrays — a process-local scalar would be rejected by
    multi-host serialization ('fully addressable arrays' error)."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    leaf = next(
        (l for l in jax.tree.leaves(like_tree)
         if isinstance(getattr(l, "sharding", None), NamedSharding)),
        None,
    )
    arr = jnp.asarray(value)
    if leaf is None:
        return arr
    rep = NamedSharding(leaf.sharding.mesh, PartitionSpec())
    return jax.device_put(arr, rep)


def save_train_state(path: str, params: Any, opt_state: Any, step: int = 0) -> str:
    """Convenience: one checkpoint holding {params, opt_state, step}."""
    return save_sharded(
        path,
        {
            "params": params,
            "opt_state": opt_state,
            "step": _replicated_scalar(step, params),
        },
    )


def restore_train_state(path: str, params_template: Any, opt_state_template: Any):
    """Returns (params, opt_state, step) resharded onto the templates."""
    out = restore_sharded(
        path,
        {
            "params": params_template,
            "opt_state": opt_state_template,
            "step": _replicated_scalar(0, params_template),
        },
    )
    return out["params"], out["opt_state"], int(out["step"])
